"""Chain-shared search kernels: one compilation for the whole goal chain.

The per-goal kernels in ``search.py`` are jitted with (goal, optimized) as
STATIC arguments, so a G-goal chain compiles G move drivers and G swap
drivers, and the g-th kernel re-traces the aux + acceptance of all g-1
prior goals — compile work grows quadratically along the chain
(VERDICT round 1, "what's weak" #2).  This module recasts the chain as
THREE compilations total:

- ``chain_optimize_rounds``: the fused ``lax.while_loop`` move driver where
  the ACTIVE goal is a traced index (``lax.switch`` over per-goal scoring
  branches) and the previously-optimized set is a traced boolean mask
  gating each goal's acceptance term.  Every goal's acceptance is traced
  ONCE; per-goal aux tensors are wrapped in ``lax.cond`` so only the active
  + prior goals' aux is actually computed at runtime.
- ``chain_swap_rounds``: same treatment for the swap phase.
- ``chain_goal_stats``: post-optimization violation/objective readback.

Host drives the chain with the SAME compiled kernels for every goal:
``optimize_goal_in_chain(state, chain, i, ...)``.

Reference semantics preserved: the lexicographic acceptance stack of
AbstractGoal.maybeApplyBalancingAction:230-272 (each candidate must be
accepted by every previously-optimized goal), SURVEY.md §A.3.
"""

from __future__ import annotations

import dataclasses
import time as _time
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..model.tensors import ClusterTensors, offline_replicas
from .agg import (
    AggCarry, apply_deltas_to_agg, compute_agg, maybe_refresh, pot_lbi_deltas,
)
from .candidates import compute_deltas, generate_candidates, select_sources
from .fill import targets_enabled
from .constraint import BalancingConstraint
from .derived import compute_derived
from .goals.base import Goal
from .search import (
    _EPS_IMPROVEMENT, _OFFLINE_BONUS, ExclusionMasks,
    OptimizationFailureError, SearchConfig, apply_selected,
    apply_swap_selection, cumulative_select, goal_aux, reduce_per_source,
    run_carry_loop, swap_grid,
)
from ..utils.flight_recorder import NO_FLIGHT, STAT_WIDTH as _FLIGHT_STATS


def _gated_aux(needed: jax.Array, goal: Goal, state, derived, constraint,
               num_topics: int, psum=None, agg=None):
    """Compute ``goal``'s aux pytree only when ``needed`` (traced bool) —
    zeros otherwise. Keeps the single chain kernel from paying every goal's
    O(P) aux reductions on every round. ``psum`` combines partition-additive
    aux partials across a mesh (the collective runs in BOTH branches — a
    ``lax.cond`` whose branches disagree on collectives would deadlock, and
    psum of the zero pytree is free). With an ``agg`` carry, agg-backed
    goals read their (already-global) partial from it — collective-free, so
    the whole aux is safely gated even under a mesh."""
    if agg is not None and goal.partial_from_agg(agg) is not None:
        def compute_from_agg(_):
            return goal.finalize_aux(goal.partial_from_agg(agg), state,
                                     derived, constraint)

        shapes = jax.eval_shape(compute_from_agg, 0)
        if not jax.tree_util.tree_leaves(shapes):
            return compute_from_agg(0)

        def zeros_from_agg(_):
            return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

        return jax.lax.cond(needed, compute_from_agg, zeros_from_agg, 0)

    def compute(_):
        return goal_aux(goal, state, derived, constraint, num_topics, psum)

    shapes = jax.eval_shape(compute, 0)
    if not jax.tree_util.tree_leaves(shapes):
        return compute(0)  # aux is None/empty: nothing to gate

    def zeros(_):
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    if psum is None:
        return jax.lax.cond(needed, compute, zeros, 0)
    # Under a mesh the psum must execute unconditionally on every device
    # (a cond whose branches disagree on collectives would mismatch), but
    # the O(P) LOCAL partial is still gated: lax.cond around
    # prepare_partial (collective-free), psum of the (possibly zero)
    # result outside.
    partial_aux = goal.prepare_partial(state, num_topics)
    if partial_aux is not None:
        def compute_partial(_):
            return goal.prepare_partial(state, num_topics)

        def zero_partial(_):
            return jax.tree.map(jnp.zeros_like, partial_aux)

        partial_aux = jax.lax.cond(needed, compute_partial, zero_partial, 0)
        partial_aux = jax.tree.map(psum, partial_aux)
    return goal.finalize_aux(partial_aux, state, derived, constraint)


def excluded_hosting_replicas(state: ClusterTensors,
                              excluded_replica_move_brokers: jax.Array,
                              ) -> jax.Array:
    """[P, S] bool: replica sits on an ALIVE excluded-for-replica-move
    broker. Any() of this is "drain pending" — goals must keep running to
    shed replicas off excluded brokers even with zero violations
    (requireLessLoad includes excluded brokers,
    ResourceDistributionGoal.java:387). Shared by the fused and
    bounded-dispatch drivers on both the single-device and sharded paths
    so their per-goal fast-path skip conditions cannot diverge."""
    from ..model.tensors import alive_mask
    excl_alive = excluded_replica_move_brokers & alive_mask(state)
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b)
    return jnp.concatenate([excl_alive, jnp.array([False])])[seg]


def _goal_flags(goals: tuple[Goal, ...]):
    lead_only = jnp.asarray([g.leadership_only for g in goals])
    incl_lead = jnp.asarray([g.include_leadership or g.leadership_only
                             for g in goals])
    indep = jnp.asarray([g.independent_per_broker for g in goals])
    return lead_only, incl_lead, indep


def _switch_goal_fn(active_idx, goals, fn):
    """``lax.switch`` over the goal index: run ``fn(goal, i)`` for the
    ACTIVE goal only (all branches traced once, one executed). The shared
    scaffolding for every per-goal dispatch in the chain kernels."""
    def branch(i):
        def run(_):
            return fn(goals[i], i)
        return run

    return jax.lax.switch(active_idx, [branch(i) for i in range(len(goals))], 0)


def _switch_scores(active_idx, goals, aux_list, state, derived, constraint):
    """(src_score[B], dst_score[B], weight[P,S]) of the active goal."""
    return _switch_goal_fn(
        active_idx, goals,
        lambda g, i: (g.source_score(state, derived, constraint, aux_list[i])
                      .astype(jnp.float32),
                      g.dest_score(state, derived, constraint, aux_list[i])
                      .astype(jnp.float32),
                      g.replica_weight(state, derived, constraint,
                                       aux_list[i]).astype(jnp.float32)))


def _switch_swap_dest_score(active_idx, goals, aux_list, state, derived,
                            constraint):
    """[B] swap counterparty score of the active goal (shared by the
    single-device and sharded swap bodies)."""
    return _switch_goal_fn(
        active_idx, goals,
        lambda g, i: g.swap_dest_score(state, derived, constraint,
                                       aux_list[i]).astype(jnp.float32))


def _switch_target_dests(active_idx, goals, aux_list, state, derived,
                         constraint, cand_p, cand_s, src_valid,
                         rank_stride: int = 1, rank_offset=0):
    """The active goal's targeted-destination column (Goal.target_dests,
    analyzer.fill) — goals without a rule contribute an all-invalid
    column so every branch returns the same shapes. ``rank_stride``/
    ``rank_offset`` interleave per-device fill positions on a mesh (see
    Goal.target_dests)."""

    def branch(i):
        g = goals[i]

        def fn(_):
            td = g.target_dests(state, derived, constraint, aux_list[i],
                                cand_p, cand_s, src_valid,
                                rank_stride=rank_stride,
                                rank_offset=rank_offset)
            if td is None:
                return (jnp.zeros_like(cand_p),
                        jnp.zeros(cand_p.shape, dtype=bool))
            return td[0].astype(jnp.int32), td[1]
        return fn

    return jax.lax.switch(active_idx, [branch(i) for i in range(len(goals))], 0)


def _chain_round_body(state: ClusterTensors, agg: "AggCarry | None",
                      active_idx: jax.Array,
                      prior_mask: jax.Array, goals: tuple[Goal, ...],
                      constraint: BalancingConstraint, cfg: SearchConfig,
                      num_topics: int, masks: ExclusionMasks,
                      collect: bool = False,
                      ) -> tuple[ClusterTensors, "AggCarry | None",
                                 jax.Array, "jax.Array | None"]:
    """One search round, chain-parameterized (traced body). ``agg`` is the
    incrementally-maintained aggregate carry (analyzer.agg): the round reads
    its per-broker aggregates from it instead of O(P·S) segment-sums and
    returns it updated by the applied batch (None = recompute-per-round,
    kept for the oracle paths).

    ``collect`` (trace-time) additionally returns a ``[STAT_WIDTH]`` f32
    flight-stats row for this round (utils.flight_recorder.STAT_COLUMNS:
    applied / valid / accepted / positive / winners / active-goal
    violation) — pure REDUCTIONS over tensors the round already computes
    (the duplicated ``reduce_per_source`` is structurally identical to
    the one inside ``cumulative_select``, so XLA CSE collapses the two),
    never a new selection input: the trajectory is byte-identical with
    collection on or off (pinned in tests/test_flight_recorder.py)."""
    lead_only_f, incl_lead_f, indep_f = _goal_flags(goals)
    is_lead_only = lead_only_f[active_idx]
    has_leadership = incl_lead_f[active_idx]

    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, agg=agg)
    is_active = jnp.arange(len(goals)) == active_idx
    aux_list = [_gated_aux(prior_mask[i] | is_active[i], g, state, derived,
                           constraint, num_topics, agg=agg)
                for i, g in enumerate(goals)]

    src_score, dst_score, weight = _switch_scores(
        active_idx, goals, aux_list, state, derived, constraint)

    # Self-healing priority (see search.score_round_candidates): offline
    # replicas are always sources with maximal weight for non-leadership
    # goals.
    off = offline_replicas(state)  # [P, S]
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    offline_per_broker = jax.ops.segment_sum(
        off.astype(jnp.float32).reshape(-1), seg, num_segments=b + 1)[:b]
    src_score = src_score + jnp.where(is_lead_only, 0.0, offline_per_broker)
    weight = jnp.where(off & ~is_lead_only, 1e30, weight)

    # UNIFORM grid layout: both the move and the leadership block always
    # exist (static shapes shared by every goal); the active goal's traced
    # flags mask out the block it doesn't use. The targeted-destination
    # column (Goal.target_dests) rides the move block; select_sources here
    # duplicates generate_candidates' internal selection structurally, so
    # XLA CSE collapses the two.
    extra = None
    if targets_enabled(state.num_partitions):
        cand_p, cand_s, src_valid = select_sources(state, src_score, weight,
                                                   cfg.num_sources)
        # Targets pause while ANY offline replica exists (traced scalar):
        # targeted steering during a drain locks in placements later
        # goals cannot repair (1k drain-50: balancedness 86.0 -> 82.74
        # with CpuUsage violated). Self-healing and the drain's rebalance
        # keep the r4 full-grid semantics; targets resume once healing
        # completes.
        t_dst, t_ok = _switch_target_dests(active_idx, goals, aux_list,
                                           state, derived, constraint,
                                           cand_p, cand_s, src_valid)
        extra = (t_dst, t_ok & ~off.any())
    cand, layout = generate_candidates(state, derived, src_score, dst_score,
                                       weight, cfg.num_sources, cfg.num_dests,
                                       include_leadership=True,
                                       leadership_only=False,
                                       extra_dst=extra)
    (r0, c0), (r1, c1) = layout
    block_ok = jnp.concatenate([
        jnp.broadcast_to(~is_lead_only, (r0 * c0,)),
        jnp.broadcast_to(has_leadership, (r1 * c1,)),
    ])
    cand = dataclasses.replace(cand, valid=cand.valid & block_ok)
    deltas = compute_deltas(state, derived, cand)

    accept = deltas.valid
    for i, g in enumerate(goals):
        accept &= (~prior_mask[i]) | g.acceptance(state, derived, constraint,
                                                  aux_list[i], deltas)

    moving_offline = off[deltas.partition, deltas.src_slot] \
        & (deltas.replica_delta > 0)

    def imp_branch(i):
        g = goals[i]

        def fn(_):
            return g.improvement(state, derived, constraint, aux_list[i],
                                 deltas).astype(jnp.float32)
        return fn

    imp = jax.lax.switch(active_idx,
                         [imp_branch(i) for i in range(len(goals))], 0)
    imp = jnp.where(moving_offline & jnp.isfinite(imp) & deltas.valid,
                    jnp.maximum(imp, 0.0) + _OFFLINE_BONUS, imp)
    score = jnp.where(accept, imp, -jnp.inf)

    independent = indep_f[active_idx] & ~prior_mask.any()
    m = max(cfg.moves_per_round, cfg.num_sources)
    is_active_f = is_active

    def recheck(sub, has_earlier):
        """Joint acceptance with cumulative pre-deltas (cumulative_select):
        prior goals gated by the traced prior mask; the ACTIVE goal guards
        its own band for interacting candidates."""
        a = jnp.ones(sub.valid.shape[0], dtype=bool)
        for i, g in enumerate(goals):
            g_acc = g.acceptance(state, derived, constraint, aux_list[i], sub)
            a &= (~prior_mask[i]) | g_acc
            a &= (~is_active_f[i]) | (~has_earlier) | g_acc
        return a

    top_idx, sel, sub, pot_d, lbi_d = cumulative_select(
        state, deltas, score, layout, m, cfg.moves_per_round, independent,
        recheck, extra_last_col=targets_enabled(state.num_partitions))
    if agg is not None:
        agg = apply_deltas_to_agg(agg, sub, sel, pot_d, lbi_d)
    new_state = apply_selected(
        state, sel, deltas.partition[top_idx], deltas.src_slot[top_idx],
        deltas.dst_broker[top_idx], cand.kind[top_idx], cand.dst_slot[top_idx])
    applied = sel.sum()
    stat = None
    if collect:
        red_idx = reduce_per_source(
            score, layout, extra_last_col=targets_enabled(
                state.num_partitions))
        viol = _switch_goal_fn(
            active_idx, goals,
            lambda g, i: g.broker_violations(
                state, derived, constraint, aux_list[i]).sum()
            .astype(jnp.float32))
        stat = jnp.stack([
            applied.astype(jnp.float32),
            deltas.valid.sum().astype(jnp.float32),
            accept.sum().astype(jnp.float32),
            (score > _EPS_IMPROVEMENT).sum().astype(jnp.float32),
            (score[red_idx] > _EPS_IMPROVEMENT).sum().astype(jnp.float32),
            viol,
        ])
        assert stat.shape == (_FLIGHT_STATS,)
    return new_state, agg, applied, stat


def _chain_rounds_driver(state: ClusterTensors, active_idx: jax.Array,
                         prior_mask: jax.Array, goals: tuple[Goal, ...],
                         constraint: BalancingConstraint, cfg: SearchConfig,
                         num_topics: int, masks: ExclusionMasks,
                         budget: jax.Array | None = None,
                         ring_rounds: int = 0,
                         ) -> tuple[ClusterTensors, jax.Array, jax.Array,
                                    "jax.Array | None"]:
    """Traced body of the fused move driver — the MEGASTEP: up to
    ``budget`` round-bodies under one ``lax.while_loop`` whose carry is
    ``((state, agg), moves, rounds, last_applied)`` with ``last_applied``
    as the on-device early-exit flag (a zero-apply round freezes the state
    and ends the loop — no host involvement). Shared by the plain and the
    donated jits below.

    ``ring_rounds`` > 0 (trace-time, the flight recorder's knob) adds a
    ``[ring_rounds, STAT_WIDTH]`` f32 ring to the carry: each round
    writes its flight-stats row at ``round % ring_rounds``, and the ring
    rides the dispatch's existing async readback (one more output
    tensor, ~3 KB at the default length — no extra host round-trip).
    Returns (final_state, total_moves, rounds_run, ring-or-None)."""
    collect = ring_rounds > 0

    def body(carry, rounds_done):
        if collect:
            s, a, ring = carry
        else:
            s, a = carry
        a = maybe_refresh(a, s, num_topics, rounds_done)
        ns, na, applied, stat = _chain_round_body(
            s, a, active_idx, prior_mask, goals, constraint, cfg,
            num_topics, masks, collect=collect)
        if collect:
            ring = ring.at[rounds_done % ring_rounds].set(stat)
            return (ns, na, ring), applied
        return (ns, na), applied

    carry0 = (state, compute_agg(state, num_topics))
    if collect:
        carry0 = carry0 + (jnp.zeros((ring_rounds, _FLIGHT_STATS),
                                     jnp.float32),)
    final_carry, total, rounds = run_carry_loop(
        body, carry0, cfg.max_rounds, budget=budget)
    if collect:
        final, _agg, ring = final_carry
        return final, total, rounds, ring
    final, _agg = final_carry
    return final, total, rounds, None


@partial(jax.jit, static_argnames=("goals", "constraint", "cfg", "num_topics",
                                   "ring_rounds"))
def chain_optimize_rounds(state: ClusterTensors, active_idx: jax.Array,
                          prior_mask: jax.Array, goals: tuple[Goal, ...],
                          constraint: BalancingConstraint, cfg: SearchConfig,
                          num_topics: int, masks: ExclusionMasks,
                          budget: jax.Array | None = None,
                          ring_rounds: int = 0):
    """Fused multi-round driver for ANY goal in the chain: one compilation
    serves all G (active_idx, prior_mask) combinations. Returns
    (final_state, total_moves, rounds_run). ``budget`` (traced) further
    caps rounds without recompiling (bounded-dispatch path).

    ``ring_rounds`` > 0 (static — the flight recorder's ON switch, one
    extra compilation per process when enabled) appends the per-round
    flight-stats ring as a FOURTH output; 0 keeps the 3-tuple contract.

    Aggregates are computed once at entry and maintained incrementally
    through the loop (analyzer.agg), with a periodic fresh recompute to
    bound f32 drift."""
    final, total, rounds, ring = _chain_rounds_driver(
        state, active_idx, prior_mask, goals, constraint, cfg, num_topics,
        masks, budget, ring_rounds=ring_rounds)
    if ring_rounds > 0:
        return final, total, rounds, ring
    return final, total, rounds


def strip_mutable(state: ClusterTensors) -> ClusterTensors:
    """The read-only remainder of a split state: ``assignment`` and
    ``leader_slot`` replaced by 0-row placeholders. The donated megastep
    kernels take the two mutable tensors as SEPARATE donated arguments —
    donating the whole pytree would also consume the topology tensors
    (topic/rack/capacity/...), which the incremental model pipeline
    (model/refresh.py) shares across generations from its topology cache;
    a donated shared buffer is deleted under the cache's feet."""
    s = state.max_replication_factor
    return dataclasses.replace(
        state,
        assignment=jnp.zeros((0, s), state.assignment.dtype),
        leader_slot=jnp.zeros((0,), state.leader_slot.dtype))


@partial(jax.jit, static_argnames=("goals", "constraint", "cfg",
                                   "num_topics", "ring_rounds"),
         donate_argnums=(0, 1))
def chain_optimize_rounds_donated(assignment: jax.Array,
                                  leader_slot: jax.Array,
                                  rest: ClusterTensors,
                                  active_idx: jax.Array,
                                  prior_mask: jax.Array,
                                  goals: tuple[Goal, ...],
                                  constraint: BalancingConstraint,
                                  cfg: SearchConfig, num_topics: int,
                                  masks: ExclusionMasks, budget: jax.Array,
                                  ring_rounds: int = 0):
    """The donated megastep: identical trace to ``chain_optimize_rounds``
    with the two mutable tensors donated, so XLA writes the new assignment
    into the old buffers instead of allocating a fresh generation per
    dispatch. Callers pass ``strip_mutable(state)`` as ``rest`` and must
    not touch the donated arrays afterwards. Returns (assignment,
    leader_slot, moves, rounds) — plus the flight-stats ring when
    ``ring_rounds`` > 0 (chain_optimize_rounds; the ring is loop-created,
    never part of the donation set)."""
    state = dataclasses.replace(rest, assignment=assignment,
                                leader_slot=leader_slot)
    final, total, rounds, ring = _chain_rounds_driver(
        state, active_idx, prior_mask, goals, constraint, cfg, num_topics,
        masks, budget, ring_rounds=ring_rounds)
    if ring_rounds > 0:
        return final.assignment, final.leader_slot, total, rounds, ring
    return final.assignment, final.leader_slot, total, rounds


def _chain_swap_body(state: ClusterTensors, agg: "AggCarry | None",
                     active_idx: jax.Array,
                     prior_mask: jax.Array, goals: tuple[Goal, ...],
                     constraint: BalancingConstraint, num_topics: int,
                     masks: ExclusionMasks, moves: int = 8,
                     ) -> tuple[ClusterTensors, "AggCarry | None", jax.Array]:
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, agg=agg)
    is_active = jnp.arange(len(goals)) == active_idx
    aux_list = [_gated_aux(prior_mask[i] | is_active[i], g, state, derived,
                           constraint, num_topics, agg=agg)
                for i, g in enumerate(goals)]
    src_score, _dst_score, weight = _switch_scores(
        active_idx, goals, aux_list, state, derived, constraint)
    dst_score = _switch_swap_dest_score(active_idx, goals, aux_list, state,
                                        derived, constraint)

    fwd, rev, net, p1, s1, p2, s2, src_b, dst_b, base_valid = swap_grid(
        state, derived, src_score, dst_score, weight)

    accept = base_valid
    for i, g in enumerate(goals):
        accept &= (~prior_mask[i]) | g.swap_acceptance(
            state, derived, constraint, aux_list[i], fwd, rev, net)

    def imp_branch(i):
        g = goals[i]

        def fn(_):
            return g.swap_improvement(state, derived, constraint,
                                      aux_list[i], fwd, rev,
                                      net).astype(jnp.float32)
        return fn

    imp = jax.lax.switch(active_idx,
                         [imp_branch(i) for i in range(len(goals))], 0)
    score = jnp.where(accept, imp, -jnp.inf)
    new_state, applied, top_idx, sel = apply_swap_selection(
        state, score, p1, s1, p2, s2, src_b, dst_b, moves)
    if agg is not None:
        # Both directional legs of every accepted swap scatter onto the
        # carry (replica + load + leadership travel per leg).
        for leg in (fwd, rev):
            leg_sub = jax.tree.map(lambda a: a[top_idx], leg)
            pot_d, lbi_d = pot_lbi_deltas(state, leg_sub)
            agg = apply_deltas_to_agg(agg, leg_sub, sel, pot_d, lbi_d)
    return new_state, agg, applied


def _chain_swap_driver(state: ClusterTensors, active_idx: jax.Array,
                       prior_mask: jax.Array, goals: tuple[Goal, ...],
                       constraint: BalancingConstraint, num_topics: int,
                       masks: ExclusionMasks, moves: int = 8,
                       max_rounds: int = 64,
                       budget: jax.Array | None = None,
                       ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    def body(carry, rounds_done):
        s, a = carry
        a = maybe_refresh(a, s, num_topics, rounds_done)
        ns, na, applied = _chain_swap_body(s, a, active_idx, prior_mask,
                                           goals, constraint, num_topics,
                                           masks, moves)
        return (ns, na), applied

    (final, _agg), total, rounds = run_carry_loop(
        body, (state, compute_agg(state, num_topics)), max_rounds,
        budget=budget)
    return final, total, rounds


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics",
                                   "moves", "max_rounds"))
def chain_swap_rounds(state: ClusterTensors, active_idx: jax.Array,
                      prior_mask: jax.Array, goals: tuple[Goal, ...],
                      constraint: BalancingConstraint, num_topics: int,
                      masks: ExclusionMasks, moves: int = 8,
                      max_rounds: int = 64,
                      budget: jax.Array | None = None,
                      ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """Fused swap-phase driver, chain-parameterized (incremental-aggregate
    carry, as chain_optimize_rounds)."""
    return _chain_swap_driver(state, active_idx, prior_mask, goals,
                              constraint, num_topics, masks, moves,
                              max_rounds, budget)


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics",
                                   "moves", "max_rounds"),
         donate_argnums=(0, 1))
def chain_swap_rounds_donated(assignment: jax.Array, leader_slot: jax.Array,
                              rest: ClusterTensors, active_idx: jax.Array,
                              prior_mask: jax.Array, goals: tuple[Goal, ...],
                              constraint: BalancingConstraint,
                              num_topics: int, masks: ExclusionMasks,
                              moves: int, max_rounds: int,
                              budget: jax.Array,
                              ) -> tuple[jax.Array, jax.Array, jax.Array,
                                         jax.Array]:
    """Donated swap megastep (see chain_optimize_rounds_donated)."""
    state = dataclasses.replace(rest, assignment=assignment,
                                leader_slot=leader_slot)
    final, total, rounds = _chain_swap_driver(
        state, active_idx, prior_mask, goals, constraint, num_topics, masks,
        moves, max_rounds, budget)
    return final.assignment, final.leader_slot, total, rounds


def _chain_goal_stats_body(state: ClusterTensors, active_idx: jax.Array,
                           goals: tuple[Goal, ...],
                           constraint: BalancingConstraint, num_topics: int,
                           masks: ExclusionMasks,
                           ) -> tuple[jax.Array, jax.Array, jax.Array]:
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)

    def branch(i):
        g = goals[i]

        def fn(_):
            aux = goal_aux(g, state, derived, constraint, num_topics)
            viol = g.broker_violations(state, derived, constraint, aux)
            obj = g.objective(state, derived, constraint, aux)
            return (viol.sum().astype(jnp.float32),
                    obj.astype(jnp.float32))
        return fn

    viol, obj = jax.lax.switch(active_idx,
                               [branch(i) for i in range(len(goals))], 0)
    return viol, obj, offline_replicas(state).sum()


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics"))
def chain_goal_stats(state: ClusterTensors, active_idx: jax.Array,
                     goals: tuple[Goal, ...],
                     constraint: BalancingConstraint, num_topics: int,
                     masks: ExclusionMasks,
                     ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(total_violation, objective, offline_remaining) of the active goal on
    ``state`` — the post-optimization readback, on device in one call."""
    return _chain_goal_stats_body(state, active_idx, goals, constraint,
                                  num_topics, masks)


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics"))
def chain_all_violations(state: ClusterTensors, goals: tuple[Goal, ...],
                         constraint: BalancingConstraint, num_topics: int,
                         masks: ExclusionMasks) -> jax.Array:
    """[G] total violation per goal on ``state`` in ONE device call — the
    pre-optimization violation snapshot (derived state shared across
    goals)."""
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    totals = []
    for g in goals:
        aux = goal_aux(g, state, derived, constraint, num_topics)
        totals.append(g.broker_violations(state, derived, constraint,
                                          aux).sum().astype(jnp.float32))
    return jnp.stack(totals)


def _chain_all_goal_stats_body(state: ClusterTensors,
                               goals: tuple[Goal, ...],
                               constraint: BalancingConstraint,
                               num_topics: int, masks: ExclusionMasks,
                               ) -> tuple[jax.Array, jax.Array, jax.Array]:
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    viols, objs = [], []
    for g in goals:
        aux = goal_aux(g, state, derived, constraint, num_topics)
        viols.append(g.broker_violations(state, derived, constraint,
                                         aux).sum().astype(jnp.float32))
        objs.append(g.objective(state, derived, constraint,
                                aux).astype(jnp.float32))
    return (jnp.stack(viols), jnp.stack(objs),
            offline_replicas(state).sum())


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics"))
def chain_all_goal_stats(state: ClusterTensors, goals: tuple[Goal, ...],
                         constraint: BalancingConstraint, num_topics: int,
                         masks: ExclusionMasks,
                         ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """([G] violation, [G] objective, offline) for EVERY goal on ``state``
    in ONE device call — the fingerprint-skip snapshot (round 18). The
    per-goal entry stats dispatches of the bounded path collapse into this
    one program: a goal whose snapshot shows zero violation (with zero
    offline replicas and no drain pending) applies nothing, so its
    move/swap dispatches — and its own entry/exit stats dispatches — can
    be skipped byte-identically, as long as no earlier goal has mutated
    the state since the snapshot (the hint-validity contract enforced by
    the optimizer's ``chain_owns_state`` gate)."""
    return _chain_all_goal_stats_body(state, goals, constraint, num_topics,
                                      masks)


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics"))
def megabatch_all_goal_stats(states: ClusterTensors,
                             goals: tuple[Goal, ...],
                             constraint: BalancingConstraint,
                             num_topics: int, masks: ExclusionMasks,
                             ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Batched fingerprint-skip snapshot: ([C, G] violation, [C, G]
    objective, [C] offline) for every goal of every cluster in ONE device
    call (the ``chain_all_goal_stats`` twin on the megabatch cluster
    axis)."""
    mask_fields, mask_ax = _mask_axes(masks)

    def per_cluster(s, tm, rm, lm):
        return _chain_all_goal_stats_body(s, goals, constraint, num_topics,
                                          ExclusionMasks(tm, rm, lm))

    return jax.vmap(per_cluster, in_axes=(0,) + mask_ax)(states,
                                                         *mask_fields)


@partial(jax.jit, static_argnames=("goals", "constraint", "cfg", "num_topics",
                                   "swap_moves", "swap_max_rounds"))
def chain_optimize_full(state: ClusterTensors, goals: tuple[Goal, ...],
                        constraint: BalancingConstraint, cfg: SearchConfig,
                        num_topics: int, masks: ExclusionMasks,
                        swap_moves: int = 8, swap_max_rounds: int = 64):
    """The ENTIRE goal chain in ONE dispatch: ``lax.scan`` over the goal
    index runs each goal's fused move/swap drivers under the acceptance of
    all prior goals, collecting per-goal entry/exit stats on device.

    This is the production solver path. The per-goal kernels above cost
    ~4-6 host↔device round-trips per goal (stats, move driver, swap driver,
    stats again) — a fixed ~0.5 s/goal floor over a high-latency device
    link regardless of scale. Here the host dispatches once and reads back
    one stacked stats pytree for the whole chain.

    Per-goal fast path: a goal whose violations AND offline-replica count
    are zero on entry is skipped entirely (``lax.cond``), unless an alive
    excluded-for-replica-move broker still hosts replicas (the drain
    story). This matches the search's own fixed point — zero violations
    means either no broker has ``source_score > 0`` (goals tie sources to
    violations) or no candidate scores a positive improvement (goals whose
    improvement is the pairwise violation delta, e.g. preferred-leader) —
    and mirrors the reference, whose greedy only acts on brokers outside
    the goal's band (AbstractGoal.java:82-135).

    Returns (final_state, per_goal_stats) where per_goal_stats is a dict of
    [G]-arrays: viol_before/after, obj_before/after, offline_before,
    moves, swaps, rounds.
    """
    g_count = len(goals)
    supports_swap = jnp.asarray([g.supports_swap for g in goals])

    def drain_pending(s: ClusterTensors) -> jax.Array:
        """True while any ALIVE excluded-for-replica-move broker still
        hosts replicas — the per-goal fast path must stay off during a
        drain (see excluded_hosting_replicas)."""
        if masks.excluded_replica_move_brokers is None:
            return jnp.bool_(False)
        return excluded_hosting_replicas(
            s, masks.excluded_replica_move_brokers).any()

    def per_goal(carry_state, g):
        prior = jnp.arange(g_count) < g
        viol0, obj0, offline0 = _chain_goal_stats_body(
            carry_state, g, goals, constraint, num_topics, masks)

        def run(s):
            # Interleave the fused move driver with the fused swap driver
            # until a swap pass applies nothing (the host loop of
            # optimize_goal_in_chain, on device). The aggregate carry is
            # computed once per goal and threaded through both phases.
            def outer_cond(c):
                _s, _a, _m, _sw, rounds, last_swapped, first = c
                return (first | (last_swapped > 0)) & (rounds < cfg.max_rounds)

            def outer_body(c):
                s, a, m_tot, sw_tot, rounds, _ls, _first = c

                # The refresh cadence must count ROUNDS SINCE THE LAST FULL
                # RECOMPUTE, which spans move/swap segments — each inner
                # loop's private counter restarts at 0, so it is offset by
                # the goal's cumulative round count (else a pass of many
                # short segments would never refresh).
                def move_body(carry, rounds_done):
                    st, ag = carry
                    ag = maybe_refresh(ag, st, num_topics,
                                       rounds + rounds_done)
                    ns, nag, applied, _stat = _chain_round_body(
                        st, ag, g, prior, goals, constraint, cfg, num_topics,
                        masks)
                    return (ns, nag), applied

                (s, a), m, r = run_carry_loop(move_body, (s, a),
                                              cfg.max_rounds)

                def do_swap(st_ag):
                    def swap_body(carry, rounds_done):
                        st, ag = carry
                        ag = maybe_refresh(ag, st, num_topics,
                                           rounds + r + rounds_done)
                        ns, nag, applied = _chain_swap_body(
                            st, ag, g, prior, goals, constraint, num_topics,
                            masks, swap_moves)
                        return (ns, nag), applied

                    (st, ag), sw, sr = run_carry_loop(swap_body, st_ag,
                                                      swap_max_rounds)
                    return st, ag, sw, sr

                def no_swap(st_ag):
                    st, ag = st_ag
                    return st, ag, jnp.int32(0), jnp.int32(0)

                s, a, sw, sr = jax.lax.cond(supports_swap[g], do_swap,
                                            no_swap, (s, a))
                return (s, a, m_tot + m, sw_tot + sw, rounds + r + sr, sw,
                        jnp.bool_(False))

            s, a, m, sw, rounds, _, _ = jax.lax.while_loop(
                outer_cond, outer_body,
                (s, compute_agg(s, num_topics), jnp.int32(0), jnp.int32(0),
                 jnp.int32(0), jnp.int32(0), jnp.bool_(True)))
            return s, m, sw, rounds

        def skip(s):
            return s, jnp.int32(0), jnp.int32(0), jnp.int32(0)

        new_state, moves, swaps, rounds = jax.lax.cond(
            (viol0 > 0) | (offline0 > 0) | drain_pending(carry_state),
            run, skip, carry_state)
        viol1, obj1, offline1 = _chain_goal_stats_body(
            new_state, g, goals, constraint, num_topics, masks)
        ys = {"viol_before": viol0, "obj_before": obj0,
              "offline_before": offline0, "viol_after": viol1,
              "obj_after": obj1, "offline_after": offline1,
              "moves": moves, "swaps": swaps, "rounds": rounds}
        return new_state, ys

    final_state, stats = jax.lax.scan(
        per_goal, state, jnp.arange(g_count, dtype=jnp.int32))
    return final_state, stats


def optimize_chain(state: ClusterTensors, chain: Sequence[Goal],
                   constraint: BalancingConstraint, cfg: SearchConfig,
                   num_topics: int, masks: ExclusionMasks | None = None,
                   ) -> tuple[ClusterTensors, list[dict]]:
    """Run the whole goal chain with the single-dispatch fused kernel and
    return (final_state, [per-goal info dict in chain order]).

    Same semantics, error behavior, and info-dict shape as calling
    ``optimize_goal_in_chain`` for each goal in order (the stats-regression
    guard of AbstractGoal.java:111-119 and the hard-goal failure of
    Goal.java:53-59 are checked per goal, in chain order, from the stacked
    on-device stats), at a fraction of the host↔device round-trips.
    """
    masks = masks or ExclusionMasks()
    goals = tuple(chain)
    if not goals:
        return state, []
    state, stats = chain_optimize_full(state, goals, constraint, cfg,
                                       num_topics, masks)
    stats = {k: jax.device_get(v) for k, v in stats.items()}
    return state, _chain_infos_from_stats(goals, stats)


def _chain_infos_from_stats(goals: tuple[Goal, ...], stats: dict,
                            ) -> list[dict]:
    """Per-goal info dicts from the stacked on-device chain stats; raises
    the per-goal errors in chain order (shared by the single-device and
    sharded whole-chain kernels).

    The ``float()``/``int()`` decodes below are the INTENTIONAL readback
    of the whole-chain stats: the device sync was paid by one
    ``device_get`` upstream (optimize_chain), so each line unpacks host
    numpy scalars — annotated so CCSA001 documents, not just polices,
    the async contract."""
    infos: list[dict] = []
    for i, goal in enumerate(goals):
        # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
        obj0, obj1 = float(stats["obj_before"][i]), float(stats["obj_after"][i])
        # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
        if int(stats["offline_before"][i]) == 0:
            if obj1 > obj0 + 1e-4 * max(1.0, abs(obj0)):
                raise StatsRegressionError(
                    f"goal {goal.name} regressed its own objective during "
                    f"its optimization: {obj0:.6g} -> {obj1:.6g}")
        # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
        total_violation = float(stats["viol_after"][i])
        succeeded = total_violation <= 1e-6
        # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
        rounds = int(stats["rounds"][i])
        if goal.is_hard and not succeeded:
            raise OptimizationFailureError(
                f"hard goal {goal.name} unsatisfied: residual violation "
                f"{total_violation:.4f} after {rounds} rounds")
        # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
        swaps = int(stats["swaps"][i])
        infos.append({
            "goal": goal.name,
            "rounds": rounds,
            # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
            "moves_applied": int(stats["moves"][i]) + swaps,
            "swaps_applied": swaps,
            "residual_violation": total_violation,
            "succeeded": succeeded,
            "objective": obj1,
            # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
            "violation_before": float(stats["viol_before"][i]),
            # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
            "violated_on_entry": float(stats["viol_before"][i]) > 1e-6,
            # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
            "offline_before": int(stats["offline_before"][i]),
            # ccsa: ok[CCSA001] decode of already-fetched host stats scalars
            "offline_remaining": int(stats["offline_after"][i]),
        })
    return infos


class StatsRegressionError(RuntimeError):
    """A goal's own objective regressed during its own optimization — the
    self-check invariant of AbstractGoal.java:111-119 (the reference throws
    IllegalStateException when a goal's ClusterModelStatsComparator prefers
    the pre-optimization stats)."""


class AdaptiveDispatch:
    """Sizes bounded dispatches by wall-clock instead of a fixed round
    count. The per-dispatch ROUND budget is the watchdog mitigation's only
    knob, but what the watchdog actually bounds is seconds — and what the
    host pays per dispatch is link latency (the axon TPU tunnel adds a
    fixed RTT per execution, BENCH r3: ~0.5 s/goal floor and 100 s at 1k
    brokers from ~16-round dispatches). Growing the budget whenever a FULL
    dispatch finishes well under the target (and shrinking when it
    overshoots) amortizes the RTT while every dispatch stays bounded.

    The trajectory is dispatch-boundary-invariant (the budget is a traced
    cap on the same fixed-point loop), so adaptation never changes
    results — equivalence with the fused whole-chain kernel holds for any
    budget sequence. Shared across the goals of one optimization pass:
    per-round cost is a property of the cluster shape, not the goal."""

    MAX_ROUNDS = 1024

    def __init__(self, initial_rounds: int, target_s: float):
        self.k = max(1, initial_rounds)
        self._min = max(1, initial_rounds)
        self._target_s = target_s

    def budget(self, remaining: int) -> int:
        return min(self.k, remaining)

    def observe(self, rounds_run: int, budget: int, elapsed_s: float) -> None:
        if self._target_s <= 0 or rounds_run < budget:
            # Partial dispatch = pass fixed point reached; its duration
            # says nothing about a full budget's cost.
            return
        if elapsed_s > 2 * self._target_s:
            self.k = max(self._min, self.k // 2)
        elif elapsed_s < self._target_s / 2 and budget == self.k:
            # Grow ONLY on evidence from a full k-round dispatch — a tail
            # dispatch capped by the pass's remaining rounds also reports
            # rounds_run == budget, but its duration says nothing about
            # what k rounds would cost (doubling on it could overshoot
            # straight into execution-watchdog territory).
            self.k = min(self.k * 2, self.MAX_ROUNDS)


@dataclasses.dataclass(frozen=True)
class MegastepConfig:
    """Knobs of the bounded-dispatch megastep path (optimizer-owned; the
    chain drivers take it pre-resolved so tests can pin each switch).

    - ``donate``: request buffer donation for the mutable state tensors.
      The effective decision additionally requires a non-zero-copy backend
      (``donation_enabled``) — on CPU, ``device_put`` may alias host
      memory (model/refresh.py's snapshot rule), and a donated aliased
      buffer would let XLA scribble over the model cache.
    - ``async_readback``: enqueue dispatch N+1 before reading dispatch N's
      scalars (one-behind pipeline; AdaptiveDispatch then learns from the
      COMPLETED dispatch one step late — its documented staleness
      contract). Off = read-then-enqueue, the r9 behavior.
    - ``deficit_moves_cap``: > 0 sizes count-distribution goals'
      moves_per_round / num_sources from the measured total surplus
      (deficit_sized_config); 0 disables sizing entirely.
    - ``direct_assignment``: run the direct-assignment transport kernel
      (analyzer.direct) as a pre-pass for count-distribution goals whose
      chain prefix is guard-representable (direct_eligible): the bulk
      surplus→deficit matching lands in ONE dispatch, the greedy rounds
      only polish the structurally-blocked residue. The optimizer sets
      this from ``solver.direct.assignment.enabled`` AND the wide-regime
      gate (it replaces deficit-sized greedy; below the gate the greedy
      path is kept so the fused/bounded byte-parity pins hold).
    - ``direct_max_sweeps``: sweep budget of one direct dispatch
      (``solver.direct.max.sweeps``).
    - ``direct_sparse_margin``: fractional band-edge margin of the
      sparse-aware plan (``solver.direct.sparse.margin.frac``) — the
      shed/fill targets sit this fraction of the band width inside the
      edges; resolved per cell by deterministic randomized rounding.
    - ``direct_sparse_salt``: extra salt string folded (crc32, trace
      time) into the rounding seed (``solver.direct.sparse.rounding.salt``)
      so fleets can decorrelate rounding replays; "" keeps the module
      default seed.
    - ``direct_goals``: per-goal density-aware path CHOICE (ROADMAP 2d).
      ``None`` routes every direct-eligible goal through the transport
      kernel (today's behavior); a tuple restricts it to the NAMED goals,
      the rest taking the greedy arm even when eligible. The optimizer
      resolves this from replica density: at sparse geometry
      Replica/LeaderReplica are measurably faster under greedy while TR
      wins under direct+polish (the documented honest negative), so
      below ``solver.direct.density.sparse.threshold`` only TR keeps the
      direct arm.
    """

    donate: bool = True
    async_readback: bool = True
    deficit_moves_cap: int = 0
    direct_assignment: bool = False
    direct_max_sweeps: int = 16
    direct_sparse_margin: float = 0.25
    direct_sparse_salt: str = ""
    direct_goals: "tuple[str, ...] | None" = None


def direct_path_chosen(megastep: "MegastepConfig", goal_name: str) -> bool:
    """Whether the per-goal density-aware choice keeps the direct arm for
    this goal (None = all direct-eligible goals, the pre-choice
    behavior). The eligibility guard (``direct.direct_eligible``) still
    applies on top — this only narrows it."""
    return (megastep.direct_goals is None
            or goal_name in megastep.direct_goals)


def donation_enabled(megastep: "MegastepConfig | None") -> bool:
    """Donate only off zero-copy backends: on CPU the state tensors may
    alias host buffers owned by the incremental model pipeline
    (refresh.py ships loads zero-copy when alignment allows), and the
    topology cache shares device arrays across generations — the same
    rule refresh.py applies to its own donation decision."""
    return (megastep is not None and megastep.donate
            and jax.default_backend() != "cpu")


class DispatchStats:
    """Per-optimization-pass dispatch accounting: how many device
    dispatches the solve cost, how many rounds each carried, and how many
    were donated / speculative (the async pump's post-convergence no-op).
    Mirrored into the sensor registry via utils.xla_telemetry so the
    bench and CI can read dispatch_count / rounds_per_dispatch_p50
    without threading state through every driver."""

    def __init__(self):
        self.rounds_per_dispatch: list[int] = []
        self.donated = 0
        self.speculative = 0
        self.by_kind: dict[str, int] = {}
        # Goals that consumed ZERO dispatches thanks to the
        # fingerprint-skip snapshot (round 18): their entry stats came
        # from the one batched pre-chain program and showed nothing to do.
        self.goals_skipped = 0
        # crc32 of the pass's per-goal entry-violation vector (the
        # round-18 fingerprint; None when the snapshot did not run).
        self.fingerprint = None

    def record(self, kind: str, rounds: int, donated: bool = False,
               speculative: bool = False, telemetry: bool = True) -> None:
        """``telemetry=False`` keeps the tally local: the megabatch pump
        splits ONE physical dispatch into per-cluster accounting records,
        and only the physical record may hit the solver_dispatches
        sensors (a 4-cluster dispatch is one XLA execution, not four)."""
        self.rounds_per_dispatch.append(int(rounds))
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        if donated:
            self.donated += 1
        if speculative:
            self.speculative += 1
        if not telemetry:
            return
        from ..utils.xla_telemetry import record_dispatch
        record_dispatch(kind, int(rounds), donated=donated,
                        speculative=speculative)

    @property
    def dispatch_count(self) -> int:
        return len(self.rounds_per_dispatch)

    def rounds_p50(self) -> float:
        if not self.rounds_per_dispatch:
            return 0.0
        ordered = sorted(self.rounds_per_dispatch)
        return float(ordered[(len(ordered) - 1) // 2])

    def as_dict(self) -> dict:
        out = {"dispatch_count": self.dispatch_count,
               "rounds_per_dispatch_p50": self.rounds_p50(),
               "donated_dispatches": self.donated,
               "speculative_dispatches": self.speculative}
        if self.by_kind.get("direct"):
            # Present only when the direct-assignment kernel ran, so
            # pre-direct accounting consumers see an unchanged dict.
            out["direct_dispatches"] = self.by_kind["direct"]
        if self.goals_skipped:
            # Present only when the fingerprint snapshot actually skipped
            # goals (same compatibility discipline as direct_dispatches).
            out["goals_skipped"] = self.goals_skipped
        if self.fingerprint is not None:
            out["violation_fingerprint"] = self.fingerprint
        return out


def deficit_sized_config(cfg: SearchConfig, viol0: float,
                         cap: int) -> SearchConfig:
    """Deficit-aware batch sizing for the count-distribution goals: size
    the per-round move budget (and the source width that bounds how many
    moves a round can actually admit — selection takes at most one move
    per source row) from the goal's measured total band violation instead
    of the configured constant, so an O(10k)-move imbalance is not fed
    through hundreds of fixed-width rounds.

    Each move shifts one replica from an over-band broker to an
    under-band one, reducing the total violation by up to 2 — the move
    target is ``viol0 / 2``. The width is rounded UP to a power of two
    (compile-count quantization: every distinct (sources, moves) pair is
    a new XLA program) and clamped to [cfg values, cap]. Sizing depends
    only on the goal's ENTRY violations, so it is identical for any
    dispatch-budget sequence — trajectory invariance holds per sized
    config."""
    from .fill import pow2_width
    target = int(viol0) // 2
    if target <= cfg.moves_per_round:
        return cfg
    q = min(pow2_width(target), max(cap, cfg.moves_per_round))
    if q <= cfg.moves_per_round and q <= cfg.num_sources:
        return cfg
    return dataclasses.replace(
        cfg, moves_per_round=max(cfg.moves_per_round, q),
        num_sources=max(cfg.num_sources, q))


def run_bounded_pass(enqueue: Callable, st, pass_cap: int,
                     controller: AdaptiveDispatch,
                     out_of_time: Callable[[], bool] | None = None,
                     async_readback: bool = True,
                     stats: DispatchStats | None = None,
                     kind: str = "move",
                     flight=NO_FLIGHT):
    """Drive one logical pass (a fixed-point loop of at most ``pass_cap``
    search rounds) as a sequence of bounded megastep dispatches.

    ``enqueue(st, budget) -> (st, applied, rounds, donated, ring)`` fires
    one dispatch and returns WITHOUT reading anything back (jax async
    dispatch); the scalars are device futures and ``donated`` reports
    whether THIS dispatch ran the donated kernel (per-dispatch, so the
    donation telemetry stays exact). ``ring`` is the dispatch's per-round
    flight-stats buffer (None on paths without one); it is read — and
    handed to ``flight`` (utils.flight_recorder goal hook) together with
    the dispatch's budget/rounds/applied/controller state — exactly when
    the dispatch's scalars are read, so recording never adds a host
    round-trip. With ``async_readback`` the pump
    keeps one dispatch in flight: dispatch N+1 is enqueued — chained on
    N's output state, budgeted against the PESSIMISTIC estimate that N
    runs its full budget (the estimate can only under-budget N+1, never
    overshoot ``pass_cap``) — before N's scalars are read, so the
    host↔device link latency of the readback overlaps device compute.
    ``controller`` observes each dispatch when its scalars arrive — one
    step behind the enqueue decision it feeds (the AdaptiveDispatch
    staleness contract). In the pipelined steady state dispatch N cannot
    start on device before N−1 completes (its input is N−1's output), so
    N's own cost is measured as the delta from the PREVIOUS readback's
    return to this one — timing from enqueue would fold N−1's remaining
    execution into N and systematically ~double the observed cost,
    pinning the budget at its floor.

    A dispatch that reports fewer rounds than its budget hit the pass's
    fixed point; the speculatively-enqueued successor (if any) re-runs a
    single zero-apply round that leaves the state byte-identical and
    applies nothing — it is recorded in ``stats`` (speculative=True) but
    contributes neither moves nor rounds to the pass totals, so the
    round budget matches the synchronous path's exactly. Trajectory is
    invariant to all of it: same round sequence, only dispatch boundaries
    and readback timing differ.

    Returns (st, applied_total, pass_rounds)."""
    applied_total = 0
    pass_rounds = 0
    est_rounds = 0
    prev = None    # (applied, rounds, budget, t0, donated, ring) — unread
    last_read_t = None
    converged = False
    while True:
        cur = None
        may_enqueue = prev is None or async_readback
        if may_enqueue and not converged and est_rounds < pass_cap \
                and not (out_of_time is not None and out_of_time()):
            budget = controller.budget(pass_cap - est_rounds)
            t0 = _time.monotonic()
            st, applied, r, donated, ring = enqueue(st, budget)
            cur = (applied, r, budget, t0, donated, ring)
            est_rounds += budget
        if prev is not None:
            applied_p, r_p, budget_p, t0_p, donated_p, ring_p = prev
            # ccsa: ok[CCSA001] THE pump readback: dispatch N's scalars are
            # read here exactly one enqueue behind — N+1 is already in
            # flight, so this block overlaps device compute by design
            r_read = int(r_p)                       # blocks on dispatch N
            now = _time.monotonic()
            start = t0_p if last_read_t is None else max(t0_p, last_read_t)
            # ccsa: ok[CCSA001] same readback point: N already synced via
            # r_read, this transfer is paid, not a new stall
            applied_total += int(applied_p)
            controller.observe(r_read, budget_p, now - start)
            last_read_t = now
            if stats is not None:
                stats.record(kind, r_read, donated=donated_p)
            # ccsa: ok[CCSA001] same readback point, applied_p already read
            flight.dispatch(kind, budget_p, r_read, int(applied_p),
                            donated=donated_p, elapsed_s=now - start,
                            controller_k=controller.k, ring=ring_p)
            pass_rounds += r_read
            est_rounds -= budget_p - r_read         # correct the estimate
            if r_read < budget_p:
                converged = True
        if converged and cur is not None:
            # Speculative post-convergence dispatch: one re-run of the
            # terminal zero-apply round (state frozen on device, applies
            # nothing). Its rounds are NOT added to pass_rounds — they
            # make no search progress, and counting them would consume
            # cfg.max_rounds budget the synchronous per-round path does
            # not pay, diverging the paths at the round-cap boundary.
            # Its ring rows repeat the terminal round — dropped for the
            # same reason.
            if stats is not None:
                # ccsa: ok[CCSA001] post-convergence drain: nothing left to
                # pipeline behind this readback — the pass is over
                stats.record(kind, int(cur[1]), donated=cur[4],
                             speculative=True)
            # ccsa: ok[CCSA001] post-convergence drain, same as above
            flight.dispatch(kind, cur[2], int(cur[1]), 0, donated=cur[4],
                            speculative=True, controller_k=controller.k)
            cur = None
        prev = cur
        if prev is None and (converged or est_rounds >= pass_cap
                             or (out_of_time is not None and out_of_time())):
            break
    return st, applied_total, pass_rounds


# ---------------------------------------------------------------------------
# Megabatch: whole buckets of clusters through ONE device program
# ---------------------------------------------------------------------------
#
# The fleet layer pads every cluster onto a shared bucket grid
# (fleet.bucketing), so same-bucket clusters are shape-identical pytrees.
# Stacking them along a leading cluster axis and vmapping the round body
# turns the megastep into a FLEET megastep: one donated dispatch advances
# every cluster in the batch by up to ``budget`` rounds, with a
# per-cluster early-exit mask replacing the scalar early-exit flag — a
# converged (or inert pad-slot) cluster's carry is frozen by a select, so
# its state stays byte-identical to a serial solve while its neighbors
# keep searching. Rounds run in lockstep: the batched dispatch costs
# max-over-clusters rounds instead of the serial sum — the
# Podracer/Anakin lever (compile once per bucket shape, amortize the
# whole fleet through it).


def stack_states(states: Sequence[ClusterTensors]) -> ClusterTensors:
    """Stack shape-identical cluster states along a new leading cluster
    axis (the megabatch layout). All states must share one padded bucket
    shape — the fleet assembler's grouping contract."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def unstack_state(batched: ClusterTensors, index: int) -> ClusterTensors:
    """Slice cluster ``index`` back out of a megabatch state."""
    return jax.tree.map(lambda x: x[index], batched)


def inert_state_like(state: ClusterTensors) -> ClusterTensors:
    """A zero-weight pad-slot cluster at ``state``'s shape: every broker
    DEAD/masked with zero capacity, every partition empty and masked —
    the same pad-row encoding fleet.bucketing uses for rows, applied to a
    WHOLE cluster slot. It generates no candidates, no violations, and no
    offline replicas, so the per-goal activation mask never wakes it; a
    partially-filled megabatch pads with these so one compiled program
    per bucket shape serves any occupancy."""
    from ..common.broker_state import BrokerState
    return dataclasses.replace(
        state,
        assignment=jnp.full_like(state.assignment, -1),
        leader_slot=jnp.full_like(state.leader_slot, -1),
        leader_load=jnp.zeros_like(state.leader_load),
        follower_load=jnp.zeros_like(state.follower_load),
        capacity=jnp.zeros_like(state.capacity),
        rack=jnp.zeros_like(state.rack),
        broker_state=jnp.full_like(state.broker_state,
                                   int(BrokerState.DEAD)),
        topic=jnp.zeros_like(state.topic),
        partition_mask=jnp.zeros_like(state.partition_mask),
        broker_mask=jnp.zeros_like(state.broker_mask))


def _mask_axes(masks: ExclusionMasks):
    """(fields, vmap axes) for a BATCHED ExclusionMasks: each field is
    either None for every cluster in the batch or stacked ``[C, ...]``
    (the assembler's mask-uniformity contract)."""
    fields = (masks.excluded_topics, masks.excluded_replica_move_brokers,
              masks.excluded_leadership_brokers)
    return fields, tuple(None if f is None else 0 for f in fields)


def _megabatch_rounds_driver(states: ClusterTensors, active0: jax.Array,
                             active_idx: jax.Array, prior_mask: jax.Array,
                             goals: tuple[Goal, ...],
                             constraint: BalancingConstraint,
                             cfg: SearchConfig, num_topics: int,
                             masks: ExclusionMasks, budget: jax.Array,
                             ring_rounds: int = 0):
    """Traced body of the batched move megastep: one ``lax.while_loop``
    whose body vmaps ``_chain_round_body`` over the leading cluster axis.

    ``active0[C]`` is the per-cluster early-exit mask threaded DISPATCH TO
    DISPATCH as a device value (the pump chains it like the state, so
    enqueueing the next dispatch never reads it back): a cluster runs a
    round only while active, a zero-apply round deactivates it, and an
    inactive cluster's whole carry (state, aggregate, ring) is frozen by a
    select — byte-identical to the serial megastep, which simply stops
    dispatching at that point. The loop ends when every cluster is
    inactive or the shared round budget is spent; while active, a
    cluster's within-dispatch round index equals the global one (all
    clusters start at round 0 together), so the aggregate refresh cadence
    matches the serial driver's exactly.

    ``ring_rounds`` > 0 grows the flight ring a CLUSTER axis:
    ``[C, ring_rounds, STAT_WIDTH]``, one per-round stats row per cluster,
    frozen with the rest of the carry once the cluster exits.

    Returns (states, total[C], rounds[C], active_out[C], ring-or-None)."""
    collect = ring_rounds > 0
    c = states.assignment.shape[0]
    mask_fields, mask_ax = _mask_axes(masks)

    def per_cluster(s, a, ring, tm, rm, lm, gr):
        m = ExclusionMasks(tm, rm, lm)
        a = maybe_refresh(a, s, num_topics, gr)
        ns, na, applied, stat = _chain_round_body(
            s, a, active_idx, prior_mask, goals, constraint, cfg,
            num_topics, m, collect=collect)
        if collect:
            ring = ring.at[gr % ring_rounds].set(stat)
        return ns, na, ring, applied

    vround = jax.vmap(per_cluster,
                      in_axes=(0, 0, 0) + mask_ax + (None,))

    def freeze(active):
        def sel(new, old):
            keep = active.reshape((c,) + (1,) * (new.ndim - 1))
            return jnp.where(keep, new, old)
        return sel

    cap = jnp.minimum(jnp.int32(cfg.max_rounds), budget.astype(jnp.int32))

    def cond(carry):
        _s, _a, _r, _tot, _rnd, gr, active = carry
        return active.any() & (gr < cap)

    def body(carry):
        st, ag, ring, tot, rnd, gr, active = carry
        nst, nag, nring, applied = vround(st, ag, ring, *mask_fields, gr)
        sel = freeze(active)
        st = jax.tree.map(sel, nst, st)
        ag = jax.tree.map(sel, nag, ag)
        ring = sel(nring, ring)
        applied = jnp.where(active, applied, 0).astype(jnp.int32)
        return (st, ag, ring, tot + applied,
                rnd + active.astype(jnp.int32), gr + 1,
                active & (applied > 0))

    agg0 = jax.vmap(lambda s: compute_agg(s, num_topics))(states)
    ring0 = jnp.zeros((c, ring_rounds if collect else 0, _FLIGHT_STATS),
                      jnp.float32)
    final, _agg, ring, total, rounds, _gr, active = jax.lax.while_loop(
        cond, body,
        (states, agg0, ring0, jnp.zeros((c,), jnp.int32),
         jnp.zeros((c,), jnp.int32), jnp.int32(0), active0))
    return final, total, rounds, active, (ring if collect else None)


@partial(jax.jit, static_argnames=("goals", "constraint", "cfg", "num_topics",
                                   "ring_rounds"))
def megabatch_optimize_rounds(states: ClusterTensors, active0: jax.Array,
                              active_idx: jax.Array, prior_mask: jax.Array,
                              goals: tuple[Goal, ...],
                              constraint: BalancingConstraint,
                              cfg: SearchConfig, num_topics: int,
                              masks: ExclusionMasks, budget: jax.Array,
                              ring_rounds: int = 0):
    """Batched fused move driver (the non-donating megabatch twin of
    ``chain_optimize_rounds``; the CPU / parity-oracle path). Occupancy is
    a traced property (``active0`` plus inert pad-slot clusters), so ONE
    compilation per bucket shape serves any fill level."""
    final, total, rounds, active, ring = _megabatch_rounds_driver(
        states, active0, active_idx, prior_mask, goals, constraint, cfg,
        num_topics, masks, budget, ring_rounds=ring_rounds)
    if ring_rounds > 0:
        return final, total, rounds, active, ring
    return final, total, rounds, active


@partial(jax.jit, static_argnames=("goals", "constraint", "cfg",
                                   "num_topics", "ring_rounds"),
         donate_argnums=(0, 1))
def megabatch_optimize_rounds_donated(assignment: jax.Array,
                                      leader_slot: jax.Array,
                                      rest: ClusterTensors,
                                      active0: jax.Array,
                                      active_idx: jax.Array,
                                      prior_mask: jax.Array,
                                      goals: tuple[Goal, ...],
                                      constraint: BalancingConstraint,
                                      cfg: SearchConfig, num_topics: int,
                                      masks: ExclusionMasks,
                                      budget: jax.Array,
                                      ring_rounds: int = 0):
    """The donated fleet megastep: identical trace to
    ``megabatch_optimize_rounds`` with the BATCHED mutable pair
    ``{assignment[C,P,S], leader_slot[C,P]}`` donated — exactly the
    strip_mutable donation set grown a cluster axis, nothing else (the
    stacked topology planes in ``rest`` are built from the refresh
    cache's shared arrays and must never be donated; CCSA002 verifies the
    batched kernel form too). Callers pass ``strip_mutable`` applied
    per cluster before stacking as ``rest``."""
    states = dataclasses.replace(rest, assignment=assignment,
                                 leader_slot=leader_slot)
    final, total, rounds, active, ring = _megabatch_rounds_driver(
        states, active0, active_idx, prior_mask, goals, constraint, cfg,
        num_topics, masks, budget, ring_rounds=ring_rounds)
    if ring_rounds > 0:
        return (final.assignment, final.leader_slot, total, rounds, active,
                ring)
    return final.assignment, final.leader_slot, total, rounds, active


def _megabatch_swap_driver(states: ClusterTensors, active0: jax.Array,
                           active_idx: jax.Array, prior_mask: jax.Array,
                           goals: tuple[Goal, ...],
                           constraint: BalancingConstraint, num_topics: int,
                           masks: ExclusionMasks, moves: int,
                           max_rounds: int, budget: jax.Array):
    """Batched swap-phase driver (same per-cluster freeze discipline as
    the move driver; swap phases carry no flight ring)."""
    c = states.assignment.shape[0]
    mask_fields, mask_ax = _mask_axes(masks)

    def per_cluster(s, a, tm, rm, lm, gr):
        m = ExclusionMasks(tm, rm, lm)
        a = maybe_refresh(a, s, num_topics, gr)
        ns, na, applied = _chain_swap_body(s, a, active_idx, prior_mask,
                                           goals, constraint, num_topics,
                                           m, moves)
        return ns, na, applied

    vround = jax.vmap(per_cluster, in_axes=(0, 0) + mask_ax + (None,))
    cap = jnp.minimum(jnp.int32(max_rounds), budget.astype(jnp.int32))

    def cond(carry):
        _s, _a, _tot, _rnd, gr, active = carry
        return active.any() & (gr < cap)

    def body(carry):
        st, ag, tot, rnd, gr, active = carry
        nst, nag, applied = vround(st, ag, *mask_fields, gr)

        def sel(new, old):
            keep = active.reshape((c,) + (1,) * (new.ndim - 1))
            return jnp.where(keep, new, old)

        st = jax.tree.map(sel, nst, st)
        ag = jax.tree.map(sel, nag, ag)
        applied = jnp.where(active, applied, 0).astype(jnp.int32)
        return (st, ag, tot + applied, rnd + active.astype(jnp.int32),
                gr + 1, active & (applied > 0))

    agg0 = jax.vmap(lambda s: compute_agg(s, num_topics))(states)
    final, _agg, total, rounds, _gr, active = jax.lax.while_loop(
        cond, body,
        (states, agg0, jnp.zeros((c,), jnp.int32),
         jnp.zeros((c,), jnp.int32), jnp.int32(0), active0))
    return final, total, rounds, active


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics",
                                   "moves", "max_rounds"))
def megabatch_swap_rounds(states: ClusterTensors, active0: jax.Array,
                          active_idx: jax.Array, prior_mask: jax.Array,
                          goals: tuple[Goal, ...],
                          constraint: BalancingConstraint, num_topics: int,
                          masks: ExclusionMasks, moves: int,
                          max_rounds: int, budget: jax.Array):
    """Batched fused swap driver (non-donating twin)."""
    return _megabatch_swap_driver(states, active0, active_idx, prior_mask,
                                  goals, constraint, num_topics, masks,
                                  moves, max_rounds, budget)


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics",
                                   "moves", "max_rounds"),
         donate_argnums=(0, 1))
def megabatch_swap_rounds_donated(assignment: jax.Array,
                                  leader_slot: jax.Array,
                                  rest: ClusterTensors, active0: jax.Array,
                                  active_idx: jax.Array,
                                  prior_mask: jax.Array,
                                  goals: tuple[Goal, ...],
                                  constraint: BalancingConstraint,
                                  num_topics: int, masks: ExclusionMasks,
                                  moves: int, max_rounds: int,
                                  budget: jax.Array):
    """Donated batched swap megastep (see
    megabatch_optimize_rounds_donated for the donation contract)."""
    states = dataclasses.replace(rest, assignment=assignment,
                                 leader_slot=leader_slot)
    final, total, rounds, active = _megabatch_swap_driver(
        states, active0, active_idx, prior_mask, goals, constraint,
        num_topics, masks, moves, max_rounds, budget)
    return final.assignment, final.leader_slot, total, rounds, active


@partial(jax.jit, static_argnames=("goals", "constraint", "num_topics"))
def megabatch_goal_stats(states: ClusterTensors, active_idx: jax.Array,
                         goals: tuple[Goal, ...],
                         constraint: BalancingConstraint, num_topics: int,
                         masks: ExclusionMasks):
    """Per-cluster (violation, objective, offline) of the active goal on a
    megabatch state — the batched twin of ``chain_goal_stats``, one device
    call for the whole bucket."""
    mask_fields, mask_ax = _mask_axes(masks)

    def per_cluster(s, tm, rm, lm):
        return _chain_goal_stats_body(s, active_idx, goals, constraint,
                                      num_topics,
                                      ExclusionMasks(tm, rm, lm))

    return jax.vmap(per_cluster, in_axes=(0,) + mask_ax)(states,
                                                         *mask_fields)


def run_megabatch_pass(enqueue: Callable, st, active0, pass_cap: int,
                       controller: AdaptiveDispatch,
                       async_readback: bool = True,
                       stats: "list[DispatchStats] | None" = None,
                       physical_stats: "DispatchStats | None" = None,
                       kind: str = "move", flights=None):
    """Drive one logical BATCHED pass as a sequence of bounded megabatch
    dispatches — the fleet twin of ``run_bounded_pass``, same one-behind
    pump. ``enqueue(st, active, budget) -> (st, active_out, applied,
    rounds, donated, ring)`` fires one batched dispatch and returns
    device futures only; the per-cluster early-exit mask ``active_out``
    chains into the next enqueue exactly like the state, so pipelining
    never waits on it. Scalars become ``[C]`` arrays: the readback
    decodes them ONCE per dispatch and splits per-cluster accounting out
    of it — ``stats[b]`` records cluster b's rounds (dispatch accounting
    split), ``flights[b]`` gets its dispatch record plus its slice of the
    cluster-axis flight ring, and ``physical_stats`` records the ONE
    actual XLA execution (the sensor-facing tally; per-cluster splits
    skip telemetry so a 4-cluster dispatch never counts as 4 device
    executions).

    The pass converges when every cluster's early-exit mask clears. The
    speculatively-enqueued successor then runs ZERO rounds (every
    cluster inactive at entry — cheaper than the serial speculative
    zero-apply round, and byte-identical since inactive clusters are
    frozen); it is recorded speculative and contributes nothing.

    Returns (st, active_final_host, applied_totals, rounds_totals) with
    the totals as per-cluster numpy int arrays."""
    import numpy as np
    c = active0.shape[0]
    applied_total = np.zeros(c, dtype=np.int64)
    rounds_total = np.zeros(c, dtype=np.int64)
    # ccsa: ok[CCSA001] pass-entry decode of the caller's activation
    # mask — nothing is in flight before the first enqueue
    active_host = np.asarray(active0).astype(bool)
    entry_active = active_host.copy()
    active_dev = active0
    est_rounds = 0
    prev = None   # (applied, rounds, active_out, budget, t0, donated, ring)
    last_read_t = None
    converged = False
    while True:
        cur = None
        may_enqueue = prev is None or async_readback
        if may_enqueue and not converged and est_rounds < pass_cap:
            budget = controller.budget(pass_cap - est_rounds)
            t0 = _time.monotonic()
            st, active_dev, applied, r, donated, ring = enqueue(
                st, active_dev, budget)
            cur = (applied, r, active_dev, budget, t0, donated, ring)
            est_rounds += budget
        if prev is not None:
            applied_p, r_p, act_p, budget_p, t0_p, donated_p, ring_p = prev
            # ccsa: ok[CCSA001] THE megabatch pump readback: dispatch N's
            # per-cluster arrays are read here exactly one enqueue behind
            # — N+1 is already in flight chained on N's output state and
            # early-exit mask, so this block overlaps device compute
            rounds_np = np.asarray(r_p)             # blocks on dispatch N
            now = _time.monotonic()
            start = t0_p if last_read_t is None else max(t0_p, last_read_t)
            # ccsa: ok[CCSA001] same readback point: N already synced via
            # rounds_np, these transfers are paid, not new stalls
            applied_np = np.asarray(applied_p)
            # ccsa: ok[CCSA001] same readback point (the early-exit mask
            # the NEXT enqueue already consumed on device)
            active_host = np.asarray(act_p).astype(bool)
            # ccsa: ok[CCSA001] decode of the already-fetched host array
            global_rounds = int(rounds_np.max()) if c else 0
            applied_total += applied_np
            rounds_total += rounds_np
            controller.observe(global_rounds, budget_p, now - start)
            last_read_t = now
            if physical_stats is not None:
                physical_stats.record(kind, global_rounds, donated=donated_p)
            for b in range(c):
                if rounds_np[b] <= 0:
                    continue
                if stats is not None:
                    # ccsa: ok[CCSA001] per-cluster split of the paid
                    # readback: host numpy scalar decodes only
                    stats[b].record(kind, int(rounds_np[b]),
                                    donated=donated_p, telemetry=False)
                if flights is not None:
                    # ccsa: ok[CCSA001] same split, host numpy decodes
                    r_b, a_b = int(rounds_np[b]), int(applied_np[b])
                    flights[b].dispatch(
                        kind, budget_p, r_b, a_b, donated=donated_p,
                        elapsed_s=now - start, controller_k=controller.k,
                        ring=None if ring_p is None else ring_p[b])
            est_rounds -= budget_p - global_rounds
            if not active_host.any():
                converged = True
        if converged and cur is not None:
            # Speculative post-convergence dispatch: every cluster entered
            # inactive, so the batched while_loop ran zero rounds and the
            # state is untouched — recorded, never counted.
            if physical_stats is not None:
                physical_stats.record(kind, 0, donated=cur[5],
                                      speculative=True)
            if flights is not None:
                # Only clusters that PARTICIPATED in this pass get the
                # speculative record — a goal-satisfied (or pad-slot)
                # cluster that never activated records no dispatch at
                # all, exactly like its serial solve.
                for b in range(c):
                    if entry_active[b]:
                        flights[b].dispatch(kind, cur[3], 0, 0,
                                            donated=cur[5],
                                            speculative=True,
                                            controller_k=controller.k)
            cur = None
        prev = cur
        if prev is None and (converged or est_rounds >= pass_cap):
            break
    return st, active_host, applied_total, rounds_total


def optimize_goal_in_chain_megabatch(states: ClusterTensors,
                                     chain: Sequence[Goal], index: int,
                                     constraint: BalancingConstraint,
                                     cfg: SearchConfig, num_topics: int,
                                     masks: ExclusionMasks,
                                     cluster_mask,
                                     dispatch_rounds: int,
                                     dispatch: AdaptiveDispatch,
                                     megastep: MegastepConfig,
                                     stats: "list[DispatchStats] | None" = None,
                                     physical_stats: "DispatchStats | None" = None,
                                     flights=None,
                                     donate_input: bool = False,
                                     entry_stats: tuple | None = None,
                                     drain_hint=None,
                                     mesh=None,
                                     ) -> tuple[ClusterTensors, list[dict]]:
    """Run goal ``chain[index]`` for EVERY cluster in a megabatch under
    the acceptance of ``chain[:index]`` — the batched twin of
    ``optimize_goal_in_chain``, bounded-dispatch path only (the megabatch
    exists to amortize dispatches; there is no batched unbounded path).

    ``cluster_mask[C]`` (host bool array) marks real cluster slots: inert
    pad slots are never activated, count no rounds, and get no info dict
    semantics beyond zeros. Per-cluster failures do NOT raise — a hard
    goal failing on cluster 2 must not abort clusters 0, 1, 3 — instead
    each returned info dict may carry ``error``/``error_type`` and the
    caller freezes that cluster for the rest of the chain (its state then
    matches the serial solve's at its raise point).

    Deficit-aware count-goal sizing is structurally OFF here: it sizes
    the search grid from ONE cluster's entry violation, and a megabatch
    shares one compiled grid across the bucket (the assembler's config
    key pins this).

    ``entry_stats`` / ``drain_hint`` (round 18): this goal's per-cluster
    ``([C] violation, [C] objective, [C] offline)`` and drain-pending
    ``[C]`` bools already computed by ONE ``megabatch_all_goal_stats``
    snapshot for the whole chain — valid only while no goal has mutated
    any cluster since the snapshot (the ``chain_owns_state`` gate). A
    goal the snapshot shows inactive for EVERY cluster consumes zero
    batched dispatches.

    ``mesh`` (round 23): a 1-D device mesh routes every batched kernel
    through its shard_map twin (parallel.megabatch_sharded) — the
    cluster axis splits ``batch_width / n_devices`` slots per device,
    everything else (this whole host loop, the pump, the donation guard)
    is unchanged because the sharded wrappers are call-compatible. The
    caller must have placed ``states``/``masks`` on the mesh and padded
    the batch to a device multiple.

    Returns (states, [per-cluster info dict])."""
    import numpy as np
    goals = tuple(chain)
    goal = goals[index]
    idx = jnp.int32(index)
    prior = jnp.asarray([j < index for j in range(len(goals))])
    c = states.assignment.shape[0]
    cluster_mask = np.asarray(cluster_mask).astype(bool)
    assert dispatch_rounds > 0, "megabatch requires the bounded path"

    # Resolve the kernel family ONCE (single-path code below): either the
    # single-device jitted megabatch kernels or their sharded twins with
    # the mesh bound in. Lazy import — analyzer must not depend on
    # parallel at module load.
    if mesh is not None:
        from ..parallel import megabatch_sharded as _mbs
        mb_stats = partial(_mbs.megabatch_goal_stats_sharded, mesh)
        mb_move = partial(_mbs.megabatch_optimize_rounds_sharded, mesh)
        mb_move_don = partial(
            _mbs.megabatch_optimize_rounds_donated_sharded, mesh)
        mb_swap = partial(_mbs.megabatch_swap_rounds_sharded, mesh)
        mb_swap_don = partial(
            _mbs.megabatch_swap_rounds_donated_sharded, mesh)
    else:
        mb_stats = megabatch_goal_stats
        mb_move = megabatch_optimize_rounds
        mb_move_don = megabatch_optimize_rounds_donated
        mb_swap = megabatch_swap_rounds
        mb_swap_don = megabatch_swap_rounds_donated

    if entry_stats is not None:
        viol0, obj0, off0 = (np.asarray(entry_stats[0]),
                             np.asarray(entry_stats[1]),
                             np.asarray(entry_stats[2]))
    else:
        viol0_d, obj0_d, off0_d = mb_stats(states, idx, goals, constraint,
                                           num_topics, masks)
        viol0 = np.asarray(viol0_d)
        obj0 = np.asarray(obj0_d)
        off0 = np.asarray(off0_d)
    if flights is not None:
        for b in range(c):
            if cluster_mask[b]:
                flights[b].entry(violation=float(viol0[b]),
                                 objective=float(obj0[b]),
                                 offline=int(off0[b]))
                flights[b].grid(cfg.num_sources, cfg.num_dests,
                                cfg.moves_per_round)
    drain = np.zeros(c, dtype=bool)
    if masks.excluded_replica_move_brokers is not None:
        drain = np.asarray(drain_hint).astype(bool) \
            if drain_hint is not None \
            else np.asarray(jax.vmap(excluded_hosting_replicas)(
                states, masks.excluded_replica_move_brokers).any(axis=(1, 2)))
    ran = cluster_mask & ((viol0 > 0) | (off0 > 0) | drain)
    if entry_stats is not None and not ran.any():
        # Whole-goal fingerprint skip: no cluster has anything to do, so
        # the goal pays zero batched dispatches (entry/exit stats both
        # come from the snapshot).
        if physical_stats is not None:
            physical_stats.goals_skipped += 1
        if stats is not None:
            for b in range(c):
                if cluster_mask[b]:
                    stats[b].goals_skipped += 1

    donate = donation_enabled(megastep)
    async_rb = bool(megastep.async_readback)
    ring_n = 0
    if flights is not None and flights and flights[0].recording:
        ring_n = flights[0].ring_rounds
    can_donate = [bool(donate_input)]

    def make_enqueue(phase: str):
        def enqueue(st, active, budget: int):
            b = jnp.int32(budget)
            ring = None
            if donate:
                if not can_donate[0]:
                    st = dataclasses.replace(
                        st, assignment=jnp.copy(st.assignment),
                        leader_slot=jnp.copy(st.leader_slot))
                rest = dataclasses.replace(
                    st,
                    assignment=jnp.zeros((c, 0, st.assignment.shape[2]),
                                         st.assignment.dtype),
                    leader_slot=jnp.zeros((c, 0), st.leader_slot.dtype))
                if phase == "move":
                    out = mb_move_don(
                        st.assignment, st.leader_slot, rest, active, idx,
                        prior, goals, constraint, cfg, num_topics, masks,
                        b, ring_rounds=ring_n)
                    a, l, applied, r, act = out[:5]
                    ring = out[5] if ring_n > 0 else None
                else:
                    a, l, applied, r, act = mb_swap_don(
                        st.assignment, st.leader_slot, rest, active, idx,
                        prior, goals, constraint, num_topics, masks, 8,
                        64, b)
                st = dataclasses.replace(st, assignment=a, leader_slot=l)
            elif phase == "move":
                out = mb_move(
                    st, active, idx, prior, goals, constraint, cfg,
                    num_topics, masks, b, ring_rounds=ring_n)
                st, applied, r, act = out[:4]
                ring = out[4] if ring_n > 0 else None
            else:
                st, applied, r, act = mb_swap(
                    st, active, idx, prior, goals, constraint, num_topics,
                    masks, 8, 64, b)
            can_donate[0] = True
            return st, act, applied, r, donate, ring
        return enqueue

    applied_total = np.zeros(c, dtype=np.int64)
    swaps_total = np.zeros(c, dtype=np.int64)
    rounds_total = np.zeros(c, dtype=np.int64)
    direct_moves = np.zeros(c, dtype=np.int64)
    direct_sweeps = np.zeros(c, dtype=np.int64)
    # Direct-assignment pre-pass, batched (analyzer.direct megabatch
    # twins): one dispatch advances EVERY participating cluster's bulk
    # transport in lockstep, with inactive clusters (pad slots, clusters
    # with offline replicas or drains — those keep the full greedy
    # semantics) frozen by the batched early-exit mask; the greedy cycle
    # below polishes the residue. Occupancy stays traced — the direct
    # program compiles once per bucket shape, like every other megabatch
    # kernel.
    use_direct = False
    if megastep.direct_assignment and direct_path_chosen(megastep,
                                                         goal.name):
        from .direct import direct_eligible
        use_direct = direct_eligible(goals, index)
    direct_active = ran & (off0 == 0) & ~drain & (viol0 > 0)
    if use_direct and direct_active.any():
        from .direct import sparse_rounding_seed
        from ..utils.sensors import SENSORS
        if mesh is not None:
            mb_direct = partial(_mbs.megabatch_direct_rounds_sharded, mesh)
            mb_direct_don = partial(
                _mbs.megabatch_direct_rounds_donated_sharded, mesh)
        else:
            from .direct import megabatch_direct_rounds as mb_direct
            from .direct import (
                megabatch_direct_rounds_donated as mb_direct_don,
            )
        active0 = jnp.asarray(direct_active)
        t0 = _time.monotonic()
        if donate:
            if not can_donate[0]:
                states = dataclasses.replace(
                    states, assignment=jnp.copy(states.assignment),
                    leader_slot=jnp.copy(states.leader_slot))
            rest = dataclasses.replace(
                states,
                assignment=jnp.zeros((c, 0, states.assignment.shape[2]),
                                     states.assignment.dtype),
                leader_slot=jnp.zeros((c, 0), states.leader_slot.dtype))
            a, l, mv, sw, _act = mb_direct_don(
                states.assignment, states.leader_slot, rest, active0,
                goals, index, constraint, num_topics, masks,
                megastep.direct_max_sweeps,
                margin_frac=megastep.direct_sparse_margin,
                seed=sparse_rounding_seed(megastep.direct_sparse_salt))
            states = dataclasses.replace(states, assignment=a,
                                         leader_slot=l)
            can_donate[0] = True
        else:
            states, mv, sw, _act = mb_direct(
                states, active0, goals, index, constraint, num_topics,
                masks, megastep.direct_max_sweeps,
                margin_frac=megastep.direct_sparse_margin,
                seed=sparse_rounding_seed(megastep.direct_sparse_salt))
        mv_np = np.asarray(mv)
        sw_np = np.asarray(sw)
        elapsed = _time.monotonic() - t0
        direct_moves += mv_np
        direct_sweeps += sw_np
        applied_total += mv_np
        # ONE physical XLA execution; per-cluster splits skip telemetry
        # (the run_megabatch_pass accounting discipline).
        if physical_stats is not None:
            physical_stats.record("direct", int(sw_np.max()),
                                  donated=donate)
        for b in range(c):
            if stats is not None and sw_np[b] > 0:
                stats[b].record("direct", int(sw_np[b]), donated=donate,
                                telemetry=False)
            if flights is not None and direct_active[b]:
                flights[b].dispatch(
                    "direct", megastep.direct_max_sweeps, int(sw_np[b]),
                    int(mv_np[b]), donated=donate, elapsed_s=elapsed)
        SENSORS.count("solver_direct_sweeps", int(sw_np.max()))
        SENSORS.count("solver_direct_moves", int(mv_np.sum()))
    alive = ran.copy()
    while True:
        # A cluster joins the next move+swap cycle exactly when the serial
        # host loop would: its last swap pass applied something (or this
        # is its first cycle) and its cumulative rounds sit below the cap.
        participate = alive & (rounds_total < cfg.max_rounds)
        if not participate.any():
            break
        active0 = jnp.asarray(participate)
        states, _act, moved, r = run_megabatch_pass(
            make_enqueue("move"), states, active0, cfg.max_rounds,
            dispatch, async_readback=async_rb, stats=stats,
            physical_stats=physical_stats, kind="move", flights=flights)
        applied_total += moved
        rounds_total += r
        if not goal.supports_swap:
            break
        states, _act, swapped, sr = run_megabatch_pass(
            make_enqueue("swap"), states, jnp.asarray(participate), 64,
            dispatch, async_readback=async_rb, stats=stats,
            physical_stats=physical_stats, kind="swap", flights=flights)
        swaps_total += swapped
        applied_total += swapped
        rounds_total += sr
        alive = participate & (swapped > 0)

    if ran.any():
        viol1_d, obj1_d, off1_d = mb_stats(
            states, idx, goals, constraint, num_topics, masks)
        viol1 = np.asarray(viol1_d)
        obj1 = np.asarray(obj1_d)
        off1 = np.asarray(off1_d)
    else:
        viol1, obj1, off1 = viol0, obj0, off0
    # Skipped clusters never ran: their entry stats ARE their exit stats
    # (the batched kernels froze them, but the goal-stats recompute on a
    # frozen state is the same value — use the entry read for exactness).
    viol1 = np.where(ran, viol1, viol0)
    obj1 = np.where(ran, obj1, obj0)
    off1 = np.where(ran, off1, off0)

    infos: list[dict] = []
    for b in range(c):
        if flights is not None and cluster_mask[b]:
            flights[b].exit(violation=float(viol1[b]),
                            objective=float(obj1[b]),
                            offline=int(off1[b]))
        total_violation = float(viol1[b])
        succeeded = total_violation <= 1e-6
        info = {
            "goal": goal.name,
            "rounds": int(rounds_total[b]),
            "moves_applied": int(applied_total[b]),
            "swaps_applied": int(swaps_total[b]),
            "residual_violation": total_violation,
            "succeeded": succeeded,
            "objective": float(obj1[b]),
            "violated_on_entry": float(viol0[b]) > 1e-6,
            "offline_remaining": int(off1[b]),
        }
        if use_direct:
            info["direct_moves"] = int(direct_moves[b])
            info["direct_sweeps"] = int(direct_sweeps[b])
        if cluster_mask[b] and int(off0[b]) == 0:
            before, after = float(obj0[b]), float(obj1[b])
            if after > before + 1e-4 * max(1.0, abs(before)):
                info["error_type"] = "StatsRegressionError"
                info["error"] = (
                    f"goal {goal.name} regressed its own objective during "
                    f"its optimization: {before:.6g} -> {after:.6g}")
        if cluster_mask[b] and goal.is_hard and not succeeded \
                and "error" not in info:
            info["error_type"] = "OptimizationFailureError"
            info["error"] = (
                f"hard goal {goal.name} unsatisfied: residual violation "
                f"{total_violation:.4f} after {int(rounds_total[b])} rounds")
        infos.append(info)
    return states, infos


def optimize_goal_in_chain(state: ClusterTensors, chain: Sequence[Goal],
                           index: int, constraint: BalancingConstraint,
                           cfg: SearchConfig, num_topics: int,
                           masks: ExclusionMasks | None = None,
                           dispatch_rounds: int = 0,
                           dispatch: AdaptiveDispatch | None = None,
                           wall_budget_s: float = 0.0,
                           megastep: MegastepConfig | None = None,
                           stats: DispatchStats | None = None,
                           donate_input: bool = False,
                           flight=NO_FLIGHT,
                           entry_stats: tuple | None = None,
                           drain_hint: bool | None = None,
                           ) -> tuple[ClusterTensors, dict]:
    """Run goal ``chain[index]`` to convergence under the acceptance of
    ``chain[:index]``, using the chain-shared kernels (same semantics and
    info dict as ``search.optimize_goal``, one compile for the whole chain).

    ``dispatch_rounds`` > 0 caps the search rounds a SINGLE device dispatch
    may run (the host loops to the same fixed point — identical
    trajectory, more round-trips). This bounds per-dispatch wall-clock: at
    1k+ brokers the unbounded fused drivers run tens of seconds in one
    XLA program, which device runtimes with an execution watchdog (the
    axon TPU tunnel) kill as wedged (BENCH r3: 'TPU worker process
    crashed' on the 1,000-broker stage).

    Enforces the per-goal stats-regression guard (AbstractGoal.java:111-119):
    the active goal's objective on exit must not exceed its objective on
    entry. Skipped when offline replicas exist at entry — self-healing
    placement takes precedence over the goal's own balance objective
    (ClusterModel.selfHealingEligibleReplicas semantics).

    ``wall_budget_s`` > 0 (fast mode: fast.mode.per.broker.move.timeout.ms
    x num_brokers) stops dispatching further search rounds for this goal
    once its elapsed wall-clock exceeds the budget — the batch-search
    analogue of the reference's per-broker move timeout
    (ResourceDistributionGoal.java:470-475), enforceable at dispatch
    granularity on the bounded path. Hard goals still raise on residual
    violations, exactly like the reference in fast mode.

    ``megastep`` selects the bounded path's dispatch machinery (donation,
    async readback, deficit-aware count-goal sizing; see MegastepConfig);
    None keeps the r9 synchronous non-donating behavior. ``donate_input``
    declares the CALLER relinquishes ``state`` — the first dispatch then
    donates it directly; otherwise it donates a device COPY of the two
    mutable tensors (intermediate states are loop-owned and donated
    as-is). ``stats`` collects per-dispatch accounting.

    ``flight`` (utils.flight_recorder goal hook) records entry/exit
    violations, grid geometry, sizing decisions, and per-dispatch
    telemetry; when it is recording, the MOVE-phase kernels run with the
    per-round stats ring enabled (``ring_rounds``) — reductions only, so
    the trajectory is unchanged (the recorder's parity contract).

    ``entry_stats`` (round 18 fingerprint skip): the goal's
    ``(violation, objective, offline)`` ALREADY computed by the one
    batched pre-chain ``chain_all_goal_stats`` program — valid only while
    no earlier goal has mutated the state since that snapshot (the
    caller's responsibility; the optimizer gates on ``chain_owns_state``).
    With it provided, the per-goal entry stats dispatch is skipped, and a
    goal with nothing to do consumes ZERO dispatches (counted in
    ``stats.goals_skipped``) — byte-identical to the unhinted path, since
    the hint holds the exact values that dispatch would have returned.
    ``drain_hint`` is the matching precomputed drain-pending bool (drain
    is goal-independent, a function of state + masks only).
    """
    goal_t0 = _time.monotonic()

    def out_of_time() -> bool:
        return wall_budget_s > 0 \
            and _time.monotonic() - goal_t0 > wall_budget_s

    masks = masks or ExclusionMasks()
    goals = tuple(chain)
    goal = goals[index]
    idx = jnp.int32(index)
    prior = jnp.asarray([j < index for j in range(len(goals))])

    if entry_stats is not None:
        viol0, obj0, offline0 = entry_stats
    else:
        viol0, obj0, offline0 = chain_goal_stats(state, idx, goals,
                                                 constraint, num_topics,
                                                 masks)
    flight.entry(violation=float(viol0), objective=float(obj0),
                 offline=int(offline0))
    total_applied = 0
    total_swaps = 0
    rounds = 0
    bounded = dispatch_rounds > 0
    if bounded and dispatch is None:
        dispatch = AdaptiveDispatch(dispatch_rounds, target_s=0.0)
    donate = donation_enabled(megastep) and bounded
    async_rb = bool(megastep.async_readback) if megastep is not None \
        else False
    drain = False
    if masks.excluded_replica_move_brokers is not None:
        drain = bool(drain_hint) if drain_hint is not None \
            else bool(excluded_hosting_replicas(
                state, masks.excluded_replica_move_brokers).any())
    # Direct-assignment pre-pass eligibility (analyzer.direct): bounded
    # path, kernel enabled for this pass (the optimizer resolves the
    # config flag AND the wide-regime gate into megastep), a
    # guard-representable chain prefix, and a clean model — self-healing
    # (offline replicas) and drains keep the full greedy semantics, the
    # same pause rule as the targeted-destination column.
    use_direct = False
    if bounded and megastep is not None and megastep.direct_assignment \
            and direct_path_chosen(megastep, goal.name) \
            and int(offline0) == 0 and not drain:
        from .direct import direct_eligible
        use_direct = direct_eligible(goals, index)
    if bounded and megastep is not None and megastep.deficit_moves_cap > 0 \
            and goal.count_based and not use_direct:
        # Deficit-aware sizing from the goal's ENTRY violations — a
        # pass-level constant, so the trajectory stays invariant to the
        # dispatch-budget sequence under the sized config.
        base_cfg = cfg
        cfg = deficit_sized_config(cfg, float(viol0),
                                   megastep.deficit_moves_cap)
        flight.sizing(entry_violation=float(viol0),
                      base_moves=base_cfg.moves_per_round,
                      base_sources=base_cfg.num_sources,
                      sized_moves=cfg.moves_per_round,
                      sized_sources=cfg.num_sources,
                      cap=megastep.deficit_moves_cap)
    flight.grid(cfg.num_sources, cfg.num_dests, cfg.moves_per_round)
    # Per-round on-device flight ring: MOVE phases of the single-device
    # chain kernels only (the stats live in the round body; swap phases
    # and the sharded kernels record at dispatch granularity).
    ring_n = flight.ring_rounds if flight.recording else 0
    # Donation gate: the first dispatch consumes the caller's state —
    # donatable only on the caller's say-so; everything after consumes
    # loop-owned intermediates. With donation ON, the first dispatch
    # COPIES the two mutable tensors instead of falling back to the
    # non-donated kernel: a copy is an O(P·RF) device op, while the
    # fallback would compile the full-chain program TWICE (plain +
    # donated — minutes each at scale).
    can_donate = [bool(donate_input)]

    def run_pass(phase: str, st, pass_cap: int):
        """One logical pass (a single fixed-point loop of up to
        ``pass_cap`` rounds), split into bounded megastep dispatches when
        bounded (round budget sized by ``dispatch``, pumped by
        run_bounded_pass). The per-dispatch cap rides a TRACED budget (no
        recompile per value); a dispatch stopping below its budget hit a
        zero-apply round, i.e. the pass's fixed point. Identical
        trajectory either way — the round sequence is the same, only
        dispatch boundaries differ."""
        if not bounded:
            # One dispatch IS the whole pass (the kernel's static cap
            # equals pass_cap).
            ring = None
            if phase == "move":
                # 3-tuple when ring_n == 0, 4-tuple with the ring
                # appended otherwise (the kernel's static-flag contract).
                out = chain_optimize_rounds(
                    st, idx, prior, goals, constraint, cfg, num_topics,
                    masks, ring_rounds=ring_n)
                st, applied, r = out[:3]
                ring = out[3] if ring_n > 0 else None
            else:
                st, applied, r = chain_swap_rounds(
                    st, idx, prior, goals, constraint, num_topics, masks)
            if stats is not None:
                stats.record(phase, int(r))
            flight.dispatch(phase, pass_cap, int(r), int(applied),
                            ring=ring)
            return st, int(applied), int(r)

        def enqueue(st, budget: int):
            b = jnp.int32(budget)
            ring = None
            if donate:
                if not can_donate[0]:
                    # Caller retains the input: donate a copy of the two
                    # mutable tensors, never the caller's buffers.
                    st = dataclasses.replace(
                        st, assignment=jnp.copy(st.assignment),
                        leader_slot=jnp.copy(st.leader_slot))
                rest = strip_mutable(st)
                if phase == "move":
                    out = chain_optimize_rounds_donated(
                        st.assignment, st.leader_slot, rest, idx, prior,
                        goals, constraint, cfg, num_topics, masks, b,
                        ring_rounds=ring_n)
                    a, l, applied, r = out[:4]
                    ring = out[4] if ring_n > 0 else None
                else:
                    a, l, applied, r = chain_swap_rounds_donated(
                        st.assignment, st.leader_slot, rest, idx, prior,
                        goals, constraint, num_topics, masks, 8, 64, b)
                st = dataclasses.replace(st, assignment=a, leader_slot=l)
            elif phase == "move":
                out = chain_optimize_rounds(
                    st, idx, prior, goals, constraint, cfg, num_topics,
                    masks, budget=b, ring_rounds=ring_n)
                st, applied, r = out[:3]
                ring = out[3] if ring_n > 0 else None
            else:
                st, applied, r = chain_swap_rounds(
                    st, idx, prior, goals, constraint, num_topics, masks,
                    budget=b)
            can_donate[0] = True
            return st, applied, r, donate, ring

        return run_bounded_pass(
            enqueue, st, pass_cap, dispatch,
            out_of_time=out_of_time if wall_budget_s > 0 else None,
            async_readback=async_rb, stats=stats, kind=phase,
            flight=flight)

    # Fast path (parity with chain_optimize_full's per-goal lax.cond skip
    # and the sharded bounded driver): nothing violated, nothing offline,
    # no drain pending = the search fixed point is immediate — skip the
    # drivers and their dispatch round-trips entirely.
    ran = float(viol0) > 0 or int(offline0) > 0 or drain
    if not ran and entry_stats is not None and stats is not None:
        # Fingerprint skip: the goal consumed ZERO dispatches — its entry
        # stats came from the batched pre-chain snapshot and its exit
        # stats ARE its entry stats (nothing ran).
        stats.goals_skipped += 1
    direct_moves = 0
    direct_sweeps = 0
    if ran and use_direct and float(viol0) > 0:
        # Direct-assignment pre-pass: the bulk transport in ONE dispatch
        # (kind="direct" in stats/flight — its own dispatch series, out
        # of the acceptance-density histogram); the greedy loop below
        # polishes whatever the feasibility masks vetoed.
        from .direct import run_direct_pass
        (state, direct_moves, direct_sweeps, d_donated,
         d_stranded) = run_direct_pass(
            state, goals, index, constraint, num_topics, masks, megastep,
            megastep.direct_max_sweeps, stats=stats, flight=flight,
            donate_input=can_donate[0])
        if d_donated:
            # The direct kernel consumed (a copy of) the mutable pair;
            # its outputs are chain-owned, so later dispatches may donate
            # them directly.
            can_donate[0] = True
        total_applied += direct_moves
        if megastep.deficit_moves_cap > 0 and goal.count_based:
            # Deficit-size the POLISH from the larger of two residual
            # estimates (no extra stats dispatch): viol0 − moves (a
            # transport move fixes at least 1 unit — but margin-depth
            # moves fix 0, so this alone can zero out) and 2× the
            # STRANDED movers the kernel reports at exit (each stranded
            # mover is up to 2 violation units feasibility refused to
            # place). When the transport left a real residue, the polish
            # must not grind it through base-width rounds.
            base_cfg = cfg
            cfg = deficit_sized_config(
                cfg, max(float(viol0) - float(direct_moves),
                         2.0 * float(d_stranded)),
                megastep.deficit_moves_cap)
            if cfg is not base_cfg:
                flight.sizing(entry_violation=float(viol0),
                              base_moves=base_cfg.moves_per_round,
                              base_sources=base_cfg.num_sources,
                              sized_moves=cfg.moves_per_round,
                              sized_sources=cfg.num_sources,
                              cap=megastep.deficit_moves_cap)
                flight.grid(cfg.num_sources, cfg.num_dests,
                            cfg.moves_per_round)
    if ran:
        while rounds < cfg.max_rounds and not out_of_time():
            state, moves, r = run_pass("move", state, cfg.max_rounds)
            total_applied += moves
            rounds += r
            if not goal.supports_swap:
                break
            state, swapped, sr = run_pass("swap", state, 64)
            total_swaps += swapped
            total_applied += swapped
            rounds += sr
            if swapped == 0:
                break

    if ran:
        viol, obj, offline = chain_goal_stats(state, idx, goals, constraint,
                                              num_topics, masks)
    else:
        # Skipped goal: the state is untouched, entry stats ARE exit stats.
        viol, obj, offline = viol0, obj0, offline0
    flight.exit(violation=float(viol), objective=float(obj),
                offline=int(offline))
    if int(offline0) == 0:
        before, after = float(obj0), float(obj)
        if after > before + 1e-4 * max(1.0, abs(before)):
            raise StatsRegressionError(
                f"goal {goal.name} regressed its own objective during its "
                f"optimization: {before:.6g} -> {after:.6g}")
    total_violation = float(viol)
    succeeded = total_violation <= 1e-6
    if goal.is_hard and not succeeded:
        raise OptimizationFailureError(
            f"hard goal {goal.name} unsatisfied: residual violation "
            f"{total_violation:.4f} after {rounds} rounds")
    info = {
        "goal": goal.name,
        "rounds": rounds,
        "moves_applied": total_applied,
        "swaps_applied": total_swaps,
        "residual_violation": total_violation,
        "succeeded": succeeded,
        "objective": float(obj),
        "violated_on_entry": float(viol0) > 1e-6,
        "offline_remaining": int(offline),
    }
    if use_direct:
        # Direct-pass attribution (keys present only when the direct mode
        # was in force, so the disabled path's info dict stays identical
        # to the pre-direct contract).
        info["direct_moves"] = direct_moves
        info["direct_sweeps"] = direct_sweeps
    return state, info
