"""Incrementally-maintained per-broker aggregates for the search loop.

Every search round needs the per-broker aggregate state (load [B, R],
replica/leader counts, potential NW-out, leader bytes-in, per-(topic,
broker) replica counts). Recomputing them from the [P, S] assignment is a
set of segment-sum scatters over every replica — O(P·S) work per round that
dominates the round body at scale (measured at 7k brokers / 1M partitions:
``broker_load`` alone ~40 ms of a ~160 ms host-CPU round; the scatters
together are more than half the round).

A move batch touches at most ``moves_per_round`` partitions, and its exact
per-broker effect is already known (CandidateDeltas), so the aggregates can
be UPDATED in O(moves) scatters instead. This module provides the carry:

- :func:`compute_agg` — the full recompute (loop entry / refresh).
- :func:`apply_deltas_to_agg` — scatter the selected move batch's effect.

Integer counts stay exact under incremental updates. Float sums
(broker_load, pot_nw_out, lbi) accumulate rounding drift relative to a
fresh segment-sum (different summation order), so the loop refreshes the
carry every :data:`REFRESH_EVERY` rounds — the drift window is ~64 rounds
of f32 adds (relative error ~1e-6, far inside the 1e-6-absolute epsilons
of the acceptance bands, which judge O(1)-magnitude normalized loads).

The reference maintains the same aggregates incrementally inside its object
graph (Broker.load updated by Replica relocation — ClusterModel.java:380
relocateReplica → Broker.removeReplica/addReplica); this is that design,
vectorized.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..common.resources import Resource
from ..model.tensors import (
    ClusterTensors, broker_leader_counts, broker_load,
    broker_replica_counts, leader_bytes_in, potential_nw_out,
    topic_broker_replica_counts,
)

# Full-recompute cadence inside a fused loop (bounds f32 drift; counts are
# exact regardless). Power of two so the modulo folds to a bit-mask.
REFRESH_EVERY = 64


@partial(jax.tree_util.register_dataclass,
         data_fields=["broker_load", "broker_replicas", "broker_leaders",
                      "pot_nw_out", "lbi", "topic_counts"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class AggCarry:
    """Replicated per-broker aggregate state threaded through the round
    loop. On a sharded mesh every field is the GLOBAL (psum'd) value — the
    selected move batch is replicated across devices, so incremental
    updates stay replicated without further collectives."""

    broker_load: jax.Array      # [B, R] f32
    broker_replicas: jax.Array  # [B] i32
    broker_leaders: jax.Array   # [B] i32
    pot_nw_out: jax.Array       # [B] f32
    lbi: jax.Array              # [B] f32 (leader NW_IN per broker)
    topic_counts: jax.Array     # [T, B] i32


def compute_agg(state: ClusterTensors, num_topics: int,
                psum=None) -> AggCarry:
    """Full aggregate recompute (the segment-sum path). ``psum`` combines
    the partition-local partials across a sharded mesh."""
    p = psum or (lambda x: x)
    return AggCarry(
        broker_load=p(broker_load(state)),
        broker_replicas=p(broker_replica_counts(state)),
        broker_leaders=p(broker_leader_counts(state)),
        pot_nw_out=p(potential_nw_out(state)),
        lbi=p(leader_bytes_in(state)),
        topic_counts=p(topic_broker_replica_counts(state, num_topics)),
    )


@dataclasses.dataclass(frozen=True)
class AggDelta:
    """Minimal per-candidate effect view for :func:`apply_deltas_to_agg`
    when a full CandidateDeltas is not at hand (sharded swap legs)."""

    src_broker: jax.Array
    dst_broker: jax.Array
    load_delta: jax.Array
    replica_delta: jax.Array
    leader_delta: jax.Array
    topic: jax.Array


def apply_deltas_to_agg(agg: AggCarry, sub, sel: jax.Array,
                        pot_delta: jax.Array, lbi_delta: jax.Array,
                        ) -> AggCarry:
    """Scatter the effect of the accepted candidates onto the carry.

    ``sub`` is the selected CandidateDeltas batch (or anything exposing the
    AggDelta fields, e.g. a swap leg), ``sel`` the accepted mask;
    ``pot_delta``/``lbi_delta`` the per-candidate potential-NW-out /
    leader-bytes-in transfer scalars (the same values cumulative_select
    feeds attach_cumulative). Non-selected rows route to the out-of-bounds
    bucket and are dropped — mirroring apply_selected's scatter
    discipline."""
    b = agg.broker_load.shape[0]
    oob = jnp.int32(b)
    src = jnp.where(sel, sub.src_broker, oob)
    dst = jnp.where(sel, sub.dst_broker, oob)
    rep = sub.replica_delta.astype(jnp.int32)
    lead = sub.leader_delta.astype(jnp.int32)
    return AggCarry(
        broker_load=agg.broker_load
        .at[src].add(-sub.load_delta, mode="drop")
        .at[dst].add(sub.load_delta, mode="drop"),
        broker_replicas=agg.broker_replicas
        .at[src].add(-rep, mode="drop").at[dst].add(rep, mode="drop"),
        broker_leaders=agg.broker_leaders
        .at[src].add(-lead, mode="drop").at[dst].add(lead, mode="drop"),
        pot_nw_out=agg.pot_nw_out
        .at[src].add(-pot_delta, mode="drop")
        .at[dst].add(pot_delta, mode="drop"),
        lbi=agg.lbi
        .at[src].add(-lbi_delta, mode="drop")
        .at[dst].add(lbi_delta, mode="drop"),
        topic_counts=agg.topic_counts
        .at[sub.topic, src].add(-rep, mode="drop")
        .at[sub.topic, dst].add(rep, mode="drop"),
    )


def pot_lbi_deltas(state: ClusterTensors, sub) -> tuple[jax.Array, jax.Array]:
    """(pot_delta, lbi_delta) for a candidate batch: potential NW-out
    travels with the replica (PotentialNwOutGoal counts every replica as a
    would-be leader), leader bytes-in with the leadership."""
    pot = jnp.where(sub.replica_delta > 0,
                    state.leader_load[sub.partition, int(Resource.NW_OUT)],
                    0.0)
    lbi = jnp.where(sub.leader_delta > 0,
                    state.leader_load[sub.partition, int(Resource.NW_IN)],
                    0.0)
    return pot, lbi


def maybe_refresh(agg: AggCarry, state: ClusterTensors, num_topics: int,
                  rounds_done: jax.Array, psum=None) -> AggCarry:
    """Fresh recompute every REFRESH_EVERY rounds (f32 drift bound); the
    cheap incremental carry otherwise. Under a mesh the psum must run
    unconditionally (collectives cannot sit in one cond branch), so the
    refresh is NOT gated there — callers on the sharded path refresh at
    dispatch boundaries instead (entry recompute)."""
    if psum is not None:
        return agg
    return jax.lax.cond(
        (rounds_done % REFRESH_EVERY) == (REFRESH_EVERY - 1),
        lambda: compute_agg(state, num_topics),
        lambda: agg)
