"""Per-round derived state shared by every goal kernel.

The reference recomputes broker loads incrementally inside its object graph;
here one fused computation refreshes every derived tensor per search round
(cheap on TPU, and XLA fuses it into the round kernel).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..common.resources import Resource
from ..model.tensors import (
    ClusterTensors, alive_mask, broker_leader_counts, broker_load,
    broker_replica_counts, new_broker_mask, potential_nw_out,
)
from .constraint import BalancingConstraint


@partial(jax.tree_util.register_dataclass,
         data_fields=["broker_load", "broker_replicas", "broker_leaders",
                      "pot_nw_out", "alive", "new_brokers", "allowed_replica_move",
                      "allowed_leadership", "avg_util", "avg_replicas",
                      "avg_leaders", "movable_partition"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class DerivedState:
    broker_load: jax.Array        # [B, R]
    broker_replicas: jax.Array    # [B] int32
    broker_leaders: jax.Array     # [B] int32
    pot_nw_out: jax.Array         # [B]
    alive: jax.Array              # [B] bool
    new_brokers: jax.Array        # [B] bool
    allowed_replica_move: jax.Array  # [B] bool (alive & not excluded as dest)
    allowed_leadership: jax.Array    # [B] bool
    avg_util: jax.Array           # [R] — Σload / Σcapacity over allowed brokers
    avg_replicas: jax.Array       # scalar f32 over alive brokers
    avg_leaders: jax.Array        # scalar f32
    movable_partition: jax.Array  # [P] bool (not in an excluded topic)


def compute_derived(state: ClusterTensors,
                    excluded_topic_mask: jax.Array | None = None,
                    excluded_replica_move_brokers: jax.Array | None = None,
                    excluded_leadership_brokers: jax.Array | None = None,
                    psum=None, agg=None) -> DerivedState:
    """All per-broker aggregates + cluster averages in one pass.

    ``excluded_*`` are boolean masks aligned with topics/brokers (host-built
    from OptimizationOptions by the optimizer). ``psum`` combines the
    partition-additive aggregates across a sharded mesh (identity when the
    whole model lives on one device). ``agg`` (an
    :class:`~cruise_control_tpu.analyzer.agg.AggCarry`) supplies the
    per-broker aggregates pre-computed — the incrementally-maintained loop
    carry — skipping the O(P·S) segment-sums (and their psums: the carry is
    already global on a mesh).
    """
    p = psum or (lambda x: x)
    alive = alive_mask(state)
    if agg is not None:
        load, reps, leads, pot = (agg.broker_load, agg.broker_replicas,
                                  agg.broker_leaders, agg.pot_nw_out)
    else:
        load = p(broker_load(state))
        reps = p(broker_replica_counts(state))
        leads = p(broker_leader_counts(state))
        pot = p(potential_nw_out(state))
    new_b = new_broker_mask(state)

    excl_rm = (jnp.zeros(state.num_brokers, dtype=bool)
               if excluded_replica_move_brokers is None else excluded_replica_move_brokers)
    excl_ld = (jnp.zeros(state.num_brokers, dtype=bool)
               if excluded_leadership_brokers is None else excluded_leadership_brokers)
    allowed_rm = alive & ~excl_rm
    allowed_ld = alive & ~excl_ld

    # avgUtilizationPercentage = Σ load / Σ capacity over brokers allowed
    # replica moves (ResourceDistributionGoal.java:245-248).
    cap_sum = jnp.maximum((state.capacity * allowed_rm[:, None]).sum(axis=0), 1e-9)
    load_sum = (load * allowed_rm[:, None]).sum(axis=0)
    avg_util = load_sum / cap_sum

    n_alive = jnp.maximum(alive.sum(), 1)
    avg_reps = (reps * alive).sum() / n_alive
    avg_leads = (leads * alive).sum() / n_alive

    if excluded_topic_mask is None:
        movable = state.partition_mask
    else:
        movable = state.partition_mask & ~excluded_topic_mask[state.topic]

    return DerivedState(
        broker_load=load, broker_replicas=reps, broker_leaders=leads,
        pot_nw_out=pot, alive=alive, new_brokers=new_b,
        allowed_replica_move=allowed_rm, allowed_leadership=allowed_ld,
        avg_util=avg_util, avg_replicas=avg_reps, avg_leaders=avg_leads,
        movable_partition=movable,
    )


def resource_limits(state: ClusterTensors, derived: DerivedState,
                    constraint: BalancingConstraint, resource: Resource,
                    for_detector: bool = False) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(lower[B], upper[B], capacity_limit[B]) absolute load limits per
    broker for one resource (balance band around the average utilization +
    the capacity threshold; ResourceDistributionGoal.initGoalState /
    CapacityGoal)."""
    r = int(resource)
    lo_mult, up_mult = constraint.balance_band(resource, for_detector)
    cap = state.capacity[:, r]
    lower = derived.avg_util[r] * lo_mult * cap
    upper = derived.avg_util[r] * up_mult * cap
    cap_limit = constraint.capacity_threshold[r] * cap
    return lower, upper, cap_limit


def count_limits(avg: jax.Array, threshold: float) -> tuple[jax.Array, jax.Array]:
    """(lower, upper) replica-count limits
    (ReplicaDistributionAbstractGoal.initGoalState: ceil(avg*t), floor(avg/t))."""
    upper = jnp.ceil(avg * threshold)
    lower = jnp.floor(avg / threshold)
    return lower, upper
