"""Analyzer plugin SPIs: optimization-options generation + rack-id mapping.

Reference parity:
- ``OptimizationOptionsGenerator`` /
  ``DefaultOptimizationOptionsGenerator.java`` — a config-swappable hook
  deciding the ``OptimizationOptions`` used for goal-violation detection
  and cached-proposal computation (config key
  ``optimization.options.generator.class``, AnalyzerConfig.java:241).
- ``RackAwareGoalRackIdMapper`` (goals/rackaware/, AnalyzerConfig.java:249)
  — transforms broker rack ids before rack-aware goals group by them
  (e.g. collapse availability-zone suffixes). The NoOp default is
  identity.

Both resolve through ``abstract_config.resolve_class`` (the
getConfiguredInstance analogue); the excluded-topics regex
``topics.excluded.from.partition.movement`` is applied by the default
generator exactly like the reference's
``KafkaCruiseControlUtils.excludedTopics``.
"""

from __future__ import annotations

import re
from typing import Protocol, Sequence

from ..config.cruise_control_config import CruiseControlConfig
from .constraint import OptimizationOptions


class RackAwareGoalRackIdMapper(Protocol):
    def apply(self, rack_id: str) -> str: ...


class NoOpRackAwareGoalRackIdMapper:
    """Identity (NoOpRackAwareGoalRackIdMapper.java)."""

    def apply(self, rack_id: str) -> str:
        return rack_id


def rack_id_mapper_from_config(config: CruiseControlConfig,
                               ) -> RackAwareGoalRackIdMapper:
    spec = config.get("rack.aware.goal.rack.id.mapper.class")
    if not spec:
        return NoOpRackAwareGoalRackIdMapper()
    from ..config.abstract_config import resolve_class
    cls = resolve_class(spec) if isinstance(spec, str) else spec
    return cls()


def compile_excluded_topics_pattern(config: CruiseControlConfig):
    """Compiled ``topics.excluded.from.partition.movement`` regex or None.
    Compiling at construction makes a malformed pattern fail FAST (at app
    startup) instead of inside every detection cycle."""
    pattern = config.get("topics.excluded.from.partition.movement") or ""
    if not pattern:
        return None
    try:
        return re.compile(pattern)
    except re.error as e:
        from ..config.configdef import ConfigException
        raise ConfigException(
            f"invalid topics.excluded.from.partition.movement regex "
            f"{pattern!r}: {e}") from None




class OptimizationOptionsGenerator(Protocol):
    def for_goal_violation_detection(
            self, topic_names: Sequence[str],
            excluded_topics: Sequence[str],
            excluded_brokers_for_leadership: Sequence[int],
            excluded_brokers_for_replica_move: Sequence[int],
    ) -> OptimizationOptions: ...

    def for_cached_proposal_calculation(
            self, topic_names: Sequence[str],
            excluded_topics: Sequence[str],
    ) -> OptimizationOptions: ...


class DefaultOptimizationOptionsGenerator:
    """DefaultOptimizationOptionsGenerator.java: detection excludes the
    recently-demoted/removed brokers it is handed; the cached-proposal
    path excludes only topics. Both merge the config regex."""

    def __init__(self, config: CruiseControlConfig):
        self._config = config
        self._pattern = compile_excluded_topics_pattern(config)

    def merged_excluded_topics(self, topic_names: Sequence[str],
                               excluded_topics: Sequence[str] = (),
                               ) -> tuple[str, ...]:
        """Explicit exclusions merged with the config regex matches — the
        ONE implementation of the never-move-these-topics rule, shared by
        detection, proposals, and every executing operation (a second copy
        would let the dryrun and execution paths diverge)."""
        merged = set(excluded_topics)
        if self._pattern is not None:
            merged.update(t for t in topic_names
                          if self._pattern.fullmatch(t))
        return tuple(sorted(merged))

    _merged_topics = merged_excluded_topics  # internal alias

    def for_goal_violation_detection(
            self, topic_names: Sequence[str],
            excluded_topics: Sequence[str],
            excluded_brokers_for_leadership: Sequence[int],
            excluded_brokers_for_replica_move: Sequence[int],
    ) -> OptimizationOptions:
        return OptimizationOptions(
            excluded_topics=self._merged_topics(topic_names, excluded_topics),
            excluded_brokers_for_leadership=tuple(
                excluded_brokers_for_leadership),
            excluded_brokers_for_replica_move=tuple(
                excluded_brokers_for_replica_move),
            is_triggered_by_goal_violation=True)

    def for_cached_proposal_calculation(
            self, topic_names: Sequence[str],
            excluded_topics: Sequence[str],
    ) -> OptimizationOptions:
        return OptimizationOptions(
            excluded_topics=self._merged_topics(topic_names,
                                                excluded_topics))


def options_generator_from_config(config: CruiseControlConfig,
                                  ) -> OptimizationOptionsGenerator:
    spec = config.get("optimization.options.generator.class")
    if not spec:
        return DefaultOptimizationOptionsGenerator(config)
    from ..config.abstract_config import resolve_class
    cls = resolve_class(spec) if isinstance(spec, str) else spec
    return cls(config)
