"""Direct-assignment transport kernels for the count-distribution goals.

The greedy search pays for a count imbalance in ROUNDS: each round
scores a top-k grid, accepts a conflict-free batch, and re-dispatches —
at the 7k-broker/1M-partition north star TopicReplicaDistributionGoal
alone burns hundreds of acceptance-density-limited rounds shedding ~980
moves each (ROADMAP item 1). But a count goal's fixed point is KNOWN in
closed form: the per-broker (or per-topic×broker) target band is a pure
function of the counts, so the whole solve is a transport problem —
surplus replicas → deficit slots — not a search problem. This module
solves that transport as a vectorized matching in one (or a few) device
dispatches (the Podracer/Anakin "stop iterating" lever):

1. **Target counts on device**: the active goal's count plane
   ``[G, B]`` and band ``[lower, upper]`` (``G`` = 1 for the
   replica/leader goals, ``num_topics`` for the topic goal), with
   donor widening when deficits exceed base surplus (the
   ``donor_widened_shed`` semantics, integral and deterministic).
2. **Surplus replica selection**: ONE segmented sort of the flattened
   replica axis by ``(cell, weight)`` — cell = (group, src broker) —
   ranks every replica within its cell; the ``surplus[cell]`` lightest
   movable replicas are the movers (light-first, matching the greedy's
   ``replica_weight``).
3. **Cumsum rank-assignment**: each mover's rank within its group maps
   through the group's cumulative ``[deficit | headroom]`` profile
   (``analyzer.fill.deficit_fill_dests`` — the same kernel the targeted
   destination column uses per-card) to a destination broker, so the
   joint assignment respects every cell's integer gap by construction.
4. **Feasibility masking**: RF-sibling exclusion (destination must not
   already host the partition — nor receive two siblings in one
   sweep), rack-awareness when a rack goal is stacked prior, dead
   brokers, per-request exclusion options, the new-broker gate, and
   leadership-excluded destinations for leader movers.
5. **Prior-goal guards**: destination caps and source floors of every
   previously-optimized goal (replica-capacity / count bands / resource
   bands / capacity thresholds / potential NW-out), evaluated JOINTLY
   via dst-/src-sorted segmented exclusive cumsums — the
   ``attach_cumulative`` pre-delta contract at O(n log n) instead of
   O(m²), with the same conservative-overcount semantics (a vetoed
   earlier mover still shifts later movers' checks, which can only make
   them stricter).
6. **One-shot scatter apply**: all surviving movers land in a single
   functional scatter; a small on-device sweep loop (``max_sweeps``)
   re-runs the plan on the updated counts until nothing moves, so
   feasibility-vetoed leftovers get a second pairing without a host
   round-trip.

Anything the transport cannot place (structurally-blocked residue)
stays for the greedy polish pass that follows — the kernel REPLACES the
deficit-sized bulk rounds, not the acceptance machinery's judgment.

Safety discipline (two prior density "fixes" silently flipped the
86.0 → 82.74 CpuUsageDistribution canary and were reverted): the kernel
ships behind ``solver.direct.assignment.enabled`` (default OFF), only
activates in the wide regime (``solver.wide.batch.min.brokers``) where
deficit-sized greedy ran before, refuses chains whose prior goals it
cannot guard (``direct_eligible``), and is gated on the bench
regression sentry + full fixture matrix, never on round counts.

Donation contract: the donated twins donate EXACTLY the strip_mutable
pair ``{assignment, leader_slot}`` (CCSA002-checked); topology tensors
are refresh-cache-shared and never donated.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..common.resources import Resource
from ..model.tensors import (
    ClusterTensors, is_leader_slot, replica_load_total,
    topic_broker_replica_counts,
)
from .constraint import BalancingConstraint
from .derived import compute_derived, count_limits, resource_limits
from .fill import deficit_fill_dests
from .goals.base import Goal
from .goals.capacity import ReplicaCapacityGoal, ResourceCapacityGoal
from .goals.distribution import (
    CountDistributionGoal, PotentialNwOutGoal, TopicReplicaDistributionGoal,
    _int_deficit_headroom,
)
from .goals.rack import RackAwareGoal
from .search import ExclusionMasks, goal_aux

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class DirectGuards:
    """Static (trace-time) description of the prior-goal constraints the
    transport plan must respect — computed from the chain prefix, one
    flag/tuple per constraint family the feasibility pass knows how to
    model."""

    rack: bool = False              # strict sibling-rack exclusion
    replica_cap: bool = False       # ReplicaCapacityGoal hard cap
    replica_band: bool = False      # per-broker replica-count band
    leader_band: bool = False       # per-broker leader-count band
    topic_band: bool = False        # per-(topic, broker) count band
    resources: tuple[int, ...] = ()      # distribution bands (upper+lower)
    cap_resources: tuple[int, ...] = ()  # hard capacity thresholds
    pot_nw_out: bool = False        # potential NW-out limit


def _guards_for(goals: tuple[Goal, ...], index: int) -> DirectGuards:
    priors = goals[:index]
    from .goals.distribution import ResourceDistributionGoal
    return DirectGuards(
        rack=any(isinstance(g, RackAwareGoal) for g in priors),
        replica_cap=any(isinstance(g, ReplicaCapacityGoal) for g in priors),
        replica_band=any(isinstance(g, CountDistributionGoal)
                         and not g.leaders for g in priors),
        leader_band=any(isinstance(g, CountDistributionGoal)
                        and g.leaders for g in priors),
        topic_band=any(isinstance(g, TopicReplicaDistributionGoal)
                       for g in priors),
        resources=tuple(sorted({int(g.resource) for g in priors
                                if isinstance(g, ResourceDistributionGoal)})),
        cap_resources=tuple(sorted({int(g.resource) for g in priors
                                    if isinstance(g, ResourceCapacityGoal)})),
        pot_nw_out=any(isinstance(g, PotentialNwOutGoal) for g in priors))


#: Mean replicas per (topic, broker) cell below which the TOPIC-plane
#: transport is skipped (the sparse-cell regime): at ~1.5 replicas/cell
#: (the 1k/100k fixture — and north-star scale) the plan's granularity
#: equals the band width, feasibility-vetoed churn dominates, and the
#: greedy polish lands in a WORSE local optimum than the greedy-only
#: trajectory (measured ~10k residual vs 316; more sweeps made it
#: worse). The cluster-wide planes (replica/leader counts) have B cells
#: for P·S replicas and are always dense.
MIN_TOPIC_CELL_DENSITY = 4.0


def direct_regime_ok(goal: Goal, num_partitions: int, max_rf: int,
                     num_brokers: int, num_topics: int) -> bool:
    """Host-side density gate for the per-goal transport plan (shape
    arithmetic only — no device sync, so it works on batched megabatch
    shapes too): the integration layer skips the direct pre-pass for
    plane geometries the plan is known to mis-fit, falling back to
    deficit-sized greedy."""
    if isinstance(goal, TopicReplicaDistributionGoal):
        cells = max(1, num_topics * num_brokers)
        return num_partitions * max_rf / cells >= MIN_TOPIC_CELL_DENSITY
    return True


def direct_eligible(goals, index: int) -> bool:
    """True when ``goals[index]`` has a direct transport formulation AND
    every prior goal's acceptance is representable by the guard set —
    an unrecognized prior (broker sets, kafka-assigner variants, custom
    plugins) means the plan could silently violate a constraint the
    greedy's lexicographic stack would have vetoed, so the caller must
    keep the greedy path (the conservative fallback is the contract)."""
    from .goals.distribution import ResourceDistributionGoal
    goal = goals[index]
    if not getattr(goal, "supports_direct", False):
        return False
    recognized = (RackAwareGoal, ReplicaCapacityGoal, ResourceCapacityGoal,
                  CountDistributionGoal, TopicReplicaDistributionGoal,
                  PotentialNwOutGoal, ResourceDistributionGoal)
    return all(isinstance(g, recognized) for g in goals[:index])


# ---------------------------------------------------------------------------
# Segmented helpers over a key-sorted axis
# ---------------------------------------------------------------------------

def _segment_starts(keys: jax.Array) -> jax.Array:
    """[N] bool — first element of each equal-key run (keys sorted)."""
    return jnp.concatenate([jnp.ones((1,), bool), keys[1:] != keys[:-1]])


def _segment_rank(keys: jax.Array) -> jax.Array:
    """[N] int32 — position within the element's equal-key run."""
    pos = jnp.arange(keys.shape[0], dtype=jnp.int32)
    start = jax.lax.cummax(jnp.where(_segment_starts(keys), pos, 0))
    return pos - start


def _segment_exclusive(keys: jax.Array, values: jax.Array) -> jax.Array:
    """Exclusive within-segment cumsum of NON-NEGATIVE ``values`` ([N] or
    [N, R]) over a key-sorted axis. Non-negativity makes the running
    total monotone, so each segment's base is recoverable by a cummax of
    the totals pinned at segment starts — no scatter, no scan."""
    cum_ex = jnp.cumsum(values, axis=0) - values
    starts = _segment_starts(keys)
    if values.ndim == 2:
        starts = starts[:, None]
    base = jax.lax.cummax(jnp.where(starts, cum_ex, jnp.zeros_like(cum_ex)),
                          axis=0)
    return cum_ex - base


# ---------------------------------------------------------------------------
# The sweep bodies (traced)
# ---------------------------------------------------------------------------

def _dst_load_caps(ds, lv_d, state, derived, constraint,
                   guards: DirectGuards):
    """Joint per-resource upper-band + hard-capacity caps at the
    destination, in the dst-sorted frame (``lv_d`` is each mover's load
    vector already masked to selected movers). Shared by BOTH transport
    modes so the prior-goal contract cannot drift between them.
    Returns (okd [N] bool, pre_load [N, R])."""
    f32 = jnp.float32
    n = ds.shape[0]
    okd = jnp.ones(n, bool)
    inf1 = jnp.full((1,), jnp.inf, f32)
    pre_load = _segment_exclusive(ds, lv_d)
    for r in guards.resources:
        _lo, up_r, _c = resource_limits(state, derived, constraint,
                                        Resource(r))
        up_pad = jnp.concatenate([up_r, inf1])
        dl_pad = jnp.concatenate([derived.broker_load[:, r],
                                  jnp.zeros((1,), f32)])
        okd &= dl_pad[ds] + pre_load[:, r] + lv_d[:, r] <= up_pad[ds] + _EPS
    for r in guards.cap_resources:
        limit = constraint.capacity_threshold[r] * state.capacity[:, r]
        lim_pad = jnp.concatenate([limit, inf1])
        dl_pad = jnp.concatenate([derived.broker_load[:, r],
                                  jnp.zeros((1,), f32)])
        okd &= dl_pad[ds] + pre_load[:, r] + lv_d[:, r] <= lim_pad[ds] + _EPS
    return okd, pre_load


def _src_load_floors(ss, lv_s, state, derived, constraint,
                     guards: DirectGuards):
    """Joint per-resource lower-band floors at the source, in the
    src-sorted frame (``lv_s`` is each mover's OUTBOUND load vector
    masked to selected movers): cumulative outflow must not take the
    source below a previously-optimized resource goal's lower band (the
    greedy's stays-in-band source arm). Shared by both transport
    modes."""
    f32 = jnp.float32
    n = ss.shape[0]
    oks = jnp.ones(n, bool)
    ninf1 = jnp.full((1,), -jnp.inf, f32)
    pre_out = _segment_exclusive(ss, lv_s)
    for r in guards.resources:
        lo_r, _up, _c = resource_limits(state, derived, constraint,
                                        Resource(r))
        lo_pad = jnp.concatenate([lo_r, ninf1])
        sl_pad = jnp.concatenate([derived.broker_load[:, r],
                                  jnp.zeros((1,), f32)])
        oks &= sl_pad[ss] - pre_out[:, r] - lv_s[:, r] >= lo_pad[ss] - _EPS
    return oks


def _surplus_deficit(cnt, lower, upper, alive, elig_dst):
    """Integral (surplus, deficit, headroom) planes with donor widening
    (donor_widened_shed made integral and deterministic): when a group's
    deficits exceed its base surplus, in-band donors shed the difference,
    filled greedily in broker-index order so the plan is a pure function
    of the counts.

    Band-edge slack: violators shed down to (and receivers fill only up
    to) ``upper − margin`` with margin = 25% of the band width — NOT to
    the band's brim. A transport that parks every touched broker exactly
    AT the upper bound leaves later goals zero joint slack (every
    subsequent count/load move into those brokers is band-vetoed), and
    the greedy polish then stalls in a worse local optimum than the
    greedy-only trajectory, whose variance tiebreak naturally lands
    mid-band (measured at 64/2048: TopicReplica residual 70 vs 0).
    Sources are still ONLY actual violators (plus widened donors), so
    the extra depth costs a bounded per-violator margin, never an O(B)
    in-band churn."""
    margin = jnp.floor(jnp.maximum(upper - lower, 0.0) * 0.25)
    upper_eff = jnp.maximum(upper - margin, lower)
    base_sur = jnp.where(
        alive[None, :] & (cnt > upper + _EPS),
        jnp.floor(jnp.maximum(cnt - upper_eff, 0.0) + _EPS), 0.0)
    # Receivers likewise fill only to ``lower + margin`` (clamped into
    # the band): deficits land center-ward instead of spreading across
    # every broker's full remaining headroom, so no receiver is left
    # sitting exactly AT lower — the mirror-image edge with zero
    # OUTBOUND slack for later goals' source-side checks.
    fill_cap = jnp.minimum(lower + jnp.maximum(margin, 1.0), upper_eff)
    defi, headr = _int_deficit_headroom(cnt, lower, fill_cap)
    defi = jnp.where(elig_dst[None, :], defi, 0.0)
    headr = jnp.where(elig_dst[None, :], headr, 0.0)
    need = jnp.maximum(defi.sum(axis=1, keepdims=True)
                       - base_sur.sum(axis=1, keepdims=True), 0.0)
    donor_room = jnp.where(
        alive[None, :],
        jnp.floor(jnp.maximum(cnt - lower, 0.0) + _EPS) - base_sur, 0.0)
    donor_room = jnp.maximum(donor_room, 0.0)
    cum_before = jnp.cumsum(donor_room, axis=1) - donor_room
    extra = jnp.clip(need - cum_before, 0.0, donor_room)
    return base_sur + extra, defi, headr


def _leadership_sweep(state: ClusterTensors, goals: tuple[Goal, ...],
                      index: int, constraint: BalancingConstraint,
                      num_topics: int, masks: ExclusionMasks,
                      sweep: jax.Array | int = 0,
                      ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """Transport sweep for the LEADER-count goal via leadership
    TRANSFERS: after the replica goals have balanced counts, a leader
    replica move is almost always vetoed by the prior replica-count band
    — the reference (and the greedy here) rebalances leader counts by
    electing a different in-sync sibling instead. Each surplus leader's
    destination menu is its partition's own sibling replicas, so the
    plan picks the best sibling broker with leader-band room and caps
    joint intake per destination; replica placement (and every
    count/rack plane) is untouched, leaving only the resource-load
    guards (leadership carries ``leader_load − follower_load``)."""
    goal = goals[index]
    guards = _guards_for(goals, index)
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal_aux(goal, state, derived, constraint, num_topics)
    counts, lower, upper, _group, movable = goal.direct_spec(
        state, derived, constraint, aux, num_topics)

    p, s = state.assignment.shape
    b = state.num_brokers
    n = p * s
    f32 = jnp.float32
    alive = derived.alive
    lead_elig = derived.allowed_leadership & alive
    cnt = counts.astype(f32)
    surplus, defi, headr = _surplus_deficit(cnt, lower, upper, alive,
                                            lead_elig)
    room = (defi + headr)[0]                                       # [B]

    # Movers: the surplus[src] lightest leaders per over-band broker.
    # Leadership leaving a broker removes (leader_load − follower_load)
    # from it — the same dst-independent source pre-filter as the
    # replica transport: a leader whose departure ALONE would cross a
    # prior resource goal's lower band can reach no sibling at all, so
    # it must not occupy a surplus rank (negative components clamped —
    # an outflow that RAISES the source's load cannot cross a floor).
    alive_pad = jnp.concatenate([alive, jnp.zeros((1,), bool)])
    src_plane = jnp.where(state.assignment >= 0, state.assignment, b)
    mv = movable & derived.movable_partition[:, None] & alive_pad[src_plane]
    if guards.resources:
        ninf1 = jnp.full((1,), -jnp.inf, f32)
        for r in guards.resources:
            lo_r, _up_r, _c = resource_limits(state, derived, constraint,
                                              Resource(r))
            own_r = jnp.maximum(state.leader_load[:, r]
                                - state.follower_load[:, r], 0.0)[:, None]
            load_pad = jnp.concatenate([derived.broker_load[:, r],
                                        jnp.zeros((1,), f32)])
            lo_pad = jnp.concatenate([lo_r, ninf1])
            mv &= load_pad[src_plane] - own_r >= lo_pad[src_plane] - _EPS
    cell = jnp.where(mv, src_plane, b).astype(jnp.int32)
    weight = replica_load_total(state)
    sc, _sk, si = jax.lax.sort(
        (cell.reshape(-1), weight.reshape(-1),
         jnp.arange(n, dtype=jnp.int32)), num_keys=2)
    rank_cell = _segment_rank(sc)
    sur_pad = jnp.concatenate([surplus[0], jnp.zeros((1,), f32)])
    mover = rank_cell.astype(f32) < sur_pad[sc]

    # Destination menu = the partition's own existing sibling replicas
    # on leadership-eligible brokers with band room; best room wins
    # (deficits before headroom), ties to the lowest slot.
    p_m = si // s
    s_m = si % s
    src = jnp.minimum((sc % (b + 1)).astype(jnp.int32), b - 1)
    assign_p = state.assignment[p_m]                               # [N, S]
    not_me = jnp.arange(s, dtype=jnp.int32)[None, :] != s_m[:, None]
    sib_b = jnp.clip(assign_p, 0, b - 1)
    room_pad = room
    lead_elig_sib = lead_elig[sib_b] & (assign_p >= 0) & not_me
    sib_room = jnp.where(lead_elig_sib, room_pad[sib_b], -1.0)
    sib_score = jnp.where(lead_elig_sib,
                          defi[0][sib_b] * 1e6 + headr[0][sib_b], -jnp.inf)
    best_slot = jnp.argmax(sib_score, axis=1).astype(jnp.int32)
    dst = sib_b[jnp.arange(n), best_slot]
    ok = mover & (jnp.take_along_axis(
        sib_room, best_slot[:, None], axis=1)[:, 0] >= 1.0)
    ok &= dst != src

    sel = ok
    pos = jnp.arange(n, dtype=jnp.int32)
    # Joint intake cap per destination + prior resource-band guards, in
    # one dst-sorted pass (leadership shifts leader_load − follower_load;
    # negative components are clamped to zero — ignoring an inflow that
    # REDUCES load only makes the check stricter).
    lead_vec = jnp.maximum(state.leader_load[p_m] - state.follower_load[p_m],
                           0.0)
    dkey = jnp.where(sel, dst, b)
    ds, _dp, d_i = jax.lax.sort((dkey, pos, pos), num_keys=2)
    sel_d = sel[d_i]
    one_d = sel_d.astype(f32)
    pre_cnt = _segment_exclusive(ds, one_d)
    room_cap = jnp.concatenate([room, jnp.full((1,), jnp.inf, f32)])
    okd = pre_cnt + 1.0 <= room_cap[ds] + _EPS
    if guards.resources or guards.cap_resources:
        okd_load, _pre = _dst_load_caps(ds, lead_vec[d_i] * sel_d[:, None],
                                        state, derived, constraint, guards)
        okd &= okd_load
    sel &= jnp.zeros(n, bool).at[d_i].set(okd)

    # Joint source-side floors (the greedy's stays-in-band src arm):
    # several leaderships leaving ONE broker in the same sweep must not
    # jointly take its load below a prior resource goal's lower band —
    # the per-mover pre-filter above only bounds a single departure.
    if guards.resources:
        skey = jnp.where(sel, src, b)
        ss, _sp, s_i = jax.lax.sort((skey, pos, pos), num_keys=2)
        sel_s = sel[s_i]
        oks = _src_load_floors(ss, lead_vec[s_i] * sel_s[:, None],
                               state, derived, constraint, guards)
        sel &= jnp.zeros(n, bool).at[s_i].set(oks)

    rows = jnp.where(sel, p_m, p)
    new_leader = state.leader_slot.at[rows].set(
        best_slot.astype(state.leader_slot.dtype), mode="drop")
    return (dataclasses.replace(state, leader_slot=new_leader),
            sel.sum().astype(jnp.int32),
            mover.sum().astype(jnp.int32))

def _direct_sweep(state: ClusterTensors, goals: tuple[Goal, ...], index: int,
                  constraint: BalancingConstraint, num_topics: int,
                  masks: ExclusionMasks, sweep: jax.Array | int = 0,
                  ) -> tuple[ClusterTensors, jax.Array]:
    """One transport sweep for ``goals[index]``: plan the full
    surplus→deficit matching on the current counts, veto infeasible
    assignments, apply the rest in one scatter. ``sweep`` (traced)
    cyclically rotates each group's rank→profile mapping so a pairing
    vetoed by feasibility (sibling/rack collisions) is re-paired with a
    DIFFERENT destination on the next sweep even when the counts did not
    change — without it a fully-vetoed plan is a fixed point and the
    residue never re-pairs. Returns (new_state, applied)."""
    goal = goals[index]
    guards = _guards_for(goals, index)
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal_aux(goal, state, derived, constraint, num_topics)
    counts, lower, upper, group, movable = goal.direct_spec(
        state, derived, constraint, aux, num_topics)

    p, s = state.assignment.shape
    b = state.num_brokers
    g_dim = counts.shape[0]
    n = p * s
    f32 = jnp.float32

    alive = derived.alive
    has_new = derived.new_brokers.any()
    elig_dst = jnp.where(has_new, derived.new_brokers,
                         derived.allowed_replica_move) & alive
    cnt = counts.astype(f32)

    # --- target distribution: integral surplus / deficit / headroom ------
    surplus, defi, headr = _surplus_deficit(cnt, lower, upper, alive,
                                            elig_dst)               # [G, B]

    # --- mover selection: segmented sort by (cell, weight) ---------------
    alive_pad = jnp.concatenate([alive, jnp.zeros((1,), bool)])
    src_plane = jnp.where(state.assignment >= 0, state.assignment, b)
    mv = movable & derived.movable_partition[:, None] & alive_pad[src_plane]
    # Destination-INDEPENDENT source feasibility must be filtered out
    # BEFORE ranking: a replica whose departure alone would cross a
    # prior resource goal's lower band can reach no destination at all,
    # so letting it occupy a surplus rank wedges that rank forever (the
    # destination rotation can only re-pair, never re-select movers) —
    # measured at 64/2048: leader replicas of near-lower-band brokers
    # froze ~50 surplus ranks the greedy clears with other replicas.
    ninf1 = jnp.full((1,), -jnp.inf, f32)
    if guards.resources:
        lead_plane = is_leader_slot(state)
        for r in guards.resources:
            lo_r, _up_r, _c = resource_limits(state, derived, constraint,
                                              Resource(r))
            own_r = jnp.where(lead_plane, state.leader_load[:, r][:, None],
                              state.follower_load[:, r][:, None])
            load_pad = jnp.concatenate([derived.broker_load[:, r],
                                        jnp.zeros((1,), f32)])
            lo_pad = jnp.concatenate([lo_r, ninf1])
            mv &= load_pad[src_plane] - own_r >= lo_pad[src_plane] - _EPS
    if guards.replica_band:
        rl, _ru = count_limits(derived.avg_replicas,
                               constraint.replica_balance_threshold)
        reps_pad = jnp.concatenate([derived.broker_replicas.astype(f32),
                                    jnp.zeros((1,), f32)])
        rlo_pad = jnp.concatenate([jnp.broadcast_to(rl, (b,)), ninf1])
        mv &= reps_pad[src_plane] - 1.0 >= rlo_pad[src_plane] - _EPS
    if guards.leader_band:
        lead_plane = is_leader_slot(state)
        ll, _lu = count_limits(derived.avg_leaders,
                               constraint.leader_replica_balance_threshold)
        leads_pad = jnp.concatenate([derived.broker_leaders.astype(f32),
                                     jnp.zeros((1,), f32)])
        llo_pad = jnp.concatenate([jnp.broadcast_to(ll, (b,)), ninf1])
        mv &= (~lead_plane) \
            | (leads_pad[src_plane] - 1.0 >= llo_pad[src_plane] - _EPS)
    cell = jnp.where(mv, group * (b + 1) + src_plane,
                     g_dim * (b + 1)).astype(jnp.int32)
    weight = replica_load_total(state)
    sc, _sk, si = jax.lax.sort(
        (cell.reshape(-1), weight.reshape(-1),
         jnp.arange(n, dtype=jnp.int32)), num_keys=2)
    rank_cell = _segment_rank(sc)
    sur_pad = jnp.concatenate([surplus, jnp.zeros((g_dim, 1), f32)],
                              axis=1).reshape(-1)
    sur_pad = jnp.concatenate([sur_pad, jnp.zeros((1,), f32)])
    mover = rank_cell.astype(f32) < sur_pad[sc]

    # --- cumsum rank-assignment over the [deficit | headroom] profile ----
    grp_key = sc // (b + 1)                     # sorted; sentinel = g_dim
    grp = jnp.minimum(grp_key, g_dim - 1)
    rank_grp = _segment_exclusive(grp_key, mover.astype(jnp.int32))
    # Per-sweep cyclic rotation within each group's position space: a
    # bijection on [0, total), so position uniqueness (and therefore every
    # cell's integer intake bound) is preserved; out-of-range ranks stay
    # put and keep their profile-overflow invalidity.
    tot_pos = (defi + headr).sum(axis=1)                           # [G]
    t_g = tot_pos[grp]
    rank_f = rank_grp.astype(f32)
    # Golden-ratio stride: consecutive profile positions usually belong
    # to the SAME broker (a deficit of d occupies d adjacent positions),
    # so a +1 rotation retries the same vetoed destination; a
    # ~0.618·total jump lands on a different broker almost every sweep.
    offs = jnp.floor(jnp.asarray(sweep, f32) * 0.6180339887 * t_g)
    rank_f = jnp.where(rank_f < t_g,
                       jnp.mod(rank_f + offs, jnp.maximum(t_g, 1.0)),
                       rank_f)
    dst, ok = deficit_fill_dests(grp, rank_f, defi, headr, elig_dst)
    ok &= mover

    # --- structural feasibility ------------------------------------------
    p_m = si // s
    s_m = si % s
    src = (sc % (b + 1)).astype(jnp.int32)
    ok &= dst != jnp.minimum(src, b - 1)
    assign_p = state.assignment[p_m]                           # [N, S]
    ok &= ~(assign_p == dst[:, None]).any(axis=1)
    is_lead = state.leader_slot[p_m] == s_m
    ok &= (~is_lead) | derived.allowed_leadership[dst]
    not_me = jnp.arange(s, dtype=jnp.int32)[None, :] != s_m[:, None]
    if guards.rack:
        rack_pad = jnp.concatenate([state.rack, state.rack[:1]])
        slot_racks = jnp.where(assign_p >= 0,
                               rack_pad[jnp.clip(assign_p, 0, b - 1)], -1)
        dst_rack = state.rack[dst]
        ok &= ~((slot_racks == dst_rack[:, None]) & not_me
                & (assign_p >= 0)).any(axis=1)

    # --- same-sweep sibling dedup via planned-destination planes ---------
    # ``si`` is a permutation of the replica axis, so one scatter writes
    # every slot exactly once; a mover is vetoed when an EARLIER (lower
    # sorted position) sibling of its partition claims the same broker —
    # or, under the rack guard, the same rack.
    pos = jnp.arange(n, dtype=jnp.int32)
    sel0 = mover & ok
    planned_dst = jnp.zeros((p, s), jnp.int32).at[p_m, s_m].set(
        jnp.where(sel0, dst, -1))
    planned_pri = jnp.zeros((p, s), jnp.int32).at[p_m, s_m].set(
        jnp.where(sel0, pos, n))
    others_dst = planned_dst[p_m]                              # [N, S]
    others_pri = planned_pri[p_m]
    earlier = not_me & (others_pri < pos[:, None])
    ok &= ~((others_dst == dst[:, None]) & earlier).any(axis=1)
    if guards.rack:
        others_rack = jnp.where(others_dst >= 0,
                                rack_pad[jnp.clip(others_dst, 0, b - 1)], -2)
        ok &= ~((others_rack == dst_rack[:, None]) & earlier).any(axis=1)

    sel = mover & ok
    # Per-mover load vector: a moving leader carries its leader load
    # (leadership travels with the slot), a follower its follower load.
    load_vec = jnp.where(is_lead[:, None], state.leader_load[p_m],
                         state.follower_load[p_m])              # [N, R]

    # --- prior-goal guards: dst-sorted joint caps ------------------------
    dst_caps = (guards.replica_cap or guards.replica_band
                or guards.leader_band or guards.resources
                or guards.cap_resources or guards.pot_nw_out)
    if dst_caps:
        dkey = jnp.where(sel, dst, b)
        ds, _dp, d_i = jax.lax.sort((dkey, pos, pos), num_keys=2)
        sel_d = sel[d_i]
        one_d = sel_d.astype(f32)
        okd = jnp.ones(n, bool)
        inf1 = jnp.full((1,), jnp.inf, f32)
        if guards.replica_cap or guards.replica_band:
            reps = derived.broker_replicas.astype(f32)
            cap_b = jnp.full((b,), jnp.inf, f32)
            if guards.replica_band:
                _rl, ru = count_limits(derived.avg_replicas,
                                       constraint.replica_balance_threshold)
                cap_b = jnp.minimum(cap_b, ru - reps)
            if guards.replica_cap:
                cap_b = jnp.minimum(
                    cap_b, constraint.max_replicas_per_broker - reps)
            pre_cnt = _segment_exclusive(ds, one_d)
            okd &= pre_cnt + 1.0 <= jnp.concatenate([cap_b, inf1])[ds] + _EPS
        if guards.leader_band:
            lead_d = (is_lead[d_i] & sel_d).astype(f32)
            _ll, lu = count_limits(derived.avg_leaders,
                                   constraint.leader_replica_balance_threshold)
            lcap = jnp.concatenate(
                [lu - derived.broker_leaders.astype(f32), inf1])
            pre_lead = _segment_exclusive(ds, lead_d)
            okd &= (lead_d == 0) | (pre_lead + 1.0 <= lcap[ds] + _EPS)
        if guards.resources or guards.cap_resources:
            okd_load, _pre = _dst_load_caps(ds, load_vec[d_i] * sel_d[:, None],
                                            state, derived, constraint,
                                            guards)
            okd &= okd_load
        if guards.pot_nw_out:
            r = int(Resource.NW_OUT)
            pot_own = state.leader_load[p_m, r][d_i] * one_d
            pre_pot = _segment_exclusive(ds, pot_own)
            limit = constraint.capacity_threshold[r] * state.capacity[:, r]
            lim_pad = jnp.concatenate([limit, inf1])
            pt_pad = jnp.concatenate([derived.pot_nw_out,
                                      jnp.zeros((1,), f32)])
            # The reference's escape hatch (PotentialNwOutGoal
            # .actionAcceptance): a move whose SOURCE already violates
            # its potential limit is tolerated — without it, a cluster
            # whose potential exceeds limits everywhere (the goal
            # violated at entry, e.g. the 1k/100k fixture at 140k
            # residual) vetoes EVERY transport move forever.
            src_pot = jnp.concatenate([derived.pot_nw_out,
                                       jnp.zeros((1,), f32)])
            src_lim = jnp.concatenate([limit, inf1])
            src_d = jnp.minimum(src[d_i], b)
            src_viol = src_pot[src_d] > src_lim[src_d] + _EPS
            okd &= (pt_pad[ds] + pre_pot + pot_own
                    <= lim_pad[ds] + _EPS) | src_viol
        sel &= jnp.zeros(n, bool).at[d_i].set(okd)

    # --- prior-goal guards: src-sorted joint floors ----------------------
    src_floors = (guards.replica_band or guards.leader_band
                  or guards.resources)
    if src_floors:
        skey = jnp.where(sel, src, b)
        ss, _sp, s_i = jax.lax.sort((skey, pos, pos), num_keys=2)
        sel_s = sel[s_i]
        one_s = sel_s.astype(f32)
        oks = jnp.ones(n, bool)
        ninf1 = jnp.full((1,), -jnp.inf, f32)
        out_rank = _segment_exclusive(ss, one_s)
        if guards.replica_band:
            rl, _ru = count_limits(derived.avg_replicas,
                                   constraint.replica_balance_threshold)
            reps_pad = jnp.concatenate(
                [derived.broker_replicas.astype(f32),
                 jnp.zeros((1,), f32)])
            floor_pad = jnp.concatenate([jnp.broadcast_to(rl, (b,)), ninf1])
            oks &= reps_pad[ss] - out_rank - 1.0 >= floor_pad[ss] - _EPS
        if guards.leader_band:
            lead_s = (is_lead[s_i] & sel_s).astype(f32)
            ll, _lu = count_limits(derived.avg_leaders,
                                   constraint.leader_replica_balance_threshold)
            leads_pad = jnp.concatenate(
                [derived.broker_leaders.astype(f32), jnp.zeros((1,), f32)])
            lfloor = jnp.concatenate([jnp.broadcast_to(ll, (b,)), ninf1])
            pre_lead_out = _segment_exclusive(ss, lead_s)
            oks &= (lead_s == 0) \
                | (leads_pad[ss] - pre_lead_out - 1.0 >= lfloor[ss] - _EPS)
        if guards.resources:
            oks &= _src_load_floors(ss, load_vec[s_i] * sel_s[:, None],
                                    state, derived, constraint, guards)
        sel &= jnp.zeros(n, bool).at[s_i].set(oks)

    # --- per-(topic, broker) band of a PRIOR topic goal ------------------
    if guards.topic_band and not isinstance(goal,
                                            TopicReplicaDistributionGoal):
        tb = topic_broker_replica_counts(state, num_topics).astype(f32)
        n_alive = jnp.maximum(alive.sum(), 1)
        t_avg = (tb * alive[None, :]).sum(axis=1) / n_alive
        t_up = jnp.ceil(t_avg * constraint.topic_replica_balance_threshold)
        t_lo = jnp.floor(t_avg / constraint.topic_replica_balance_threshold)
        topic_m = state.topic[p_m]
        # dst side: joint intake per (topic, dst) cell must stay under the
        # prior topic band's upper.
        tdkey = jnp.where(sel, topic_m * (b + 1) + dst,
                          num_topics * (b + 1)).astype(jnp.int32)
        ts, _tp, t_i = jax.lax.sort((tdkey, pos, pos), num_keys=2)
        sel_t = sel[t_i].astype(f32)
        pre_td = _segment_exclusive(ts, sel_t)
        tb_pad = jnp.concatenate([tb, jnp.zeros((num_topics, 1), f32)],
                                 axis=1).reshape(-1)
        tb_pad = jnp.concatenate([tb_pad, jnp.zeros((1,), f32)])
        up_flat = jnp.concatenate(
            [jnp.broadcast_to(t_up[:, None], (num_topics, b + 1)).reshape(-1),
             jnp.full((1,), jnp.inf, f32)])
        okt = (sel_t == 0) \
            | (tb_pad[ts] + pre_td + 1.0 <= up_flat[ts] + _EPS)
        sel &= jnp.zeros(n, bool).at[t_i].set(okt)
        # src side: joint outflow per (topic, src) must stay at/above the
        # prior topic band's lower.
        tskey = jnp.where(sel, topic_m * (b + 1) + src,
                          num_topics * (b + 1)).astype(jnp.int32)
        ts2, _tp2, t2_i = jax.lax.sort((tskey, pos, pos), num_keys=2)
        sel_t2 = sel[t2_i].astype(f32)
        pre_ts = _segment_exclusive(ts2, sel_t2)
        lo_flat = jnp.concatenate(
            [jnp.broadcast_to(t_lo[:, None], (num_topics, b + 1)).reshape(-1),
             jnp.full((1,), -jnp.inf, f32)])
        okt2 = (sel_t2 == 0) \
            | (tb_pad[ts2] - pre_ts - 1.0 >= lo_flat[ts2] - _EPS)
        sel &= jnp.zeros(n, bool).at[t2_i].set(okt2)

    # --- one-shot scatter apply ------------------------------------------
    rows = jnp.where(sel, p_m, p)
    new_assignment = state.assignment.at[rows, s_m].set(
        dst.astype(state.assignment.dtype), mode="drop")
    return (dataclasses.replace(state, assignment=new_assignment),
            sel.sum().astype(jnp.int32),
            mover.sum().astype(jnp.int32))


def _sweep_fn(goals: tuple[Goal, ...], index: int):
    """Leader-count goals transport LEADERSHIP (sibling re-election);
    every other count goal transports replicas. Trace-time dispatch."""
    g = goals[index]
    if isinstance(g, CountDistributionGoal) and g.leaders:
        return _leadership_sweep
    return _direct_sweep


def _stall_limit(goals: tuple[Goal, ...], index: int) -> int:
    """Consecutive zero-apply sweeps tolerated before the loop gives the
    residue up to the greedy polish. The replica transports re-pair
    vetoed movers by rotation, so a zero-apply sweep can still unlock
    the next one — give rotation a few chances; the leadership sweep
    has no rotation (its destination menu is the partition's own
    siblings), so a zero-apply sweep would recompute a byte-identical
    plan forever — exit on the first."""
    return 1 if _sweep_fn(goals, index) is _leadership_sweep else 3


def _direct_rounds_driver(state: ClusterTensors, goals: tuple[Goal, ...],
                          index: int, constraint: BalancingConstraint,
                          num_topics: int, masks: ExclusionMasks,
                          max_sweeps: int):
    """Sweep loop (traced): unlike the greedy megastep's zero-APPLY exit,
    the direct loop keeps sweeping while the plan still has MOVERS —
    a sweep whose every pairing was feasibility-vetoed applies nothing,
    but the next sweep's rotation can re-pair the residue. A bounded
    zero-apply STREAK (``_stall_limit``) still ends a stalled loop: a
    structurally-stuck residue must fall to the greedy polish, not burn
    the whole ``max_sweeps`` budget recomputing vetoed plans."""
    if not direct_eligible(goals, index):   # trace-time guard
        raise ValueError(
            f"goal {goals[index].name} / chain prefix not direct-eligible "
            "(see direct_eligible)")
    sweep_fn = _sweep_fn(goals, index)
    stall = _stall_limit(goals, index)

    def cond(c):
        _st, _tot, i, planned, zeros = c
        return (planned > 0) & (i < max_sweeps) & (zeros < stall)

    def body(c):
        st, tot, i, _planned, zeros = c
        ns, applied, planned = sweep_fn(st, goals, index, constraint,
                                        num_topics, masks, sweep=i)
        zeros = jnp.where(applied > 0, jnp.int32(0), zeros + 1)
        return ns, tot + applied, i + 1, planned, zeros

    final, total, sweeps, planned, _z = jax.lax.while_loop(
        cond, body,
        (state, jnp.int32(0), jnp.int32(0), jnp.int32(1), jnp.int32(0)))
    # ``planned`` at exit = movers the plan still wanted but could not
    # place (0 when the transport fully converged): the caller's honest
    # residue signal for sizing the greedy polish.
    return final, total, sweeps, planned


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps"))
def direct_transport_rounds(state: ClusterTensors, goals: tuple[Goal, ...],
                            index: int, constraint: BalancingConstraint,
                            num_topics: int, masks: ExclusionMasks,
                            max_sweeps: int = 8):
    """The direct-assignment solve for ``goals[index]`` under the guards
    of ``goals[:index]``: up to ``max_sweeps`` transport sweeps inside
    ONE ``lax.while_loop`` dispatch (a stalled loop ends on device).
    Returns (final_state, moves_applied, sweeps_run, movers_stranded)."""
    return _direct_rounds_driver(state, goals, index, constraint,
                                 num_topics, masks, max_sweeps)


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps"),
         donate_argnums=(0, 1))
def direct_transport_rounds_donated(assignment: jax.Array,
                                    leader_slot: jax.Array,
                                    rest: ClusterTensors,
                                    goals: tuple[Goal, ...], index: int,
                                    constraint: BalancingConstraint,
                                    num_topics: int, masks: ExclusionMasks,
                                    max_sweeps: int = 8):
    """Donated twin (identical trace): callers pass
    ``chain.strip_mutable(state)`` as ``rest`` and relinquish the two
    mutable tensors — the donation set is exactly the strip_mutable pair,
    nothing else (CCSA002)."""
    state = dataclasses.replace(rest, assignment=assignment,
                                leader_slot=leader_slot)
    final, total, sweeps, planned = _direct_rounds_driver(
        state, goals, index, constraint, num_topics, masks, max_sweeps)
    return final.assignment, final.leader_slot, total, sweeps, planned


# ---------------------------------------------------------------------------
# Megabatch twins: whole buckets of clusters, one direct program
# ---------------------------------------------------------------------------

def _megabatch_direct_driver(states: ClusterTensors, active0: jax.Array,
                             goals: tuple[Goal, ...], index: int,
                             constraint: BalancingConstraint,
                             num_topics: int, masks: ExclusionMasks,
                             max_sweeps: int):
    """Batched sweep loop with the megabatch freeze discipline: an
    inactive cluster's whole state is frozen by a select, so a pad slot
    (or a cluster whose plan converged) stays byte-identical while its
    batchmates keep sweeping — one compiled program per bucket shape
    serves any occupancy (occupancy is traced, never a new compile)."""
    if not direct_eligible(goals, index):   # trace-time guard
        raise ValueError(
            f"goal {goals[index].name} / chain prefix not direct-eligible "
            "(see direct_eligible)")
    c = states.assignment.shape[0]
    fields = (masks.excluded_topics, masks.excluded_replica_move_brokers,
              masks.excluded_leadership_brokers)
    ax = tuple(None if f is None else 0 for f in fields)

    sweep_fn = _sweep_fn(goals, index)
    stall = _stall_limit(goals, index)

    def per_cluster(st, tm, rm, lm, i):
        return sweep_fn(st, goals, index, constraint, num_topics,
                        ExclusionMasks(tm, rm, lm), sweep=i)

    vsweep = jax.vmap(per_cluster, in_axes=(0,) + ax + (None,))

    def cond(carry):
        _st, _tot, _swp, i, active, _z = carry
        return active.any() & (i < max_sweeps)

    def body(carry):
        st, tot, swp, i, active, zeros = carry
        nst, applied, planned = vsweep(st, *fields, i)

        def keep(new, old):
            k = active.reshape((c,) + (1,) * (new.ndim - 1))
            return jnp.where(k, new, old)

        st = jax.tree.map(keep, nst, st)
        applied = jnp.where(active, applied, 0).astype(jnp.int32)
        zeros = jnp.where(active & (applied == 0), zeros + 1,
                          jnp.where(active, 0, zeros))
        return (st, tot + applied, swp + active.astype(jnp.int32), i + 1,
                active & (planned > 0) & (zeros < stall), zeros)

    final, total, sweeps, _i, active, _z = jax.lax.while_loop(
        cond, body,
        (states, jnp.zeros((c,), jnp.int32), jnp.zeros((c,), jnp.int32),
         jnp.int32(0), active0, jnp.zeros((c,), jnp.int32)))
    return final, total, sweeps, active


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps"))
def megabatch_direct_rounds(states: ClusterTensors, active0: jax.Array,
                            goals: tuple[Goal, ...], index: int,
                            constraint: BalancingConstraint,
                            num_topics: int, masks: ExclusionMasks,
                            max_sweeps: int = 8):
    """Batched direct solve over a leading cluster axis. Returns
    (states, moves[C], sweeps[C], active_out[C])."""
    return _megabatch_direct_driver(states, active0, goals, index,
                                    constraint, num_topics, masks,
                                    max_sweeps)


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps"),
         donate_argnums=(0, 1))
def megabatch_direct_rounds_donated(assignment: jax.Array,
                                    leader_slot: jax.Array,
                                    rest: ClusterTensors, active0: jax.Array,
                                    goals: tuple[Goal, ...], index: int,
                                    constraint: BalancingConstraint,
                                    num_topics: int, masks: ExclusionMasks,
                                    max_sweeps: int = 8):
    """Donated batched twin: donation set is exactly the strip_mutable
    pair grown a cluster axis ``{assignment[C,P,S], leader_slot[C,P]}``
    (CCSA002); the stacked topology planes in ``rest`` are
    refresh-cache-shared and never donated."""
    states = dataclasses.replace(rest, assignment=assignment,
                                 leader_slot=leader_slot)
    final, total, sweeps, active = _megabatch_direct_driver(
        states, active0, goals, index, constraint, num_topics, masks,
        max_sweeps)
    return final.assignment, final.leader_slot, total, sweeps, active


# ---------------------------------------------------------------------------
# Host-side pass driver
# ---------------------------------------------------------------------------

def run_direct_pass(state: ClusterTensors, goals, index: int,
                    constraint: BalancingConstraint, num_topics: int,
                    masks: ExclusionMasks, megastep, max_sweeps: int,
                    stats=None, flight=None, donate_input: bool = False):
    """Fire the direct solve as ONE device dispatch and read its scalars
    back synchronously (there is nothing to pipeline behind a single
    dispatch). Donation follows the megastep discipline: the first
    mutating dispatch either consumes the caller's buffers
    (``donate_input``) or donates a device COPY of the two mutable
    tensors; the flight record and dispatch stats land under
    ``kind="direct"`` so solver_dispatches{kind="direct"} is its own
    series and the acceptance-density histogram (defined only for greedy
    move dispatches on a recorded grid) never sees these.

    Returns (state, moves, sweeps, donated, stranded) — ``stranded`` is
    the mover count the plan still wanted but could not place at exit
    (the caller's residue signal for sizing the greedy polish)."""
    import time as _time

    from ..utils.sensors import SENSORS
    from .chain import donation_enabled, strip_mutable
    goals = tuple(goals)
    donate = donation_enabled(megastep)
    t0 = _time.monotonic()
    if donate:
        if not donate_input:
            state = dataclasses.replace(
                state, assignment=jnp.copy(state.assignment),
                leader_slot=jnp.copy(state.leader_slot))
        a, l, total, sweeps, planned = direct_transport_rounds_donated(
            state.assignment, state.leader_slot, strip_mutable(state),
            goals, index, constraint, num_topics, masks, max_sweeps)
        state = dataclasses.replace(state, assignment=a, leader_slot=l)
    else:
        state, total, sweeps, planned = direct_transport_rounds(
            state, goals, index, constraint, num_topics, masks, max_sweeps)
    moves = int(total)
    sweeps_run = int(sweeps)
    stranded = int(planned)
    elapsed = _time.monotonic() - t0
    if stats is not None:
        stats.record("direct", sweeps_run, donated=donate)
    if flight is not None:
        flight.dispatch("direct", max_sweeps, sweeps_run, moves,
                        donated=donate, elapsed_s=elapsed)
    SENSORS.count("solver_direct_sweeps", sweeps_run)
    SENSORS.count("solver_direct_moves", moves)
    return state, moves, sweeps_run, donate, stranded
