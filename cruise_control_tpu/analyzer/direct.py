"""Direct-assignment transport kernels for the count-distribution goals.

The greedy search pays for a count imbalance in ROUNDS: each round
scores a top-k grid, accepts a conflict-free batch, and re-dispatches —
at the 7k-broker/1M-partition north star TopicReplicaDistributionGoal
alone burns hundreds of acceptance-density-limited rounds shedding ~980
moves each (ROADMAP item 1). But a count goal's fixed point is KNOWN in
closed form: the per-broker (or per-topic×broker) target band is a pure
function of the counts, so the whole solve is a transport problem —
surplus replicas → deficit slots — not a search problem. This module
solves that transport as a vectorized matching in one (or a few) device
dispatches (the Podracer/Anakin "stop iterating" lever):

1. **Target counts on device**: the active goal's count plane
   ``[G, B]`` and band ``[lower, upper]`` (``G`` = 1 for the
   replica/leader goals, ``num_topics`` for the topic goal), as
   FRACTIONAL per-cell shed/fill targets resolved to integers by
   deterministic randomized rounding (round 21: one plan for every
   density regime — see ``_surplus_deficit``), with proportional donor
   widening when deficits exceed base surplus.
2. **Surplus replica selection**: ONE segmented sort of the flattened
   replica axis by ``(cell, weight)`` — cell = (group, src broker) —
   ranks every replica within its cell; the ``surplus[cell]`` lightest
   movable replicas are the movers (light-first, matching the greedy's
   ``replica_weight``).
3. **Cumsum rank-assignment**: each mover's rank within its group maps
   through the group's cumulative ``[deficit | headroom]`` profile
   (``analyzer.fill.deficit_fill_dests`` — the same kernel the targeted
   destination column uses per-card) to a destination broker, so the
   joint assignment respects every cell's integer gap by construction.
4. **Feasibility masking**: RF-sibling exclusion (destination must not
   already host the partition — nor receive two siblings in one
   sweep), rack-awareness when a rack goal is stacked prior, dead
   brokers, per-request exclusion options, the new-broker gate, and
   leadership-excluded destinations for leader movers.
5. **Prior-goal guards**: destination caps and source floors of every
   previously-optimized goal (replica-capacity / count bands / resource
   bands / capacity thresholds / potential NW-out), evaluated JOINTLY
   via dst-/src-sorted segmented exclusive cumsums — the
   ``attach_cumulative`` pre-delta contract at O(n log n) instead of
   O(m²), with the same conservative-overcount semantics (a vetoed
   earlier mover still shifts later movers' checks, which can only make
   them stricter).
6. **One-shot scatter apply**: all surviving movers land in a single
   functional scatter; a small on-device sweep loop (``max_sweeps``)
   re-runs the plan on the updated counts until nothing moves, so
   feasibility-vetoed leftovers get a second pairing without a host
   round-trip.

Anything the transport cannot place (structurally-blocked residue)
stays for the greedy polish pass that follows — the kernel REPLACES the
deficit-sized bulk rounds, not the acceptance machinery's judgment.

Safety discipline (two prior density "fixes" silently flipped the
86.0 → 82.74 CpuUsageDistribution canary and were reverted): the kernel
ships behind ``solver.direct.assignment.enabled`` (default OFF), only
activates in the wide regime (``solver.wide.batch.min.brokers``) where
deficit-sized greedy ran before, refuses chains whose prior goals it
cannot guard (``direct_eligible``), and is gated on the bench
regression sentry + full fixture matrix, never on round counts.

SPMD layout (round 21): every rank the plan assigns — within-cell
mover ranks, group fill ranks, per-destination intake positions,
per-source outflow positions — is parameterized by
``(rank_stride, block)``: a replica on block ``d`` with local rank
``r`` occupies global position ``r·stride + d``. On the partition-
sharded mesh each device passes its shard index as ``block`` and the
shard count as ``rank_stride``, so device-local sorts yield globally
unique positions without a global sort (the ``target_dests``
interleaved-fill treatment, generalized to the whole plan). Load-sum
guards cannot interleave (per-mover loads are heterogeneous), so each
block is budgeted ``1/stride`` of the remaining headroom —
conservative, never unsafe. ``rank_stride == 1`` (every single-device
caller) is byte-identical to the unparameterized plan.

Donation contract: the donated twins donate EXACTLY the strip_mutable
pair ``{assignment, leader_slot}`` (CCSA002-checked); topology tensors
are refresh-cache-shared and never donated.
"""

from __future__ import annotations

import dataclasses
import zlib
from functools import partial

import jax
import jax.numpy as jnp

from ..common.resources import Resource
from ..model.tensors import (
    ClusterTensors, is_leader_slot, replica_load_total,
    topic_broker_replica_counts,
)
from .constraint import BalancingConstraint
from .derived import compute_derived, count_limits, resource_limits
from .fill import deficit_fill_dests
from .goals.base import Goal
from .goals.capacity import ReplicaCapacityGoal, ResourceCapacityGoal
from .goals.distribution import (
    CountDistributionGoal, PotentialNwOutGoal, TopicReplicaDistributionGoal,
)
from .goals.rack import RackAwareGoal
from .search import ExclusionMasks, goal_aux

_EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class DirectGuards:
    """Static (trace-time) description of the prior-goal constraints the
    transport plan must respect — computed from the chain prefix, one
    flag/tuple per constraint family the feasibility pass knows how to
    model."""

    rack: bool = False              # strict sibling-rack exclusion
    replica_cap: bool = False       # ReplicaCapacityGoal hard cap
    replica_band: bool = False      # per-broker replica-count band
    leader_band: bool = False       # per-broker leader-count band
    topic_band: bool = False        # per-(topic, broker) count band
    resources: tuple[int, ...] = ()      # distribution bands (upper+lower)
    cap_resources: tuple[int, ...] = ()  # hard capacity thresholds
    pot_nw_out: bool = False        # potential NW-out limit


def _guards_for(goals: tuple[Goal, ...], index: int) -> DirectGuards:
    priors = goals[:index]
    from .goals.distribution import ResourceDistributionGoal
    return DirectGuards(
        rack=any(isinstance(g, RackAwareGoal) for g in priors),
        replica_cap=any(isinstance(g, ReplicaCapacityGoal) for g in priors),
        replica_band=any(isinstance(g, CountDistributionGoal)
                         and not g.leaders for g in priors),
        leader_band=any(isinstance(g, CountDistributionGoal)
                        and g.leaders for g in priors),
        topic_band=any(isinstance(g, TopicReplicaDistributionGoal)
                       for g in priors),
        resources=tuple(sorted({int(g.resource) for g in priors
                                if isinstance(g, ResourceDistributionGoal)})),
        cap_resources=tuple(sorted({int(g.resource) for g in priors
                                    if isinstance(g, ResourceCapacityGoal)})),
        pot_nw_out=any(isinstance(g, PotentialNwOutGoal) for g in priors))


def direct_eligible(goals, index: int) -> bool:
    """True when ``goals[index]`` has a direct transport formulation AND
    every prior goal's acceptance is representable by the guard set —
    an unrecognized prior (broker sets, kafka-assigner variants, custom
    plugins) means the plan could silently violate a constraint the
    greedy's lexicographic stack would have vetoed, so the caller must
    keep the greedy path (the conservative fallback is the contract)."""
    from .goals.distribution import ResourceDistributionGoal
    goal = goals[index]
    if not getattr(goal, "supports_direct", False):
        return False
    recognized = (RackAwareGoal, ReplicaCapacityGoal, ResourceCapacityGoal,
                  CountDistributionGoal, TopicReplicaDistributionGoal,
                  PotentialNwOutGoal, ResourceDistributionGoal)
    return all(isinstance(g, recognized) for g in goals[:index])


# ---------------------------------------------------------------------------
# Deterministic randomized rounding (the sparse-plan PRNG, CCSA004)
# ---------------------------------------------------------------------------

#: Trace-time crc32-derived seed of the rounding PRNG — the repo's
#: approved deterministic-seeding idiom (lint CCSA004: no host RNG, no
#: clocks, no builtin hash()). Callers may override it with a crc32 of
#: ``solver.direct.sparse.rounding.salt`` so fleets can decorrelate
#: replays without breaking byte-determinism within one configuration.
SPARSE_ROUNDING_SEED = zlib.crc32(b"cruise-control:direct.sparse.rounding")
_SALT_SURPLUS = zlib.crc32(b"direct.sparse.plane:surplus")
_SALT_HEADROOM = zlib.crc32(b"direct.sparse.plane:headroom")


def sparse_rounding_seed(salt: str = "") -> int:
    """The rounding seed for a configured salt string
    (``solver.direct.sparse.rounding.salt``): empty → the module
    default; otherwise crc32 of the salt folded over it. Host-side,
    trace-time only — the value enters the kernels as a static."""
    if not salt:
        return SPARSE_ROUNDING_SEED
    return SPARSE_ROUNDING_SEED ^ zlib.crc32(salt.encode("utf-8"))


def _hash_uniform(idx: jax.Array, sweep, salt: int) -> jax.Array:
    """Deterministic per-index uniforms in [0, 1): a splitmix-style
    integer finalizer over (index, sweep, trace-time crc32 salt) — pure
    jnp on uint32, so the draw replays byte-identically on device with
    no host RNG in the loop (the CCSA004 contract)."""
    x = idx.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    x = x + jnp.asarray(sweep, jnp.uint32) * jnp.uint32(0x85EBCA77)
    x = x + jnp.uint32(salt & 0xFFFFFFFF)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return jnp.minimum(x.astype(jnp.float32) * jnp.float32(2.0 ** -32),
                       jnp.float32(1.0 - 1e-7))


def _round_systematic(x: jax.Array, u: jax.Array) -> jax.Array:
    """Systematic (low-discrepancy) randomized rounding along the broker
    axis: ``x`` [G, B] non-negative fractional targets, ``u`` [G]
    uniforms. ``T[g, b] = ⌊cum[b] + u⌋ − ⌊cum[b−1] + u⌋ ∈ {⌊x⌋, ⌈x⌉}``
    with ``E[T] = x`` exactly and ``|Σ_b T − Σ_b x| < 1`` per group —
    expected counts match the fractional band math, and a group's
    realized total stays within one replica of it (independent
    per-cell Bernoulli draws would drift by O(√B)). Integral inputs
    pass through unchanged, so the dense regime keeps its exact
    plans."""
    c = jnp.cumsum(x, axis=1)
    y = jnp.floor(c + u[:, None])
    return jnp.diff(y, axis=1, prepend=0.0)


# ---------------------------------------------------------------------------
# Segmented helpers over a key-sorted axis
# ---------------------------------------------------------------------------

def _segment_starts(keys: jax.Array) -> jax.Array:
    """[N] bool — first element of each equal-key run (keys sorted)."""
    return jnp.concatenate([jnp.ones((1,), bool), keys[1:] != keys[:-1]])


def _segment_rank(keys: jax.Array) -> jax.Array:
    """[N] int32 — position within the element's equal-key run."""
    pos = jnp.arange(keys.shape[0], dtype=jnp.int32)
    start = jax.lax.cummax(jnp.where(_segment_starts(keys), pos, 0))
    return pos - start


def _segment_exclusive(keys: jax.Array, values: jax.Array) -> jax.Array:
    """Exclusive within-segment cumsum of NON-NEGATIVE ``values`` ([N] or
    [N, R]) over a key-sorted axis. Non-negativity makes the running
    total monotone, so each segment's base is recoverable by a cummax of
    the totals pinned at segment starts — no scatter, no scan."""
    cum_ex = jnp.cumsum(values, axis=0) - values
    starts = _segment_starts(keys)
    if values.ndim == 2:
        starts = starts[:, None]
    base = jax.lax.cummax(jnp.where(starts, cum_ex, jnp.zeros_like(cum_ex)),
                          axis=0)
    return cum_ex - base


# ---------------------------------------------------------------------------
# The sweep bodies (traced)
# ---------------------------------------------------------------------------

def _dst_load_caps(ds, lv_d, state, derived, constraint,
                   guards: DirectGuards, ds_b=None, share: float = 1.0):
    """Joint per-resource upper-band + hard-capacity caps at the
    destination, in the dst-sorted frame (``lv_d`` is each mover's load
    vector already masked to selected movers). Shared by BOTH transport
    modes so the prior-goal contract cannot drift between them.

    ``ds`` is the SEGMENT key (at ``rank_stride > 1`` a composite
    ``dst·stride + block``); ``ds_b`` the broker index it maps to, and
    ``share`` the stride: load sums cannot interleave like count ranks
    (per-mover loads are heterogeneous), so each block is budgeted
    ``1/stride`` of the destination's remaining headroom — a block's
    inflow scaled by ``stride`` must fit the full headroom. Conservative
    (joint overshoot impossible; unbalanced blocks under-use the cap and
    re-pair next sweep), exact at stride 1. Returns
    (okd [N] bool, pre_load [N, R])."""
    f32 = jnp.float32
    n = ds.shape[0]
    ds_b = ds if ds_b is None else ds_b
    okd = jnp.ones(n, bool)
    inf1 = jnp.full((1,), jnp.inf, f32)
    pre_load = _segment_exclusive(ds, lv_d)
    for r in guards.resources:
        _lo, up_r, _c = resource_limits(state, derived, constraint,
                                        Resource(r))
        up_pad = jnp.concatenate([up_r, inf1])
        dl_pad = jnp.concatenate([derived.broker_load[:, r],
                                  jnp.zeros((1,), f32)])
        okd &= dl_pad[ds_b] + (pre_load[:, r] + lv_d[:, r]) * share \
            <= up_pad[ds_b] + _EPS
    for r in guards.cap_resources:
        limit = constraint.capacity_threshold[r] * state.capacity[:, r]
        lim_pad = jnp.concatenate([limit, inf1])
        dl_pad = jnp.concatenate([derived.broker_load[:, r],
                                  jnp.zeros((1,), f32)])
        okd &= dl_pad[ds_b] + (pre_load[:, r] + lv_d[:, r]) * share \
            <= lim_pad[ds_b] + _EPS
    return okd, pre_load


def _src_load_floors(ss, lv_s, state, derived, constraint,
                     guards: DirectGuards, ss_b=None, share: float = 1.0):
    """Joint per-resource lower-band floors at the source, in the
    src-sorted frame (``lv_s`` is each mover's OUTBOUND load vector
    masked to selected movers): cumulative outflow must not take the
    source below a previously-optimized resource goal's lower band (the
    greedy's stays-in-band source arm). Shared by both transport modes.
    ``ss``/``ss_b``/``share`` follow the ``_dst_load_caps`` stride
    contract (each block budgeted ``1/stride`` of the floor
    headroom)."""
    f32 = jnp.float32
    n = ss.shape[0]
    ss_b = ss if ss_b is None else ss_b
    oks = jnp.ones(n, bool)
    ninf1 = jnp.full((1,), -jnp.inf, f32)
    pre_out = _segment_exclusive(ss, lv_s)
    for r in guards.resources:
        lo_r, _up, _c = resource_limits(state, derived, constraint,
                                        Resource(r))
        lo_pad = jnp.concatenate([lo_r, ninf1])
        sl_pad = jnp.concatenate([derived.broker_load[:, r],
                                  jnp.zeros((1,), f32)])
        oks &= sl_pad[ss_b] - (pre_out[:, r] + lv_s[:, r]) * share \
            >= lo_pad[ss_b] - _EPS
    return oks


def _surplus_deficit(cnt, lower, upper, alive, elig_dst, sweep=0,
                     margin_frac: float = 0.25,
                     seed: int = SPARSE_ROUNDING_SEED):
    """Integral (surplus, deficit, headroom) planes from FRACTIONAL
    per-cell targets resolved by deterministic randomized rounding —
    ONE plan for every density regime (round 21, retiring the
    ``MIN_TOPIC_CELL_DENSITY`` gate).

    The round-17 plan floored its band-edge margin and its donor room
    to integers — exact in the dense regime, but at a 1-count band
    (the sparse-cell regime: ~1.5 replicas per (topic, broker) cell at
    1k/100k and north-star scale) the floor collapsed the margin to
    zero, every touched cell landed exactly AT the band edge, donor
    widening drained in-band donors in broker-index order (packing
    low-index brokers), and the greedy polish inherited a layout it
    could not fix (measured residual ~10k vs greedy's 316). Here the
    shed target (``upper − margin``), the fill target
    (``lower + max(margin, 0.5)``) and the donor-widening shares all
    stay FRACTIONAL: a group-wide violation gap is spread across its
    in-band donors proportional to their fractional room (no
    broker-index packing), and systematic randomized rounding — one
    crc32-derived uniform per (group, plane, sweep), ``_hash_uniform``
    — resolves every fractional plane to integers with expectation
    EQUAL to the fractional band math and per-group totals within one
    replica of it. Re-drawing per sweep lets a rounding outcome that
    paired badly re-round after the counts update.

    Hard integral caps close the loop independent of the rounding: a
    source never sheds below ``lower`` (``⌊cnt − lower⌋``), a receiver
    never fills above ``upper`` (``⌊upper − cnt⌋``), so every rounding
    outcome stays inside the band by construction.

    Band-edge slack rationale (unchanged from round 17): a transport
    that parks every touched broker exactly AT a band edge leaves
    later goals zero joint slack and the greedy polish stalls in a
    worse local optimum than greedy-only (measured at 64/2048:
    TopicReplica residual 70 vs 0). Deficits are violation-sized only
    (``lower − cnt``); receivers additionally expose headroom up to
    the fill target, so inflow lands center-ward without O(B) in-band
    churn."""
    g_dim = cnt.shape[0]
    width = jnp.maximum(upper - lower, 0.0)
    margin = width * margin_frac
    hi_t = jnp.maximum(upper - margin, lower)   # fractional shed ceiling
    lo_t = jnp.minimum(lower + jnp.maximum(margin, 0.5), hi_t)  # fill target
    gidx = jnp.arange(g_dim, dtype=jnp.uint32)

    viol_dst = elig_dst[None, :] & (cnt < lower - _EPS)
    sur_f = jnp.where(alive[None, :] & (cnt > upper + _EPS),
                      jnp.maximum(cnt - hi_t, 0.0), 0.0)
    # Deficits are integral by construction (band edges and counts are
    # integers); the fractional mass lives in the shed targets and the
    # center-ward headroom below.
    defi = jnp.where(viol_dst, lower - cnt, 0.0)
    head_f = jnp.where(elig_dst[None, :],
                       jnp.maximum(lo_t - jnp.maximum(cnt, lower), 0.0), 0.0)

    # Proportional donor widening: when violation deficits exceed base
    # surplus, in-band donors cover the gap in proportion to their
    # fractional room (cnt down to the fill target) — spread across the
    # whole group instead of drained in broker-index order.
    need = jnp.maximum(defi.sum(axis=1, keepdims=True)
                       - sur_f.sum(axis=1, keepdims=True), 0.0)
    donor_room = jnp.where(alive[None, :],
                           jnp.maximum(jnp.minimum(cnt, hi_t) - lo_t, 0.0),
                           0.0)
    share = donor_room / jnp.maximum(donor_room.sum(axis=1, keepdims=True),
                                     _EPS)
    extra_f = jnp.minimum(need * share, donor_room)

    u_s = _hash_uniform(gidx, sweep, seed ^ _SALT_SURPLUS)
    u_h = _hash_uniform(gidx, sweep, seed ^ _SALT_HEADROOM)
    sur_cap = jnp.where(alive[None, :],
                        jnp.floor(jnp.maximum(cnt - lower, 0.0) + _EPS), 0.0)
    surplus = jnp.minimum(_round_systematic(sur_f + extra_f, u_s), sur_cap)
    room_cap = jnp.floor(jnp.maximum(upper - cnt, 0.0) + _EPS)
    defi = jnp.minimum(defi, room_cap)
    headr = jnp.where(elig_dst[None, :],
                      jnp.minimum(_round_systematic(head_f, u_h),
                                  jnp.maximum(room_cap - defi, 0.0)), 0.0)
    return surplus, defi, headr


def _leadership_sweep(state: ClusterTensors, goals: tuple[Goal, ...],
                      index: int, constraint: BalancingConstraint,
                      num_topics: int, masks: ExclusionMasks,
                      sweep: jax.Array | int = 0,
                      rank_stride: int = 1, block: jax.Array | int = 0,
                      psum=None, margin_frac: float = 0.25,
                      seed: int = SPARSE_ROUNDING_SEED,
                      ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """Transport sweep for the LEADER-count goal via leadership
    TRANSFERS: after the replica goals have balanced counts, a leader
    replica move is almost always vetoed by the prior replica-count band
    — the reference (and the greedy here) rebalances leader counts by
    electing a different in-sync sibling instead. Each surplus leader's
    destination menu is its partition's own sibling replicas, so the
    plan picks the best sibling broker with leader-band room and caps
    joint intake per destination; replica placement (and every
    count/rack plane) is untouched, leaving only the resource-load
    guards (leadership carries ``leader_load − follower_load``)."""
    goal = goals[index]
    guards = _guards_for(goals, index)
    ps = psum if psum is not None else (lambda x: x)
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=psum)
    aux = goal_aux(goal, state, derived, constraint, num_topics, psum=psum)
    counts, lower, upper, _group, movable = goal.direct_spec(
        state, derived, constraint, aux, num_topics)

    p, s = state.assignment.shape
    b = state.num_brokers
    n = p * s
    f32 = jnp.float32
    stride = int(rank_stride)
    str_f = f32(stride)
    alive = derived.alive
    lead_elig = derived.allowed_leadership & alive
    cnt = counts.astype(f32)
    surplus, defi, headr = _surplus_deficit(
        cnt, lower, upper, alive, lead_elig, sweep=sweep,
        margin_frac=margin_frac, seed=seed)
    room = (defi + headr)[0]                                       # [B]

    # Movers: the surplus[src] lightest leaders per over-band broker.
    # Leadership leaving a broker removes (leader_load − follower_load)
    # from it — the same dst-independent source pre-filter as the
    # replica transport: a leader whose departure ALONE would cross a
    # prior resource goal's lower band can reach no sibling at all, so
    # it must not occupy a surplus rank (negative components clamped —
    # an outflow that RAISES the source's load cannot cross a floor).
    alive_pad = jnp.concatenate([alive, jnp.zeros((1,), bool)])
    src_plane = jnp.where(state.assignment >= 0, state.assignment, b)
    mv = movable & derived.movable_partition[:, None] & alive_pad[src_plane]
    if guards.resources:
        ninf1 = jnp.full((1,), -jnp.inf, f32)
        for r in guards.resources:
            lo_r, _up_r, _c = resource_limits(state, derived, constraint,
                                              Resource(r))
            own_r = jnp.maximum(state.leader_load[:, r]
                                - state.follower_load[:, r], 0.0)[:, None]
            load_pad = jnp.concatenate([derived.broker_load[:, r],
                                        jnp.zeros((1,), f32)])
            lo_pad = jnp.concatenate([lo_r, ninf1])
            mv &= load_pad[src_plane] - own_r >= lo_pad[src_plane] - _EPS
    cell = jnp.where(mv, src_plane, b).astype(jnp.int32)
    weight = replica_load_total(state)
    if stride > 1:
        blk_rows = jnp.broadcast_to(jnp.asarray(block, jnp.int32), (p,))
        blk_plane = jnp.broadcast_to(blk_rows[:, None], (p, s))
        key0 = cell * stride + blk_plane
    else:
        key0 = cell
    sc, _sk, si = jax.lax.sort(
        (key0.reshape(-1), weight.reshape(-1),
         jnp.arange(n, dtype=jnp.int32)), num_keys=2)
    rank_cell = _segment_rank(sc)
    cell_s = sc // stride if stride > 1 else sc
    blk_s = sc % stride if stride > 1 else jnp.zeros_like(sc)
    sur_pad = jnp.concatenate([surplus[0], jnp.zeros((1,), f32)])
    mover = (rank_cell * stride + blk_s).astype(f32) < sur_pad[cell_s]

    # Destination menu = the partition's own existing sibling replicas
    # on leadership-eligible brokers with band room; best room wins
    # (deficits before headroom), ties to the lowest slot.
    p_m = si // s
    s_m = si % s
    src = jnp.minimum((cell_s % (b + 1)).astype(jnp.int32), b - 1)
    assign_p = state.assignment[p_m]                               # [N, S]
    not_me = jnp.arange(s, dtype=jnp.int32)[None, :] != s_m[:, None]
    sib_b = jnp.clip(assign_p, 0, b - 1)
    room_pad = room
    lead_elig_sib = lead_elig[sib_b] & (assign_p >= 0) & not_me
    sib_room = jnp.where(lead_elig_sib, room_pad[sib_b], -1.0)
    sib_score = jnp.where(lead_elig_sib,
                          defi[0][sib_b] * 1e6 + headr[0][sib_b], -jnp.inf)
    best_slot = jnp.argmax(sib_score, axis=1).astype(jnp.int32)
    dst = sib_b[jnp.arange(n), best_slot]
    ok = mover & (jnp.take_along_axis(
        sib_room, best_slot[:, None], axis=1)[:, 0] >= 1.0)
    ok &= dst != src

    sel = ok
    pos = jnp.arange(n, dtype=jnp.int32)
    # Joint intake cap per destination + prior resource-band guards, in
    # one dst-sorted pass (leadership shifts leader_load − follower_load;
    # negative components are clamped to zero — ignoring an inflow that
    # REDUCES load only makes the check stricter).
    lead_vec = jnp.maximum(state.leader_load[p_m] - state.follower_load[p_m],
                           0.0)
    dkey = jnp.where(sel, dst, b)
    dkey_s = dkey * stride + blk_s if stride > 1 else dkey
    ds, _dp, d_i = jax.lax.sort((dkey_s, pos, pos), num_keys=2)
    ds_b = ds // stride if stride > 1 else ds
    blk_d = (ds % stride).astype(f32) if stride > 1 \
        else jnp.zeros((n,), f32)
    sel_d = sel[d_i]
    one_d = sel_d.astype(f32)
    pre_cnt = _segment_exclusive(ds, one_d)
    room_cap = jnp.concatenate([room, jnp.full((1,), jnp.inf, f32)])
    okd = pre_cnt * str_f + blk_d + 1.0 <= room_cap[ds_b] + _EPS
    if guards.resources or guards.cap_resources:
        okd_load, _pre = _dst_load_caps(ds, lead_vec[d_i] * sel_d[:, None],
                                        state, derived, constraint, guards,
                                        ds_b=ds_b, share=str_f)
        okd &= okd_load
    sel &= jnp.zeros(n, bool).at[d_i].set(okd)

    # Joint source-side floors (the greedy's stays-in-band src arm):
    # several leaderships leaving ONE broker in the same sweep must not
    # jointly take its load below a prior resource goal's lower band —
    # the per-mover pre-filter above only bounds a single departure.
    if guards.resources:
        skey = jnp.where(sel, src, b)
        skey_s = skey * stride + blk_s if stride > 1 else skey
        ss, _sp, s_i = jax.lax.sort((skey_s, pos, pos), num_keys=2)
        ss_b = ss // stride if stride > 1 else ss
        sel_s = sel[s_i]
        oks = _src_load_floors(ss, lead_vec[s_i] * sel_s[:, None],
                               state, derived, constraint, guards,
                               ss_b=ss_b, share=str_f)
        sel &= jnp.zeros(n, bool).at[s_i].set(oks)

    rows = jnp.where(sel, p_m, p)
    new_leader = state.leader_slot.at[rows].set(
        best_slot.astype(state.leader_slot.dtype), mode="drop")
    return (dataclasses.replace(state, leader_slot=new_leader),
            ps(sel.sum().astype(jnp.int32)),
            ps(mover.sum().astype(jnp.int32)))

def _direct_sweep(state: ClusterTensors, goals: tuple[Goal, ...], index: int,
                  constraint: BalancingConstraint, num_topics: int,
                  masks: ExclusionMasks, sweep: jax.Array | int = 0,
                  rank_stride: int = 1, block: jax.Array | int = 0,
                  psum=None, margin_frac: float = 0.25,
                  seed: int = SPARSE_ROUNDING_SEED,
                  ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """One transport sweep for ``goals[index]``: plan the full
    surplus→deficit matching on the current counts, veto infeasible
    assignments, apply the rest in one scatter. ``sweep`` (traced)
    cyclically rotates each group's rank→profile mapping so a pairing
    vetoed by feasibility (sibling/rack collisions) is re-paired with a
    DIFFERENT destination on the next sweep even when the counts did not
    change — without it a fully-vetoed plan is a fixed point and the
    residue never re-pairs.

    ``(rank_stride, block)`` select the SPMD rank layout (module
    docstring): on the mesh each device passes its shard index and the
    shard count, and ``psum`` (the mesh collective) makes the count
    planes and the returned scalars global. The same kernel evaluated
    single-device with ``block = partition_row // shard_rows`` is the
    mesh path's byte-parity reference. Returns
    (new_state, applied, planned)."""
    goal = goals[index]
    guards = _guards_for(goals, index)
    ps = psum if psum is not None else (lambda x: x)
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=psum)
    aux = goal_aux(goal, state, derived, constraint, num_topics, psum=psum)
    counts, lower, upper, group, movable = goal.direct_spec(
        state, derived, constraint, aux, num_topics)

    p, s = state.assignment.shape
    b = state.num_brokers
    g_dim = counts.shape[0]
    n = p * s
    f32 = jnp.float32
    stride = int(rank_stride)

    alive = derived.alive
    has_new = derived.new_brokers.any()
    elig_dst = jnp.where(has_new, derived.new_brokers,
                         derived.allowed_replica_move) & alive
    cnt = counts.astype(f32)

    # --- target distribution: integral surplus / deficit / headroom ------
    surplus, defi, headr = _surplus_deficit(
        cnt, lower, upper, alive, elig_dst, sweep=sweep,
        margin_frac=margin_frac, seed=seed)                         # [G, B]

    # --- mover selection: segmented sort by (cell, weight) ---------------
    alive_pad = jnp.concatenate([alive, jnp.zeros((1,), bool)])
    src_plane = jnp.where(state.assignment >= 0, state.assignment, b)
    mv = movable & derived.movable_partition[:, None] & alive_pad[src_plane]
    # Destination-INDEPENDENT source feasibility must be filtered out
    # BEFORE ranking: a replica whose departure alone would cross a
    # prior resource goal's lower band can reach no destination at all,
    # so letting it occupy a surplus rank wedges that rank forever (the
    # destination rotation can only re-pair, never re-select movers) —
    # measured at 64/2048: leader replicas of near-lower-band brokers
    # froze ~50 surplus ranks the greedy clears with other replicas.
    ninf1 = jnp.full((1,), -jnp.inf, f32)
    if guards.resources:
        lead_plane = is_leader_slot(state)
        for r in guards.resources:
            lo_r, _up_r, _c = resource_limits(state, derived, constraint,
                                              Resource(r))
            own_r = jnp.where(lead_plane, state.leader_load[:, r][:, None],
                              state.follower_load[:, r][:, None])
            load_pad = jnp.concatenate([derived.broker_load[:, r],
                                        jnp.zeros((1,), f32)])
            lo_pad = jnp.concatenate([lo_r, ninf1])
            mv &= load_pad[src_plane] - own_r >= lo_pad[src_plane] - _EPS
    if guards.replica_band:
        rl, _ru = count_limits(derived.avg_replicas,
                               constraint.replica_balance_threshold)
        reps_pad = jnp.concatenate([derived.broker_replicas.astype(f32),
                                    jnp.zeros((1,), f32)])
        rlo_pad = jnp.concatenate([jnp.broadcast_to(rl, (b,)), ninf1])
        mv &= reps_pad[src_plane] - 1.0 >= rlo_pad[src_plane] - _EPS
    if guards.leader_band:
        lead_plane = is_leader_slot(state)
        ll, _lu = count_limits(derived.avg_leaders,
                               constraint.leader_replica_balance_threshold)
        leads_pad = jnp.concatenate([derived.broker_leaders.astype(f32),
                                     jnp.zeros((1,), f32)])
        llo_pad = jnp.concatenate([jnp.broadcast_to(ll, (b,)), ninf1])
        mv &= (~lead_plane) \
            | (leads_pad[src_plane] - 1.0 >= llo_pad[src_plane] - _EPS)
    cell = jnp.where(mv, group * (b + 1) + src_plane,
                     g_dim * (b + 1)).astype(jnp.int32)
    weight = replica_load_total(state)
    if stride > 1:
        # Sort by (cell, block, weight): each block's rows keep their
        # local light-first order, and a device owning ONE block sees
        # the exact order of its local (cell, weight) sort — the SPMD
        # equivalence that makes single-device emulation byte-exact.
        blk_rows = jnp.broadcast_to(jnp.asarray(block, jnp.int32), (p,))
        blk_plane = jnp.broadcast_to(blk_rows[:, None], (p, s))
        key0 = cell * stride + blk_plane
    else:
        key0 = cell
    sc, _sk, si = jax.lax.sort(
        (key0.reshape(-1), weight.reshape(-1),
         jnp.arange(n, dtype=jnp.int32)), num_keys=2)
    rank_cell = _segment_rank(sc)              # within (cell, block)
    cell_s = sc // stride if stride > 1 else sc
    blk_s = sc % stride if stride > 1 else jnp.zeros_like(sc)
    sur_pad = jnp.concatenate([surplus, jnp.zeros((g_dim, 1), f32)],
                              axis=1).reshape(-1)
    sur_pad = jnp.concatenate([sur_pad, jnp.zeros((1,), f32)])
    # Interleaved global within-cell rank: local rank · stride + block.
    mover = (rank_cell * stride + blk_s).astype(f32) < sur_pad[cell_s]

    # --- cumsum rank-assignment over the [deficit | headroom] profile ----
    grp_key = cell_s // (b + 1)                 # sorted; sentinel = g_dim
    grp = jnp.minimum(grp_key, g_dim - 1)
    if stride > 1:
        # Within-(group, block) mover ordinal, interleaved to a globally
        # unique fill position (ordinal · stride + block) — computed in a
        # second sorted frame because (group, block) runs are not
        # contiguous in the (cell, block)-major frame.
        pos0 = jnp.arange(n, dtype=jnp.int32)
        gb_key = jnp.where(grp_key < g_dim, grp_key * stride + blk_s,
                           g_dim * stride).astype(jnp.int32)
        gs, _gp, g_i = jax.lax.sort((gb_key, pos0, pos0), num_keys=2)
        r_local = _segment_exclusive(gs, mover[g_i].astype(jnp.int32))
        rank_grp = jnp.zeros((n,), jnp.int32).at[g_i].set(
            r_local * stride + gs % stride)
    else:
        rank_grp = _segment_exclusive(grp_key, mover.astype(jnp.int32))
    # Per-sweep cyclic rotation within each group's position space: a
    # bijection on [0, total), so position uniqueness (and therefore every
    # cell's integer intake bound) is preserved; out-of-range ranks stay
    # put and keep their profile-overflow invalidity.
    tot_pos = (defi + headr).sum(axis=1)                           # [G]
    t_g = tot_pos[grp]
    rank_f = rank_grp.astype(f32)
    # Golden-ratio stride: consecutive profile positions usually belong
    # to the SAME broker (a deficit of d occupies d adjacent positions),
    # so a +1 rotation retries the same vetoed destination; a
    # ~0.618·total jump lands on a different broker almost every sweep.
    offs = jnp.floor(jnp.asarray(sweep, f32) * 0.6180339887 * t_g)
    rank_f = jnp.where(rank_f < t_g,
                       jnp.mod(rank_f + offs, jnp.maximum(t_g, 1.0)),
                       rank_f)
    dst, ok = deficit_fill_dests(grp, rank_f, defi, headr, elig_dst)
    ok &= mover

    # --- structural feasibility ------------------------------------------
    p_m = si // s
    s_m = si % s
    src = (cell_s % (b + 1)).astype(jnp.int32)
    ok &= dst != jnp.minimum(src, b - 1)
    assign_p = state.assignment[p_m]                           # [N, S]
    ok &= ~(assign_p == dst[:, None]).any(axis=1)
    is_lead = state.leader_slot[p_m] == s_m
    ok &= (~is_lead) | derived.allowed_leadership[dst]
    not_me = jnp.arange(s, dtype=jnp.int32)[None, :] != s_m[:, None]
    if guards.rack:
        rack_pad = jnp.concatenate([state.rack, state.rack[:1]])
        slot_racks = jnp.where(assign_p >= 0,
                               rack_pad[jnp.clip(assign_p, 0, b - 1)], -1)
        dst_rack = state.rack[dst]
        ok &= ~((slot_racks == dst_rack[:, None]) & not_me
                & (assign_p >= 0)).any(axis=1)

    # --- same-sweep sibling dedup via planned-destination planes ---------
    # ``si`` is a permutation of the replica axis, so one scatter writes
    # every slot exactly once; a mover is vetoed when an EARLIER (lower
    # sorted position) sibling of its partition claims the same broker —
    # or, under the rack guard, the same rack.
    pos = jnp.arange(n, dtype=jnp.int32)
    sel0 = mover & ok
    planned_dst = jnp.zeros((p, s), jnp.int32).at[p_m, s_m].set(
        jnp.where(sel0, dst, -1))
    planned_pri = jnp.zeros((p, s), jnp.int32).at[p_m, s_m].set(
        jnp.where(sel0, pos, n))
    others_dst = planned_dst[p_m]                              # [N, S]
    others_pri = planned_pri[p_m]
    earlier = not_me & (others_pri < pos[:, None])
    ok &= ~((others_dst == dst[:, None]) & earlier).any(axis=1)
    if guards.rack:
        others_rack = jnp.where(others_dst >= 0,
                                rack_pad[jnp.clip(others_dst, 0, b - 1)], -2)
        ok &= ~((others_rack == dst_rack[:, None]) & earlier).any(axis=1)

    sel = mover & ok
    # Per-mover load vector: a moving leader carries its leader load
    # (leadership travels with the slot), a follower its follower load.
    load_vec = jnp.where(is_lead[:, None], state.leader_load[p_m],
                         state.follower_load[p_m])              # [N, R]

    # --- prior-goal guards: dst-sorted joint caps ------------------------
    # At rank_stride > 1 the frame segments on (dst, block): COUNT caps
    # interleave (a block's k-th intake claims global position
    # k·stride + block, unique per destination, so the joint bound holds
    # across blocks with no collective); LOAD caps budget each block
    # 1/stride of the headroom (_dst_load_caps). stride == 1 reduces to
    # the exact round-17 formulas.
    str_f = f32(stride)
    dst_caps = (guards.replica_cap or guards.replica_band
                or guards.leader_band or guards.resources
                or guards.cap_resources or guards.pot_nw_out)
    if dst_caps:
        dkey = jnp.where(sel, dst, b)
        dkey_s = dkey * stride + blk_s if stride > 1 else dkey
        ds, _dp, d_i = jax.lax.sort((dkey_s, pos, pos), num_keys=2)
        ds_b = ds // stride if stride > 1 else ds
        blk_d = (ds % stride).astype(f32) if stride > 1 \
            else jnp.zeros((n,), f32)
        sel_d = sel[d_i]
        one_d = sel_d.astype(f32)
        okd = jnp.ones(n, bool)
        inf1 = jnp.full((1,), jnp.inf, f32)
        if guards.replica_cap or guards.replica_band:
            reps = derived.broker_replicas.astype(f32)
            cap_b = jnp.full((b,), jnp.inf, f32)
            if guards.replica_band:
                _rl, ru = count_limits(derived.avg_replicas,
                                       constraint.replica_balance_threshold)
                cap_b = jnp.minimum(cap_b, ru - reps)
            if guards.replica_cap:
                cap_b = jnp.minimum(
                    cap_b, constraint.max_replicas_per_broker - reps)
            pre_cnt = _segment_exclusive(ds, one_d)
            okd &= pre_cnt * str_f + blk_d + 1.0 \
                <= jnp.concatenate([cap_b, inf1])[ds_b] + _EPS
        if guards.leader_band:
            lead_d = (is_lead[d_i] & sel_d).astype(f32)
            _ll, lu = count_limits(derived.avg_leaders,
                                   constraint.leader_replica_balance_threshold)
            lcap = jnp.concatenate(
                [lu - derived.broker_leaders.astype(f32), inf1])
            pre_lead = _segment_exclusive(ds, lead_d)
            okd &= (lead_d == 0) \
                | (pre_lead * str_f + blk_d + 1.0 <= lcap[ds_b] + _EPS)
        if guards.resources or guards.cap_resources:
            okd_load, _pre = _dst_load_caps(ds, load_vec[d_i] * sel_d[:, None],
                                            state, derived, constraint,
                                            guards, ds_b=ds_b, share=str_f)
            okd &= okd_load
        if guards.pot_nw_out:
            r = int(Resource.NW_OUT)
            pot_own = state.leader_load[p_m, r][d_i] * one_d
            pre_pot = _segment_exclusive(ds, pot_own)
            limit = constraint.capacity_threshold[r] * state.capacity[:, r]
            lim_pad = jnp.concatenate([limit, inf1])
            pt_pad = jnp.concatenate([derived.pot_nw_out,
                                      jnp.zeros((1,), f32)])
            # The reference's escape hatch (PotentialNwOutGoal
            # .actionAcceptance): a move whose SOURCE already violates
            # its potential limit is tolerated — without it, a cluster
            # whose potential exceeds limits everywhere (the goal
            # violated at entry, e.g. the 1k/100k fixture at 140k
            # residual) vetoes EVERY transport move forever.
            src_pot = jnp.concatenate([derived.pot_nw_out,
                                       jnp.zeros((1,), f32)])
            src_lim = jnp.concatenate([limit, inf1])
            src_d = jnp.minimum(src[d_i], b)
            src_viol = src_pot[src_d] > src_lim[src_d] + _EPS
            okd &= (pt_pad[ds_b] + (pre_pot + pot_own) * str_f
                    <= lim_pad[ds_b] + _EPS) | src_viol
        sel &= jnp.zeros(n, bool).at[d_i].set(okd)

    # --- prior-goal guards: src-sorted joint floors ----------------------
    # Mirror of the dst caps: COUNT floors interleave outflow positions
    # (k-th departure from block d holds global position k·stride + d),
    # LOAD floors budget each block 1/stride of the slack above the band.
    src_floors = (guards.replica_band or guards.leader_band
                  or guards.resources)
    if src_floors:
        skey = jnp.where(sel, src, b)
        skey_s = skey * stride + blk_s if stride > 1 else skey
        ss, _sp, s_i = jax.lax.sort((skey_s, pos, pos), num_keys=2)
        ss_b = ss // stride if stride > 1 else ss
        blk_o = (ss % stride).astype(f32) if stride > 1 \
            else jnp.zeros((n,), f32)
        sel_s = sel[s_i]
        one_s = sel_s.astype(f32)
        oks = jnp.ones(n, bool)
        ninf1 = jnp.full((1,), -jnp.inf, f32)
        out_rank = _segment_exclusive(ss, one_s)
        if guards.replica_band:
            rl, _ru = count_limits(derived.avg_replicas,
                                   constraint.replica_balance_threshold)
            reps_pad = jnp.concatenate(
                [derived.broker_replicas.astype(f32),
                 jnp.zeros((1,), f32)])
            floor_pad = jnp.concatenate([jnp.broadcast_to(rl, (b,)), ninf1])
            oks &= reps_pad[ss_b] - (out_rank * str_f + blk_o) - 1.0 \
                >= floor_pad[ss_b] - _EPS
        if guards.leader_band:
            lead_s = (is_lead[s_i] & sel_s).astype(f32)
            ll, _lu = count_limits(derived.avg_leaders,
                                   constraint.leader_replica_balance_threshold)
            leads_pad = jnp.concatenate(
                [derived.broker_leaders.astype(f32), jnp.zeros((1,), f32)])
            lfloor = jnp.concatenate([jnp.broadcast_to(ll, (b,)), ninf1])
            pre_lead_out = _segment_exclusive(ss, lead_s)
            oks &= (lead_s == 0) \
                | (leads_pad[ss_b] - (pre_lead_out * str_f + blk_o) - 1.0
                   >= lfloor[ss_b] - _EPS)
        if guards.resources:
            oks &= _src_load_floors(ss, load_vec[s_i] * sel_s[:, None],
                                    state, derived, constraint, guards,
                                    ss_b=ss_b, share=str_f)
        sel &= jnp.zeros(n, bool).at[s_i].set(oks)

    # --- per-(topic, broker) band of a PRIOR topic goal ------------------
    if guards.topic_band and not isinstance(goal,
                                            TopicReplicaDistributionGoal):
        tb = ps(topic_broker_replica_counts(state, num_topics)).astype(f32)
        n_alive = jnp.maximum(alive.sum(), 1)
        t_avg = (tb * alive[None, :]).sum(axis=1) / n_alive
        t_up = jnp.ceil(t_avg * constraint.topic_replica_balance_threshold)
        t_lo = jnp.floor(t_avg / constraint.topic_replica_balance_threshold)
        topic_m = state.topic[p_m]
        # dst side: joint intake per (topic, dst) cell must stay under the
        # prior topic band's upper (interleaved positions at stride > 1).
        tdkey = jnp.where(sel, topic_m * (b + 1) + dst,
                          num_topics * (b + 1)).astype(jnp.int32)
        tdkey_s = tdkey * stride + blk_s if stride > 1 else tdkey
        ts, _tp, t_i = jax.lax.sort((tdkey_s, pos, pos), num_keys=2)
        ts_b = ts // stride if stride > 1 else ts
        blk_t = (ts % stride).astype(f32) if stride > 1 \
            else jnp.zeros((n,), f32)
        sel_t = sel[t_i].astype(f32)
        pre_td = _segment_exclusive(ts, sel_t)
        tb_pad = jnp.concatenate([tb, jnp.zeros((num_topics, 1), f32)],
                                 axis=1).reshape(-1)
        tb_pad = jnp.concatenate([tb_pad, jnp.zeros((1,), f32)])
        up_flat = jnp.concatenate(
            [jnp.broadcast_to(t_up[:, None], (num_topics, b + 1)).reshape(-1),
             jnp.full((1,), jnp.inf, f32)])
        okt = (sel_t == 0) \
            | (tb_pad[ts_b] + pre_td * str_f + blk_t + 1.0
               <= up_flat[ts_b] + _EPS)
        sel &= jnp.zeros(n, bool).at[t_i].set(okt)
        # src side: joint outflow per (topic, src) must stay at/above the
        # prior topic band's lower.
        tskey = jnp.where(sel, topic_m * (b + 1) + src,
                          num_topics * (b + 1)).astype(jnp.int32)
        tskey_s = tskey * stride + blk_s if stride > 1 else tskey
        ts2, _tp2, t2_i = jax.lax.sort((tskey_s, pos, pos), num_keys=2)
        ts2_b = ts2 // stride if stride > 1 else ts2
        blk_t2 = (ts2 % stride).astype(f32) if stride > 1 \
            else jnp.zeros((n,), f32)
        sel_t2 = sel[t2_i].astype(f32)
        pre_ts = _segment_exclusive(ts2, sel_t2)
        lo_flat = jnp.concatenate(
            [jnp.broadcast_to(t_lo[:, None], (num_topics, b + 1)).reshape(-1),
             jnp.full((1,), -jnp.inf, f32)])
        okt2 = (sel_t2 == 0) \
            | (tb_pad[ts2_b] - (pre_ts * str_f + blk_t2) - 1.0
               >= lo_flat[ts2_b] - _EPS)
        sel &= jnp.zeros(n, bool).at[t2_i].set(okt2)

    # --- one-shot scatter apply ------------------------------------------
    rows = jnp.where(sel, p_m, p)
    new_assignment = state.assignment.at[rows, s_m].set(
        dst.astype(state.assignment.dtype), mode="drop")
    return (dataclasses.replace(state, assignment=new_assignment),
            ps(sel.sum().astype(jnp.int32)),
            ps(mover.sum().astype(jnp.int32)))


def _sweep_fn(goals: tuple[Goal, ...], index: int):
    """Leader-count goals transport LEADERSHIP (sibling re-election);
    every other count goal transports replicas. Trace-time dispatch."""
    g = goals[index]
    if isinstance(g, CountDistributionGoal) and g.leaders:
        return _leadership_sweep
    return _direct_sweep


def _stall_limit(goals: tuple[Goal, ...], index: int) -> int:
    """Consecutive zero-apply sweeps tolerated before the loop gives the
    residue up to the greedy polish. The replica transports re-pair
    vetoed movers by rotation, so a zero-apply sweep can still unlock
    the next one — give rotation a few chances; the leadership sweep
    has no rotation (its destination menu is the partition's own
    siblings), so a zero-apply sweep would recompute a byte-identical
    plan forever — exit on the first."""
    return 1 if _sweep_fn(goals, index) is _leadership_sweep else 3


def _direct_rounds_driver(state: ClusterTensors, goals: tuple[Goal, ...],
                          index: int, constraint: BalancingConstraint,
                          num_topics: int, masks: ExclusionMasks,
                          max_sweeps: int, rank_stride: int = 1,
                          block: jax.Array | int = 0, psum=None,
                          margin_frac: float = 0.25,
                          seed: int = SPARSE_ROUNDING_SEED):
    """Sweep loop (traced): unlike the greedy megastep's zero-APPLY exit,
    the direct loop keeps sweeping while the plan still has MOVERS —
    a sweep whose every pairing was feasibility-vetoed applies nothing,
    but the next sweep's rotation can re-pair the residue. A bounded
    zero-apply STREAK (``_stall_limit``) still ends a stalled loop: a
    structurally-stuck residue must fall to the greedy polish, not burn
    the whole ``max_sweeps`` budget recomputing vetoed plans.

    A second streak watches PROGRESS: because the fractional plan keeps
    a headroom/widening tail alive until every deficit is filled, a
    wedged residue can apply a tiny trickle of moves each sweep without
    ever shrinking the plan — the zero-apply streak never fires and the
    loop burns the whole budget on a plateau (measured at 200b/10k/40t:
    all three count goals ran 13-16 of 16 sweeps for moves the polish
    replays in 2-4 rounds). A sweep must shrink ``planned`` by at least
    an EIGHTH below the best seen so far to reset the streak;
    ``_stall_limit`` consecutive non-improving sweeps end the loop. The
    geometric bar (not strict decrease) matters twice over: the
    per-sweep rounding re-draw wobbles the plan by ±1 per group per
    plane, so a plateau still "improves" by one count every few sweeps,
    and a sweep costs roughly 1.3 greedy polish rounds — progress in
    single counts per sweep is cheaper replayed by the polish, which
    the caller already sizes from the stranded residue.

    ``(rank_stride, block, psum)`` thread the SPMD layout (module
    docstring) so the mesh path can run THIS loop per shard — the
    returned scalars are already psum'd global, so the while predicate
    agrees across devices by construction."""
    if not direct_eligible(goals, index):   # trace-time guard
        raise ValueError(
            f"goal {goals[index].name} / chain prefix not direct-eligible "
            "(see direct_eligible)")
    sweep_fn = _sweep_fn(goals, index)
    stall = _stall_limit(goals, index)
    big = jnp.int32(jnp.iinfo(jnp.int32).max)

    def cond(c):
        _st, _tot, i, planned, zeros, _best, noprog = c
        return ((planned > 0) & (i < max_sweeps) & (zeros < stall)
                & (noprog < stall))

    def body(c):
        st, tot, i, _planned, zeros, best, noprog = c
        ns, applied, planned = sweep_fn(st, goals, index, constraint,
                                        num_topics, masks, sweep=i,
                                        rank_stride=rank_stride, block=block,
                                        psum=psum, margin_frac=margin_frac,
                                        seed=seed)
        zeros = jnp.where(applied > 0, jnp.int32(0), zeros + 1)
        improved = planned < best - best // 8
        noprog = jnp.where(improved, jnp.int32(0), noprog + 1)
        return (ns, tot + applied, i + 1, planned, zeros,
                jnp.minimum(best, planned), noprog)

    final, total, sweeps, planned, _z, _b, _np = jax.lax.while_loop(
        cond, body,
        (state, jnp.int32(0), jnp.int32(0), jnp.int32(1), jnp.int32(0),
         big, jnp.int32(0)))
    # ``planned`` at exit = movers the plan still wanted but could not
    # place (0 when the transport fully converged): the caller's honest
    # residue signal for sizing the greedy polish.
    return final, total, sweeps, planned


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps",
                                   "margin_frac", "seed"))
def direct_transport_rounds(state: ClusterTensors, goals: tuple[Goal, ...],
                            index: int, constraint: BalancingConstraint,
                            num_topics: int, masks: ExclusionMasks,
                            max_sweeps: int = 8, margin_frac: float = 0.25,
                            seed: int = SPARSE_ROUNDING_SEED):
    """The direct-assignment solve for ``goals[index]`` under the guards
    of ``goals[:index]``: up to ``max_sweeps`` transport sweeps inside
    ONE ``lax.while_loop`` dispatch (a stalled loop ends on device).
    Returns (final_state, moves_applied, sweeps_run, movers_stranded)."""
    return _direct_rounds_driver(state, goals, index, constraint,
                                 num_topics, masks, max_sweeps,
                                 margin_frac=margin_frac, seed=seed)


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps",
                                   "margin_frac", "seed"),
         donate_argnums=(0, 1))
def direct_transport_rounds_donated(assignment: jax.Array,
                                    leader_slot: jax.Array,
                                    rest: ClusterTensors,
                                    goals: tuple[Goal, ...], index: int,
                                    constraint: BalancingConstraint,
                                    num_topics: int, masks: ExclusionMasks,
                                    max_sweeps: int = 8,
                                    margin_frac: float = 0.25,
                                    seed: int = SPARSE_ROUNDING_SEED):
    """Donated twin (identical trace): callers pass
    ``chain.strip_mutable(state)`` as ``rest`` and relinquish the two
    mutable tensors — the donation set is exactly the strip_mutable pair,
    nothing else (CCSA002)."""
    state = dataclasses.replace(rest, assignment=assignment,
                                leader_slot=leader_slot)
    final, total, sweeps, planned = _direct_rounds_driver(
        state, goals, index, constraint, num_topics, masks, max_sweeps,
        margin_frac=margin_frac, seed=seed)
    return final.assignment, final.leader_slot, total, sweeps, planned


# ---------------------------------------------------------------------------
# Megabatch twins: whole buckets of clusters, one direct program
# ---------------------------------------------------------------------------

def _megabatch_direct_driver(states: ClusterTensors, active0: jax.Array,
                             goals: tuple[Goal, ...], index: int,
                             constraint: BalancingConstraint,
                             num_topics: int, masks: ExclusionMasks,
                             max_sweeps: int, margin_frac: float = 0.25,
                             seed: int = SPARSE_ROUNDING_SEED):
    """Batched sweep loop with the megabatch freeze discipline: an
    inactive cluster's whole state is frozen by a select, so a pad slot
    (or a cluster whose plan converged) stays byte-identical while its
    batchmates keep sweeping — one compiled program per bucket shape
    serves any occupancy (occupancy is traced, never a new compile)."""
    if not direct_eligible(goals, index):   # trace-time guard
        raise ValueError(
            f"goal {goals[index].name} / chain prefix not direct-eligible "
            "(see direct_eligible)")
    c = states.assignment.shape[0]
    fields = (masks.excluded_topics, masks.excluded_replica_move_brokers,
              masks.excluded_leadership_brokers)
    ax = tuple(None if f is None else 0 for f in fields)

    sweep_fn = _sweep_fn(goals, index)
    stall = _stall_limit(goals, index)

    def per_cluster(st, tm, rm, lm, i):
        return sweep_fn(st, goals, index, constraint, num_topics,
                        ExclusionMasks(tm, rm, lm), sweep=i,
                        margin_frac=margin_frac, seed=seed)

    vsweep = jax.vmap(per_cluster, in_axes=(0,) + ax + (None,))

    def cond(carry):
        _st, _tot, _swp, i, active, _z = carry
        return active.any() & (i < max_sweeps)

    def body(carry):
        st, tot, swp, i, active, zeros = carry
        nst, applied, planned = vsweep(st, *fields, i)

        def keep(new, old):
            k = active.reshape((c,) + (1,) * (new.ndim - 1))
            return jnp.where(k, new, old)

        st = jax.tree.map(keep, nst, st)
        applied = jnp.where(active, applied, 0).astype(jnp.int32)
        zeros = jnp.where(active & (applied == 0), zeros + 1,
                          jnp.where(active, 0, zeros))
        return (st, tot + applied, swp + active.astype(jnp.int32), i + 1,
                active & (planned > 0) & (zeros < stall), zeros)

    final, total, sweeps, _i, active, _z = jax.lax.while_loop(
        cond, body,
        (states, jnp.zeros((c,), jnp.int32), jnp.zeros((c,), jnp.int32),
         jnp.int32(0), active0, jnp.zeros((c,), jnp.int32)))
    return final, total, sweeps, active


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps",
                                   "margin_frac", "seed"))
def megabatch_direct_rounds(states: ClusterTensors, active0: jax.Array,
                            goals: tuple[Goal, ...], index: int,
                            constraint: BalancingConstraint,
                            num_topics: int, masks: ExclusionMasks,
                            max_sweeps: int = 8, margin_frac: float = 0.25,
                            seed: int = SPARSE_ROUNDING_SEED):
    """Batched direct solve over a leading cluster axis. Returns
    (states, moves[C], sweeps[C], active_out[C])."""
    return _megabatch_direct_driver(states, active0, goals, index,
                                    constraint, num_topics, masks,
                                    max_sweeps, margin_frac=margin_frac,
                                    seed=seed)


@partial(jax.jit, static_argnames=("goals", "index", "constraint",
                                   "num_topics", "max_sweeps",
                                   "margin_frac", "seed"),
         donate_argnums=(0, 1))
def megabatch_direct_rounds_donated(assignment: jax.Array,
                                    leader_slot: jax.Array,
                                    rest: ClusterTensors, active0: jax.Array,
                                    goals: tuple[Goal, ...], index: int,
                                    constraint: BalancingConstraint,
                                    num_topics: int, masks: ExclusionMasks,
                                    max_sweeps: int = 8,
                                    margin_frac: float = 0.25,
                                    seed: int = SPARSE_ROUNDING_SEED):
    """Donated batched twin: donation set is exactly the strip_mutable
    pair grown a cluster axis ``{assignment[C,P,S], leader_slot[C,P]}``
    (CCSA002); the stacked topology planes in ``rest`` are
    refresh-cache-shared and never donated."""
    states = dataclasses.replace(rest, assignment=assignment,
                                 leader_slot=leader_slot)
    final, total, sweeps, active = _megabatch_direct_driver(
        states, active0, goals, index, constraint, num_topics, masks,
        max_sweeps, margin_frac=margin_frac, seed=seed)
    return final.assignment, final.leader_slot, total, sweeps, active


# ---------------------------------------------------------------------------
# Host-side pass driver
# ---------------------------------------------------------------------------

def run_direct_pass(state: ClusterTensors, goals, index: int,
                    constraint: BalancingConstraint, num_topics: int,
                    masks: ExclusionMasks, megastep, max_sweeps: int,
                    stats=None, flight=None, donate_input: bool = False):
    """Fire the direct solve as ONE device dispatch and read its scalars
    back synchronously (there is nothing to pipeline behind a single
    dispatch). Donation follows the megastep discipline: the first
    mutating dispatch either consumes the caller's buffers
    (``donate_input``) or donates a device COPY of the two mutable
    tensors; the flight record and dispatch stats land under
    ``kind="direct"`` so solver_dispatches{kind="direct"} is its own
    series and the acceptance-density histogram (defined only for greedy
    move dispatches on a recorded grid) never sees these.

    Returns (state, moves, sweeps, donated, stranded) — ``stranded`` is
    the mover count the plan still wanted but could not place at exit
    (the caller's residue signal for sizing the greedy polish)."""
    import time as _time

    from ..utils.sensors import SENSORS
    from .chain import donation_enabled, strip_mutable
    goals = tuple(goals)
    donate = donation_enabled(megastep)
    margin_frac = float(getattr(megastep, "direct_sparse_margin", 0.25))
    seed = sparse_rounding_seed(getattr(megastep, "direct_sparse_salt", ""))
    # ccsa: ok[CCSA004] flight-telemetry stamp on the host driver — the
    # value never feeds the plan or the rounding seed
    t0 = _time.monotonic()
    if donate:
        if not donate_input:
            state = dataclasses.replace(
                state, assignment=jnp.copy(state.assignment),
                leader_slot=jnp.copy(state.leader_slot))
        a, l, total, sweeps, planned = direct_transport_rounds_donated(
            state.assignment, state.leader_slot, strip_mutable(state),
            goals, index, constraint, num_topics, masks, max_sweeps,
            margin_frac=margin_frac, seed=seed)
        state = dataclasses.replace(state, assignment=a, leader_slot=l)
    else:
        state, total, sweeps, planned = direct_transport_rounds(
            state, goals, index, constraint, num_topics, masks, max_sweeps,
            margin_frac=margin_frac, seed=seed)
    moves = int(total)
    sweeps_run = int(sweeps)
    stranded = int(planned)
    # ccsa: ok[CCSA004] flight-telemetry stamp on the host driver — the
    # value never feeds the plan or the rounding seed
    elapsed = _time.monotonic() - t0
    if stats is not None:
        stats.record("direct", sweeps_run, donated=donate)
    if flight is not None:
        flight.dispatch("direct", max_sweeps, sweeps_run, moves,
                        donated=donate, elapsed_s=elapsed)
    SENSORS.count("solver_direct_sweeps", sweeps_run)
    SENSORS.count("solver_direct_moves", moves)
    SENSORS.count("solver_direct_stranded", stranded)
    return state, moves, sweeps_run, donate, stranded
