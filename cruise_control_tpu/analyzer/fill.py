"""Constructive destination assignment for the candidate grid.

The top-k × top-k grid gives every source replica the same ``num_dests``
globally-best destinations. For goals whose destination demand is
PER-CARD — count goals need a broker with headroom in *this card's
topic*, resource goals need a broker whose band gap fits *this card's
size* — the shared destination list is the round-count bottleneck at
scale: the reference's greedy never pays it because each
``rebalanceForBroker`` walks candidate brokers per replica
(AbstractGoal.java:82-135), while the batched grid funnels thousands of
sources through ≤ 32 destinations (measured r4: TopicReplica ≈ 65% of
the 7k/1M wall-clock; DiskUsage tail ≈ 50 accepted moves/round).

This module computes one TARGETED destination per source card, appended
to the move block as an extra grid column (candidates.generate_candidates
``extra_dst``), so each card competes with a destination constructed for
it:

- ``deficit_fill_dests``: proportional fill over per-(topic, broker)
  deficits then remaining headroom — card ranks within their topic are
  mapped through the cumulative deficit/headroom profile, so a round's
  joint assignment respects every cell's integer headroom by
  construction (TopicReplicaDistributionGoal.java /
  ReplicaDistributionAbstractGoal.java band semantics).
- ``best_fit_dests``: first-fit-decreasing style matching for resource
  goals — each card's replica size is matched round-robin across the
  destinations whose band gap fits it
  (ResourceDistributionGoal.java:380-435 requireLessLoad, without the
  shared-destination funnel).

All kernels are O(k·log B) gathers + O(T·B) cumsums — no [k, B]
materialization — and run unmodified under the partition-sharded mesh
(inputs are replicated aux/derived aggregates; card ranks are
device-local, cross-device overfill is vetoed by the joint acceptance
recheck).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

# Experiment kill-switch: CC_TARGET_DESTS=0 removes the targeted column
# from every search path (per-goal, chain, sharded) — the control arm for
# attributing per-round cost and fixed-point depth to this machinery.
TARGET_DESTS_ON = os.environ.get("CC_TARGET_DESTS", "1") == "1"

# Scale gate (measured at 7k/1M, r5): the per-round cost of the targeted
# branch (per-card fill ranks + cumulative profiles) buys nothing at
# north-star scale — TopicReplica reaches the same deep fixed point
# without it (~242 s vs ~288 s per full pass) because the 2048-wide grid
# already saturates the deficit profile over enough rounds; at tool/mid
# scale the column clears residuals the shared grid cannot reach. Static
# per-shape decision (num_partitions is a trace-time constant).
TARGET_DESTS_MAX_P = int(os.environ.get("CC_TARGET_DESTS_MAX_P", "500000"))


def targets_enabled(num_partitions: int) -> bool:
    return TARGET_DESTS_ON and num_partitions < TARGET_DESTS_MAX_P


def pow2_width(n: int) -> int:
    """Round a measured work size up to the next power of two — the
    compile-count quantization of every deficit-sized grid width (each
    distinct static width is a new XLA program, so sized widths must come
    from a tiny set)."""
    return 1 << max(0, int(n) - 1).bit_length()


# Per-goal-class filter for attribution experiments: comma-separated class
# names; empty = all classes contribute targeted destinations.
_TGT_CLASSES = os.environ.get("CC_TGT_CLASSES", "")


def class_enabled(goal) -> bool:
    return (not _TGT_CLASSES
            or type(goal).__name__ in _TGT_CLASSES.split(","))


def row_searchsorted(cum: jax.Array, rows: jax.Array, q: jax.Array,
                     ) -> jax.Array:
    """Per-card first index j with ``cum[rows[i], j] > q[i]`` (rows of
    ``cum`` non-decreasing); returns ``cum.shape[1]`` when no such j.
    Manual binary search: ceil(log2(n)) unrolled steps of [k] gathers —
    never materializes the [k, n] row gather."""
    n = cum.shape[1]
    lo = jnp.zeros(q.shape, jnp.int32)
    hi = jnp.full(q.shape, n, jnp.int32)
    # Interval width n halves per step; width-1 intervals need one final
    # step to resolve, so ceil(log2(n)) + 1 <= n.bit_length() + 1 overall.
    for _ in range(max(1, int(n).bit_length())):
        mid = (lo + hi) // 2
        v = cum[rows, jnp.minimum(mid, n - 1)]
        gt = v > q
        hi = jnp.where(gt & (mid < hi), mid, hi)
        lo = jnp.where(gt, lo, jnp.minimum(mid + 1, hi))
    return hi


def rank_within_group(group: jax.Array, valid: jax.Array) -> jax.Array:
    """[k] — number of EARLIER valid cards with the same group id (the
    card's fill position within its group). O(k²) boolean mask over the
    card batch (k ≤ a few thousand)."""
    k = group.shape[0]
    idx = jnp.arange(k)
    earlier = idx[:, None] > idx[None, :]
    same = group[:, None] == group[None, :]
    return (earlier & same & valid[None, :]).sum(axis=1).astype(jnp.int32)


def exclusive_rank(valid: jax.Array) -> jax.Array:
    """[k] — number of earlier valid cards (single-group fast path)."""
    c = jnp.cumsum(valid.astype(jnp.int32))
    return (c - valid.astype(jnp.int32)).astype(jnp.int32)


def deficit_fill_dests(topic_idx: jax.Array, rank: jax.Array,
                       deficit: jax.Array, headroom: jax.Array,
                       eligible: jax.Array,
                       ) -> tuple[jax.Array, jax.Array]:
    """Targeted destination per card by proportional fill.

    ``deficit``/``headroom`` are [G, B] NON-NEGATIVE integer-valued floats
    (deficit ⊆ headroom is NOT assumed — headroom here is the capacity
    REMAINING after the deficit portion). Card i (group g = topic_idx[i],
    fill position q = rank[i]) lands in the broker owning position q of
    the concatenated [deficit | headroom] profile of its group — deficits
    fill first, every broker receives at most deficit+headroom cards per
    round. Returns (dst [k] int32, ok [k] bool)."""
    f32 = jnp.float32
    d = jnp.where(eligible[None, :], deficit, 0.0).astype(f32)
    h = jnp.where(eligible[None, :], headroom, 0.0).astype(f32)
    cum_d = jnp.cumsum(d, axis=1)
    cum_h = jnp.cumsum(h, axis=1)
    tot_d = cum_d[:, -1][topic_idx]
    tot_h = cum_h[:, -1][topic_idx]
    q = rank.astype(f32) + 0.5  # strictly inside the owning cell
    in_def = q < tot_d
    j_d = row_searchsorted(cum_d, topic_idx, q)
    j_h = row_searchsorted(cum_h, topic_idx, q - tot_d)
    b = deficit.shape[1]
    dst = jnp.where(in_def, j_d, j_h)
    ok = (q < tot_d + tot_h) & (dst < b)
    return jnp.clip(dst, 0, b - 1).astype(jnp.int32), ok


def best_fit_dests(size: jax.Array, rank: jax.Array, headroom: jax.Array,
                   eligible: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Targeted destination per card by size fit: destinations sorted by
    band gap descending; card i (size s, fill position q = rank[i]) is
    assigned round-robin across the destinations whose gap fits s.
    Returns (dst [k] int32, ok [k] bool)."""
    b = headroom.shape[0]
    key = jnp.where(eligible, headroom, -jnp.inf)
    vals, idx = jax.lax.top_k(key, b)  # descending
    # m = count of destinations with gap >= size: first j with
    # -vals[j] > -size on the ascending -vals row.
    m = row_searchsorted(-vals[None, :], jnp.zeros_like(rank), -size)
    ok = (m > 0) & jnp.isfinite(size) & (size > 0)
    q = rank % jnp.maximum(m, 1)
    dst = idx[jnp.clip(q, 0, b - 1)]
    return dst.astype(jnp.int32), ok
