"""Balancing constraints and per-optimization options.

Reference parity: analyzer/BalancingConstraint.java:50-270 (thresholds from
config), analyzer/OptimizationOptions.java (excluded topics / brokers for
leadership / brokers for replica move, fast mode).

These are *static* (hashable) dataclasses: they are baked into the jitted
solver as compile-time constants, so changing a threshold triggers a
recompile but costs nothing per-step.
"""

from __future__ import annotations

import dataclasses

from ..common.resources import Resource
from ..config.cruise_control_config import CruiseControlConfig

# ResourceDistributionGoal.java:57 — goals aim inside the configured band so
# results don't sit on the boundary.
BALANCE_MARGIN = 0.9


@dataclasses.dataclass(frozen=True)
class BalancingConstraint:
    resource_balance_threshold: tuple[float, float, float, float] = (1.1, 1.1, 1.1, 1.1)
    capacity_threshold: tuple[float, float, float, float] = (0.7, 0.8, 0.8, 0.8)
    low_utilization_threshold: tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    replica_balance_threshold: float = 1.1
    leader_replica_balance_threshold: float = 1.1
    topic_replica_balance_threshold: float = 1.1
    max_replicas_per_broker: int = 10_000
    goal_violation_distribution_threshold_multiplier: float = 1.0

    @classmethod
    def from_config(cls, cfg: CruiseControlConfig) -> "BalancingConstraint":
        def per_resource(fmt: dict[Resource, str]) -> tuple[float, ...]:
            return tuple(cfg.get_double(fmt[r]) for r in Resource)

        return cls(
            resource_balance_threshold=per_resource({
                Resource.CPU: "cpu.balance.threshold",
                Resource.NW_IN: "network.inbound.balance.threshold",
                Resource.NW_OUT: "network.outbound.balance.threshold",
                Resource.DISK: "disk.balance.threshold"}),
            capacity_threshold=per_resource({
                Resource.CPU: "cpu.capacity.threshold",
                Resource.NW_IN: "network.inbound.capacity.threshold",
                Resource.NW_OUT: "network.outbound.capacity.threshold",
                Resource.DISK: "disk.capacity.threshold"}),
            low_utilization_threshold=per_resource({
                Resource.CPU: "cpu.low.utilization.threshold",
                Resource.NW_IN: "network.inbound.low.utilization.threshold",
                Resource.NW_OUT: "network.outbound.low.utilization.threshold",
                Resource.DISK: "disk.low.utilization.threshold"}),
            replica_balance_threshold=cfg.get_double("replica.count.balance.threshold"),
            leader_replica_balance_threshold=cfg.get_double(
                "leader.replica.count.balance.threshold"),
            topic_replica_balance_threshold=cfg.get_double(
                "topic.replica.count.balance.threshold"),
            max_replicas_per_broker=cfg.get_long("max.replicas.per.broker"),
            goal_violation_distribution_threshold_multiplier=cfg.get_double(
                "goal.violation.distribution.threshold.multiplier"),
        )

    def balance_band(self, resource: Resource,
                     for_detector: bool = False) -> tuple[float, float]:
        """(lower, upper) utilization multipliers around the average
        (GoalUtils.computeResourceUtilizationBalanceThreshold)."""
        t = self.resource_balance_threshold[int(resource)]
        if for_detector:
            t *= self.goal_violation_distribution_threshold_multiplier
        spread = (t - 1.0) * BALANCE_MARGIN
        return 1.0 - spread, 1.0 + spread


@dataclasses.dataclass(frozen=True)
class OptimizationOptions:
    excluded_topics: tuple[str, ...] = ()
    excluded_brokers_for_leadership: tuple[int, ...] = ()
    excluded_brokers_for_replica_move: tuple[int, ...] = ()
    requested_destination_broker_ids: tuple[int, ...] = ()
    only_move_immigrant_replicas: bool = False
    is_triggered_by_goal_violation: bool = False
    fast_mode: bool = False
