"""Goal SPI for the TPU solver.

Reference parity: analyzer/goals/Goal.java:39-163 (optimize /
actionAcceptance / completeness) and AbstractGoal.java. Redesigned for
batch evaluation: a goal is a STATIC (hashable, frozen) object whose methods
are pure traced functions over (state, derived, constraint, deltas). The
sequential callback protocol "every previously optimized goal must accept
the action" (AbstractGoal.maybeApplyBalancingAction:230) becomes an AND over
each goal's vectorized ``acceptance`` mask, evaluated for thousands of
candidates at once.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ...model.tensors import ClusterTensors, replica_load_total
from ..candidates import CandidateDeltas
from ..constraint import BalancingConstraint
from ..derived import DerivedState


@dataclasses.dataclass(frozen=True)
class Goal:
    """Base goal. Subclasses override the kernel methods; instances carry
    only static config (so they can be jit-static arguments)."""

    name: str = "goal"
    is_hard: bool = False
    include_leadership: bool = False
    leadership_only: bool = False
    # Swap phase eligibility (ResourceDistributionGoal.java:421-430: only
    # when plain moves fail to reach the band are swaps tried).
    supports_swap: bool = False
    # True when acceptance/improvement depend ONLY on the candidate's own
    # partition (rack layout, broker-set membership, preferred leader) and
    # not on per-broker totals: the conflict-free accept step may then take
    # MANY moves per broker per round (only one per partition), which is
    # what makes structural goals converge in O(P / num_sources) rounds
    # instead of O(P / num_dests).
    independent_per_broker: bool = False
    # True when broker_violations/source_score are additive reductions over
    # the partition axis (rack duplicates, non-preferred leaders): under a
    # partition-sharded mesh the sharded search psums them across devices.
    partition_additive_scores: bool = False
    # True for goals whose per-round accepted-move count is source-limited
    # and whose band structure tolerates wide joint batches WITHOUT the
    # final-quality loss wider batches cause for the early count/resource
    # goals (measured at 1k/100k, docs/DESIGN.md): the bounded per-goal
    # driver runs these with a 4x source grid. Only goals late enough in
    # the chain that their coarser placements cannot be locked in against
    # later goals' fixes should set this (validated for
    # TopicReplicaDistributionGoal: rounds 482 -> 106, balancedness and
    # violated set unchanged).
    prefers_wide_batches: bool = False
    # True for the count-distribution family (replica / leader-replica /
    # topic-replica counts): total band violation ≈ 2 × the moves still
    # needed, so the bounded megastep driver may size the per-round move
    # budget and source width from the MEASURED surplus
    # (chain.deficit_sized_config) instead of the configured constant —
    # an O(10k)-move imbalance then stops burning hundreds of fixed-width
    # rounds. Resource goals must NOT set this: their violation is in
    # load units, not move counts.
    count_based: bool = False
    # True for goals whose decisions read measured resource loads (the
    # capacity / resource-distribution / potential-NW-out / leader-bytes-in
    # family): they need a substantially complete metric model, mirroring
    # ResourceDistributionGoal.clusterModelCompletenessRequirements:164-167
    # (numWindows/2 valid windows + min.valid.partition.ratio). Structural
    # goals (rack, counts, preferred leader) run on topology alone — one
    # window, any coverage (ReplicaDistributionAbstractGoal's weak
    # requirements).
    uses_resource_metrics: bool = False
    # True when the goal's fixed point has a closed-form transport
    # formulation the direct-assignment kernel (analyzer.direct) can
    # solve: ``direct_spec`` must then return the count plane + band +
    # grouping the kernel plans over. Only the count-distribution family
    # qualifies; whether the kernel actually RUNS additionally requires
    # every prior goal in the chain to be guard-representable
    # (analyzer.direct.direct_eligible).
    supports_direct: bool = False

    def completeness_requirements(self, num_windows: int,
                                  min_valid_partition_ratio: float,
                                  ) -> tuple[int, float]:
        """(min_valid_windows, min_monitored_partitions_ratio) this goal
        needs before its output is trustworthy
        (Goal.clusterModelCompletenessRequirements)."""
        if self.uses_resource_metrics:
            return max(1, num_windows // 2), min_valid_partition_ratio
        return 1, 0.0

    # -- evaluation kernels (traced) --------------------------------------
    def prepare_partial(self, state: ClusterTensors, num_topics: int) -> Any:
        """Per-round aux tensors that are ADDITIVE over the partition axis
        (e.g. [T, B] topic counts). Under a partition-sharded mesh each
        device computes its partial and the search psums the pytree."""
        return None

    def partial_from_agg(self, agg) -> Any:
        """This goal's prepare_partial result read from the incrementally-
        maintained AggCarry (analyzer.agg) instead of an O(P·S) recompute,
        or None when the goal is not agg-backed. The returned partial is
        already GLOBAL (no psum needed on a mesh)."""
        return None

    def finalize_aux(self, partial: Any, state: ClusterTensors,
                     derived: DerivedState,
                     constraint: BalancingConstraint) -> Any:
        """Non-additive post-processing of the (already psum'd) partial
        (e.g. balance bands from counts). Default: aux = partial."""
        return partial

    def prepare(self, state: ClusterTensors, derived: DerivedState,
                constraint: BalancingConstraint, num_topics: int) -> Any:
        """Single-device aux composition. Do NOT override this — the search
        paths call prepare_partial/finalize_aux directly (the sharded path
        psums the partial between them); override THOSE to customize aux, or
        an override would be silently bypassed during optimization."""
        return self.finalize_aux(self.prepare_partial(state, num_topics),
                                 state, derived, constraint)

    def broker_violations(self, state, derived, constraint, aux) -> jax.Array:
        """[B] violation magnitude per broker (0 = satisfied)."""
        raise NotImplementedError

    def objective(self, state, derived, constraint, aux) -> jax.Array:
        """Scalar, lower is better. Default: total violation."""
        return self.broker_violations(state, derived, constraint, aux).sum()

    def acceptance(self, state, derived, constraint, aux,
                   deltas: CandidateDeltas) -> jax.Array:
        """[N] bool — does this (already-optimized) goal tolerate each
        candidate action? (Goal.actionAcceptance, vectorized.)"""
        return jnp.ones(deltas.valid.shape[0], dtype=bool)

    def improvement(self, state, derived, constraint, aux,
                    deltas: CandidateDeltas) -> jax.Array:
        """[N] — decrease of this goal's objective if the candidate is
        applied (positive = improves). Default: pairwise violation delta."""
        raise NotImplementedError

    def swap_leg_acceptance(self, state, derived, constraint, aux,
                            leg: CandidateDeltas) -> jax.Array:
        """[N] bool — tolerate one directional leg of a swap, judged as an
        ordinary move. Default: ``acceptance``. Per-partition structural
        goals (rack, broker-set, topic counts) keep this; goals judged on
        per-broker TOTALS override it to all-true and judge the net
        transfer in ``swap_net_acceptance`` instead. The sharded solver
        evaluates leg acceptance on the device OWNING the leg's partition —
        implementations may index per-partition state freely."""
        return self.acceptance(state, derived, constraint, aux, leg)

    def swap_net_acceptance(self, state, derived, constraint, aux,
                            net: CandidateDeltas) -> jax.Array:
        """[N] bool — tolerate the NET transfer of a swap (replica counts
        unchanged, load(a) − load(b) moves src→dst). Default: all-true.
        CONTRACT: implementations must use only broker-indexed state
        (``derived`` aggregates, capacities) and the deltas' own fields —
        ``net.partition`` holds GLOBAL partition ids under the sharded
        solver, so per-partition gathers are out of bounds there."""
        return jnp.ones(net.valid.shape[0], dtype=bool)

    def swap_improvement(self, state, derived, constraint, aux,
                         fwd: CandidateDeltas, rev: CandidateDeltas,
                         net: CandidateDeltas) -> jax.Array:
        """[N] — decrease of this goal's objective if the SWAP is applied.
        Default: ``improvement`` on the net transfer (sufficient for
        totals-judged goals, where a swap is the signed net move).
        Structural goals whose objective lives on BOTH legs — e.g. the
        kafka-assigner even-rack goal, where each leg can fix or create a
        rack duplicate while the net transfer moves no replica — override
        this to score the legs (the reference's swap inner loop evaluates
        the exchange as a pair, KafkaAssignerEvenRackAwareGoal.java)."""
        return self.improvement(state, derived, constraint, aux, net)

    def swap_dest_score(self, state, derived, constraint, aux) -> jax.Array:
        """[B] — counterparty attractiveness for the SWAP grid. Default:
        ``dest_score``. Goals whose move destinations exclude exactly the
        brokers swaps exist to reach (the even-rack goal's dest_score
        drops over-ceiling brokers, but a count-preserving exchange WANTS
        the over-ceiling broker holding the replica to take back)
        override this."""
        return self.dest_score(state, derived, constraint, aux)

    def swap_acceptance(self, state, derived, constraint, aux,
                        fwd: CandidateDeltas, rev: CandidateDeltas,
                        net: CandidateDeltas) -> jax.Array:
        """[N] bool — tolerate each candidate SWAP: both directional legs
        pass ``swap_leg_acceptance`` and the net transfer passes
        ``swap_net_acceptance`` (ActionType.INTER_BROKER_REPLICA_SWAP
        handling in the reference's actionAcceptance). Override the two
        components, not this composition — the sharded solver calls them
        separately (legs on the owning device, net on the replicated
        pairing grid)."""
        return self.swap_leg_acceptance(state, derived, constraint, aux, fwd) \
            & self.swap_leg_acceptance(state, derived, constraint, aux, rev) \
            & self.swap_net_acceptance(state, derived, constraint, aux, net)

    # -- candidate generation hints ---------------------------------------
    def source_score(self, state, derived, constraint, aux) -> jax.Array:
        """[B] — >0 means the broker should shed (rebalanceForBroker's
        requireLessLoad set)."""
        return self.broker_violations(state, derived, constraint, aux)

    def dest_score(self, state, derived, constraint, aux) -> jax.Array:
        """[B] — destination attractiveness; -inf = ineligible."""
        raise NotImplementedError

    def replica_weight(self, state, derived, constraint, aux) -> jax.Array:
        """[P, S] — which replicas to move first (SortedReplicas analogue)."""
        return replica_load_total(state)

    def target_dests(self, state, derived, constraint, aux,
                     cand_p: jax.Array, cand_s: jax.Array,
                     src_valid: jax.Array, rank_stride: int = 1,
                     rank_offset=0,
                     ) -> "tuple[jax.Array, jax.Array] | None":
        """Optional constructive per-card destination (analyzer.fill): for
        the selected source replicas ``(cand_p, cand_s)[k]``, return
        (dst_broker [k] int32, ok [k] bool) — one destination built for
        each card — or None when the goal has no per-card destination
        rule. The search appends the result as an extra column of the
        move grid; all acceptance/selection machinery applies unchanged,
        so a targeted destination is a HINT, never a bypass.

        ``rank_stride``/``rank_offset`` map local fill ranks onto a
        GLOBAL fill-position space (position = rank·stride + offset):
        the partition-sharded mesh passes (num_shards, shard) so each
        device claims an interleaved, collision-free slice of the shared
        deficit/headroom profile — without it every device fills the
        same positions and the targeted column collapses mesh quality
        (measured r5). Single-device callers keep the identity (1, 0)."""
        return None

    def direct_spec(self, state, derived, constraint, aux, num_topics: int):
        """The direct-assignment transport formulation
        (analyzer.direct; only meaningful when ``supports_direct``):
        ``(counts [G, B], lower [G, 1], upper [G, 1], group [P, S] int32,
        movable [P, S] bool)`` — the count plane the goal balances, its
        band, which group each replica slot belongs to, and which
        replicas the goal may relocate. ``G`` = 1 for cluster-wide count
        goals, ``num_topics`` for per-topic planes."""
        return None


def pair_improvement(values: jax.Array, deltas: CandidateDeltas,
                     delta: jax.Array, viol_fn) -> jax.Array:
    """Improvement of Σ viol(broker) restricted to the touched (src, dst)
    pair. ``values[B]`` is the per-broker quantity, ``delta[N]`` how much
    each candidate transfers, ``viol_fn(value, broker_idx)`` the violation
    magnitude (broker_idx lets per-broker limits be gathered)."""
    src, dst = deltas.src_broker, deltas.dst_broker
    before = viol_fn(values[src], src) + viol_fn(values[dst], dst)
    after = viol_fn(values[src] - delta, src) + viol_fn(values[dst] + delta, dst)
    return jnp.where(deltas.valid, before - after, -jnp.inf)


def gather_pair(arr: jax.Array, deltas: CandidateDeltas,
                column: int | None = None) -> tuple[jax.Array, jax.Array]:
    """(src_value, dst_value) per candidate from a [B] or [B, R] array."""
    if column is None:
        return arr[deltas.src_broker], arr[deltas.dst_broker]
    return arr[deltas.src_broker, column], arr[deltas.dst_broker, column]


def donor_widened_shed(values: jax.Array, lower, upper,
                       derived: DerivedState) -> jax.Array:
    """Per-broker shed pressure with donor widening
    (ResourceDistributionGoal.java:388 requireMoreLoad): anything above the
    upper band sheds; when some eligible broker sits below the lower band,
    every broker above the LOWER band becomes a donor for move-in.
    ``values`` is [B] (or [T, B] for per-topic bands with broadcastable
    lower/upper); masked to alive brokers."""
    eligible = derived.alive & derived.allowed_replica_move
    under_any = ((values < lower) & eligible).any(axis=-1, keepdims=True)
    over = jnp.maximum(values - upper, 0.0)
    donor = jnp.where(under_any, jnp.maximum(values - lower, 0.0), 0.0)
    return jnp.where(derived.alive, over + donor, 0.0)


def new_broker_gate(derived: DerivedState, deltas: CandidateDeltas) -> jax.Array:
    """When NEW brokers exist, only they may receive replicas
    (ResourceDistributionGoal.rebalanceByMovingLoadIn:444-447)."""
    has_new = derived.new_brokers.any()
    dst_is_new = derived.new_brokers[deltas.dst_broker]
    is_move = deltas.replica_delta > 0
    return jnp.where(has_new & is_move, dst_is_new, True)
