"""Soft distribution goals.

Reference parity: analyzer/goals/ResourceDistributionGoal.java (1,078 LoC;
per-resource balance band avg·(1±threshold·margin), move-out/move-in/swap),
ReplicaDistributionGoal.java / LeaderReplicaDistributionGoal.java /
TopicReplicaDistributionGoal.java over ReplicaDistributionAbstractGoal.java,
PotentialNwOutGoal.java, LeaderBytesInDistributionGoal.java,
PreferredLeaderElectionGoal.java, MinTopicLeadersPerBrokerGoal.java.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ...common.resources import Resource
from ...model.tensors import (
    is_leader_slot, replica_exists, replica_load_column, replica_load_total,
    topic_broker_leader_counts,
    topic_broker_replica_counts,
)
from ..candidates import CandidateDeltas
from ..derived import count_limits, resource_limits
from ..fill import (
    best_fit_dests, deficit_fill_dests, exclusive_rank, rank_within_group,
)
from .base import Goal, donor_widened_shed, new_broker_gate, pair_improvement


def _band_viol(value, lower, upper):
    return jnp.maximum(value - upper, 0.0) + jnp.maximum(lower - value, 0.0)


def _dest_eligible(derived):
    """Destination eligibility shared by dest_score and the targeted-dest
    kernels (new-broker gating per
    ResourceDistributionGoal.rebalanceByMovingLoadIn:444-447)."""
    has_new = derived.new_brokers.any()
    return jnp.where(has_new, derived.new_brokers,
                     derived.allowed_replica_move) & derived.alive


def _int_deficit_headroom(counts, lower, upper):
    """Integer (deficit, remaining-headroom) planes from a float count
    plane and band: deficit = whole replicas needed to reach the lower
    band (capped by what fits under the upper band), headroom = whole
    replicas addable beyond that while staying at or under the upper
    band. Shapes broadcast ([G, B] counts with [G, 1] or scalar bands)."""
    h_int = jnp.floor(jnp.maximum(upper - counts, 0.0) + 1e-6)
    d_int = jnp.minimum(h_int, jnp.ceil(
        jnp.maximum(lower - counts, 0.0) - 1e-6))
    return jnp.maximum(d_int, 0.0), h_int - jnp.maximum(d_int, 0.0)


@dataclasses.dataclass(frozen=True)
class ResourceDistributionGoal(Goal):
    """Per-resource balance band around the cluster-average utilization
    (ResourceDistributionGoal.java §A.1-A.2 of SURVEY.md)."""

    resource: Resource = Resource.DISK

    def _limits(self, state, derived, constraint):
        return resource_limits(state, derived, constraint, self.resource)

    def _low_util(self, derived, constraint):
        # avg ≤ low.utilization.threshold flips the goal into no-op
        # (over-provisioned detection; ResourceDistributionGoal.java:262-277).
        r = int(self.resource)
        return derived.avg_util[r] <= constraint.low_utilization_threshold[r]

    def broker_violations(self, state, derived, constraint, aux):
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)
        load = derived.broker_load[:, r]
        viol = _band_viol(load, lower, upper)
        viol = jnp.where(derived.alive & derived.allowed_replica_move, viol, 0.0)
        return jnp.where(self._low_util(derived, constraint),
                         jnp.zeros_like(viol), viol)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        # ResourceDistributionGoal.actionAcceptance (MOVE/LEADERSHIP arm):
        # 1) if src above lower AND dst under upper now, require both to stay
        #    in band after; 2) otherwise require the move not to increase the
        #    pairwise utilization gap.
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)
        load = derived.broker_load[:, r]
        d = deltas.load_delta[:, r]
        src, dst = deltas.src_broker, deltas.dst_broker
        eps = 1e-6
        # Round-start loads shifted by same-round higher-ranked candidates.
        ls = load[src] - deltas.pre_load("pre_src_load", r)
        ld = load[dst] + deltas.pre_load("pre_dst_load", r)

        # BRANCH CHOICE uses the UNSHIFTED loads: the pre terms may
        # overcount (rejected earlier candidates are included), and a
        # shifted predicate could flip from the strict stays_in_band branch
        # to the looser no_worse branch — non-monotone in the overcount,
        # breaking the conservative-relaxation contract. The band/util
        # CHECKS inside each branch use the shifted loads, where overcount
        # is strictly stricter.
        src_above_lower = load[src] >= lower[src] - eps
        dst_under_upper = load[dst] <= upper[dst] + eps
        stays_in_band = (ld + d <= upper[dst] + eps) \
            & (ls - d >= lower[src] - eps)

        cap_src = jnp.maximum(state.capacity[src, r], 1e-9)
        cap_dst = jnp.maximum(state.capacity[dst, r], 1e-9)
        util_src_before = ls / cap_src
        util_dst_after = (ld + d) / cap_dst
        no_worse = util_dst_after <= util_src_before + eps

        accept = jnp.where(src_above_lower & dst_under_upper, stays_in_band, no_worse)
        return accept | (d <= eps) | self._low_util(derived, constraint) \
            | (~derived.alive[src])

    def improvement(self, state, derived, constraint, aux, deltas):
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)

        def viol(value, idx):
            return _band_viol(value, lower[idx], upper[idx])

        imp = pair_improvement(derived.broker_load[:, r], deltas,
                               deltas.load_delta[:, r], viol)
        # Tiebreak among BAND-FIXING moves only: prefer the one narrowing
        # the pair gap most. Never applied to imp <= 0 candidates — an
        # unconditional variance term accepts unbounded in-band refinement
        # churn (O(P) moves the reference never makes: its greedy only
        # acts on brokers outside the band, ResourceDistributionGoal
        # .java:380-435).
        load = derived.broker_load[:, r]
        d = deltas.load_delta[:, r]
        src, dst = deltas.src_broker, deltas.dst_broker
        gap_before = load[src] - load[dst]
        gap_after = gap_before - 2 * d
        var_gain = (gap_before ** 2 - gap_after ** 2) * 1e-6
        return jnp.where(deltas.valid,
                         imp + jnp.where(imp > 0, var_gain, 0.0), -jnp.inf) \
            * new_broker_gate(derived, deltas)

    def source_score(self, state, derived, constraint, aux):
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)
        shed = donor_widened_shed(derived.broker_load[:, r], lower, upper,
                                  derived)
        # Low-utilization state is a no-op for balancing (the goal flips to
        # over-provisioned detection, ResourceDistributionGoal.java:262-277):
        # no sources, so the search — fused or per-goal — generates no
        # candidates, consistent with broker_violations returning zeros.
        return jnp.where(self._low_util(derived, constraint),
                         jnp.zeros_like(shed), shed)

    def dest_score(self, state, derived, constraint, aux):
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)
        load = derived.broker_load[:, r]
        headroom = upper - load
        under_bonus = jnp.maximum(lower - load, 0.0) * 10.0
        has_new = derived.new_brokers.any()
        eligible = jnp.where(has_new, derived.new_brokers, derived.allowed_replica_move)
        return jnp.where(eligible & (headroom > 0), headroom + under_bonus, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        # TWO-SIDED FIT-PRIORITY ordering (r5): replicas that can actually
        # complete an in-band move — small enough for some destination's
        # band gap AND for their own broker's surplus above its lower
        # band — rank above the rest (largest-fitting first,
        # first-fit-decreasing). The convergence tail stalls on the SRC
        # side of the stays_in_band acceptance: donors just above their
        # lower band cannot shed a replica bigger than their surplus, and
        # a size-descending order fills the grid with exactly those
        # vetoed moves (~52 accepted/round of a 256-source grid at 7k).
        # A pure feasibility MASK measured neutral-to-negative in r4
        # (oversized replicas must stay reachable for the no-worse
        # branch); this only reorders priority.
        r = int(self.resource)
        size = replica_load_column(state, r)
        lower, upper, _cap = self._limits(state, derived, constraint)
        load = derived.broker_load[:, r]
        headroom = upper - load
        elig = _dest_eligible(derived) & (headroom > 0)
        max_gap = jnp.max(jnp.where(elig, headroom, 0.0))
        b = state.num_brokers
        src_room = jnp.concatenate([load - lower, jnp.array([0.0])])[
            jnp.where(state.assignment >= 0, state.assignment, b)]
        peak = jnp.max(size) + 1.0
        fits = (size <= max_gap) & (size <= src_room) & (size > 0)
        return jnp.where(fits, peak + size, size)

    def target_dests(self, state, derived, constraint, aux,
                     cand_p, cand_s, src_valid, rank_stride=1,
                     rank_offset=0):
        from ..fill import class_enabled
        if not class_enabled(self):
            return None
        # Size-matched (first-fit-decreasing) destination per card: the
        # shared top-num_dests list starves the convergence tail — once
        # only small under-band gaps remain, a heavy card fits none of
        # the listed destinations and the round stalls at a handful of
        # accepted moves (r4, docs/DESIGN.md "destination-limited tail").
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)
        headroom = upper - derived.broker_load[:, r]
        size = replica_load_column(state, r)[cand_p, cand_s]
        rank = exclusive_rank(src_valid) * rank_stride + rank_offset
        dst, ok = best_fit_dests(size, rank, headroom,
                                 _dest_eligible(derived) & (headroom > 0))
        return dst, ok & src_valid \
            & ~self._low_util(derived, constraint)

    def swap_leg_acceptance(self, state, derived, constraint, aux, leg):
        # Judged on the net transfer only (leg-wise band checks would veto
        # swaps whose net effect stays inside the band).
        return jnp.ones(leg.valid.shape[0], dtype=bool)

    def swap_net_acceptance(self, state, derived, constraint, aux, net):
        # Net transfer is SIGNED; accept iff the PAIR's band violation does
        # not worsen (two-sided — the one-sided move acceptance would let a
        # src-gaining swap blow past the source's band).
        r = int(self.resource)
        lower, upper, _cap = self._limits(state, derived, constraint)
        load = derived.broker_load[:, r]
        d = net.load_delta[:, r]
        src, dst = net.src_broker, net.dst_broker

        def viol(value, idx):
            return _band_viol(value, lower[idx], upper[idx])

        before = viol(load[src], src) + viol(load[dst], dst)
        after = viol(load[src] - d, src) + viol(load[dst] + d, dst)
        return (after <= before + 1e-6) \
            | self._low_util(derived, constraint)


@dataclasses.dataclass(frozen=True)
class CountDistributionGoal(Goal):
    """Replica- / leader-count balance
    (ReplicaDistributionGoal.java, LeaderReplicaDistributionGoal.java)."""

    leaders: bool = False
    count_based: bool = True
    supports_direct: bool = True

    def _counts(self, derived):
        return (derived.broker_leaders if self.leaders
                else derived.broker_replicas).astype(jnp.float32)

    def _limits(self, derived, constraint):
        if self.leaders:
            return count_limits(derived.avg_leaders,
                                constraint.leader_replica_balance_threshold)
        return count_limits(derived.avg_replicas, constraint.replica_balance_threshold)

    def _delta(self, deltas):
        return (deltas.leader_delta if self.leaders else deltas.replica_delta) \
            .astype(jnp.float32)

    def broker_violations(self, state, derived, constraint, aux):
        lower, upper = self._limits(derived, constraint)
        viol = _band_viol(self._counts(derived), lower, upper)
        return jnp.where(derived.alive, viol, 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        # ReplicaDistributionGoal.actionAcceptance: leadership/swap ACCEPT;
        # moves must keep dst under upper and src above lower (counting
        # same-round higher-ranked candidates' in/outflow).
        lower, upper = self._limits(derived, constraint)
        counts = self._counts(derived)
        d = self._delta(deltas)
        pre_dst = deltas.pre0("pre_dst_leaders" if self.leaders
                              else "pre_dst_count")
        pre_src = deltas.pre0("pre_src_leaders" if self.leaders
                              else "pre_src_count")
        dst_ok = counts[deltas.dst_broker] + pre_dst + d <= upper + 1e-6
        src_ok = counts[deltas.src_broker] - pre_src - d >= lower - 1e-6
        return (d == 0) | (dst_ok & src_ok) | (~derived.alive[deltas.src_broker])

    def improvement(self, state, derived, constraint, aux, deltas):
        lower, upper = self._limits(derived, constraint)

        def viol(value, idx):
            return _band_viol(value, lower, upper)

        imp = pair_improvement(self._counts(derived), deltas, self._delta(deltas), viol)
        counts = self._counts(derived)
        d = self._delta(deltas)
        gap_before = counts[deltas.src_broker] - counts[deltas.dst_broker]
        # Band-fixing tiebreak only (see ResourceDistributionGoal): an
        # unconditional variance term would accept O(P) in-band churn.
        var_gain = (gap_before ** 2 - (gap_before - 2 * d) ** 2) * 1e-6
        return jnp.where(deltas.valid,
                         imp + jnp.where(imp > 0, var_gain, 0.0), -jnp.inf) \
            * new_broker_gate(derived, deltas)

    def source_score(self, state, derived, constraint, aux):
        lower, upper = self._limits(derived, constraint)
        return donor_widened_shed(self._counts(derived), lower, upper, derived)

    def dest_score(self, state, derived, constraint, aux):
        lower, upper = self._limits(derived, constraint)
        counts = self._counts(derived)
        headroom = upper - counts
        under_bonus = jnp.maximum(lower - counts, 0.0) * 10.0
        has_new = derived.new_brokers.any()
        eligible = jnp.where(has_new, derived.new_brokers, derived.allowed_replica_move)
        return jnp.where(eligible & (headroom > 0), headroom + under_bonus, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        w = -replica_load_total(state)  # light replicas first
        if self.leaders:
            return jnp.where(is_leader_slot(state), w, -jnp.inf)
        return w

    def target_dests(self, state, derived, constraint, aux,
                     cand_p, cand_s, src_valid, rank_stride=1,
                     rank_offset=0):
        from ..fill import class_enabled
        if not class_enabled(self):
            return None
        # Deficit-proportional fill over the single cluster-wide count
        # band (T = 1 case of the TopicReplica kernel): under-band
        # brokers absorb cards first, then remaining whole-count
        # headroom, each destination at most its integer gap per round.
        lower, upper = self._limits(derived, constraint)
        counts = self._counts(derived)
        deficit, headroom = _int_deficit_headroom(counts[None, :],
                                                  lower, upper)
        rank = exclusive_rank(src_valid) * rank_stride + rank_offset
        dst, ok = deficit_fill_dests(
            jnp.zeros_like(cand_p), rank, deficit,
            headroom, _dest_eligible(derived))
        return dst, ok & src_valid

    def direct_spec(self, state, derived, constraint, aux, num_topics):
        # One cluster-wide group: the [B] count plane and its band. The
        # leaders variant relocates LEADER replicas (leadership travels
        # with the slot, so a relocation shifts the leader count exactly
        # like the greedy's leader-replica moves).
        lower, upper = self._limits(derived, constraint)
        counts = self._counts(derived)[None, :]
        group = jnp.zeros(state.assignment.shape, jnp.int32)
        movable = is_leader_slot(state) if self.leaders \
            else replica_exists(state)
        return (counts, jnp.reshape(lower, (1, 1)).astype(jnp.float32),
                jnp.reshape(upper, (1, 1)).astype(jnp.float32), group,
                movable)

    def swap_leg_acceptance(self, state, derived, constraint, aux, leg):
        # Counts are judged on the net transfer only.
        return jnp.ones(leg.valid.shape[0], dtype=bool)

    def swap_net_acceptance(self, state, derived, constraint, aux, net):
        # Replica counts are swap-invariant; leadership may transfer with
        # the heavier replica (net.leader_delta ∈ {-1, 0, 1}, signed) —
        # accept iff the pair's count-band violation does not worsen.
        lower, upper = self._limits(derived, constraint)
        counts = self._counts(derived)
        d = self._delta(net)

        def viol(value):
            return _band_viol(value, lower, upper)

        src, dst = net.src_broker, net.dst_broker
        before = viol(counts[src]) + viol(counts[dst])
        after = viol(counts[src] - d) + viol(counts[dst] + d)
        return after <= before + 1e-6


@dataclasses.dataclass(frozen=True)
class TopicReplicaDistributionGoal(Goal):
    """Per-topic replica balance across brokers
    (TopicReplicaDistributionGoal.java:594LoC). Uses a [T, B] count plane —
    fine up to mid-size clusters; sharded over the mesh at large T×B."""

    prefers_wide_batches: bool = True
    count_based: bool = True
    supports_direct: bool = True

    def prepare_partial(self, state, num_topics):
        return {"counts": topic_broker_replica_counts(state, num_topics)
                .astype(jnp.float32)}

    def partial_from_agg(self, agg):
        return {"counts": agg.topic_counts.astype(jnp.float32)}

    def finalize_aux(self, partial, state, derived, constraint):
        counts = partial["counts"]
        n_alive = jnp.maximum(derived.alive.sum(), 1)
        avg = (counts * derived.alive[None, :]).sum(axis=1) / n_alive  # [T]
        upper = jnp.ceil(avg * constraint.topic_replica_balance_threshold)
        lower = jnp.floor(avg / constraint.topic_replica_balance_threshold)
        return {"counts": counts, "upper": upper, "lower": lower}

    def broker_violations(self, state, derived, constraint, aux):
        viol = _band_viol(aux["counts"], aux["lower"][:, None], aux["upper"][:, None])
        return jnp.where(derived.alive, viol.sum(axis=0), 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        t = deltas.topic
        d = deltas.replica_delta.astype(jnp.float32)
        dst_cnt = aux["counts"][t, deltas.dst_broker] \
            + deltas.pre0("pre_dst_topic_count")
        src_cnt = aux["counts"][t, deltas.src_broker] \
            - deltas.pre0("pre_src_topic_count")
        dst_ok = dst_cnt + d <= aux["upper"][t] + 1e-6
        src_ok = src_cnt - d >= aux["lower"][t] - 1e-6
        return (d == 0) | (dst_ok & src_ok) | (~derived.alive[deltas.src_broker])

    def improvement(self, state, derived, constraint, aux, deltas):
        t = deltas.topic
        d = deltas.replica_delta.astype(jnp.float32)
        up, lo = aux["upper"][t], aux["lower"][t]
        src_cnt = aux["counts"][t, deltas.src_broker]
        dst_cnt = aux["counts"][t, deltas.dst_broker]
        before = _band_viol(src_cnt, lo, up) + _band_viol(dst_cnt, lo, up)
        after = _band_viol(src_cnt - d, lo, up) + _band_viol(dst_cnt + d, lo, up)
        imp = before - after
        # Band-fixing tiebreak only (see ResourceDistributionGoal).
        var_gain = ((src_cnt - dst_cnt) ** 2 - (src_cnt - dst_cnt - 2 * d) ** 2) * 1e-6
        return jnp.where(deltas.valid,
                         imp + jnp.where(imp > 0, var_gain, 0.0), -jnp.inf) \
            * new_broker_gate(derived, deltas)

    def _over_donor(self, derived, aux):
        """[T, B] — per-(topic, broker) shed pressure with donor widening."""
        return donor_widened_shed(aux["counts"], aux["lower"][:, None],
                                  aux["upper"][:, None], derived)

    def source_score(self, state, derived, constraint, aux):
        score = self._over_donor(derived, aux).sum(axis=0)
        return jnp.where(derived.alive, score, 0.0)

    def dest_score(self, state, derived, constraint, aux):
        headroom = jnp.maximum(aux["upper"][:, None] - aux["counts"], 0.0).sum(axis=0)
        has_new = derived.new_brokers.any()
        eligible = jnp.where(has_new, derived.new_brokers, derived.allowed_replica_move)
        return jnp.where(eligible, headroom, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        b = state.num_brokers
        t = state.topic[:, None]
        slot_b = jnp.clip(state.assignment, 0, b - 1)
        pressure = self._over_donor(derived, aux)
        w = pressure[t.repeat(state.max_replication_factor, 1), slot_b]
        return jnp.where(replica_exists(state), w, -jnp.inf)

    def target_dests(self, state, derived, constraint, aux,
                     cand_p, cand_s, src_valid, rank_stride=1,
                     rank_offset=0):
        from ..fill import class_enabled
        if not class_enabled(self):
            return None
        # Per-topic deficit fill: the round-count bottleneck of the 7k/1M
        # north star (r4: ~65% of wall-clock) was this goal funneling
        # thousands of per-topic cards through ≤ num_dests shared
        # destinations — each card instead targets position rank-in-topic
        # of its topic's [deficit | headroom] profile, so a round's joint
        # assignment respects every (topic, broker) integer gap. Measured
        # at 7k (r5): the reachable fixed point deepens from residual
        # violation 1497 (r4, destination-starved) to ~53; a
        # deficit-only variant saved nothing (327 s vs 323 s) at worse
        # residual (80), so the full profile stays.
        t = state.topic[cand_p]
        deficit, headroom = _int_deficit_headroom(
            aux["counts"], aux["lower"][:, None], aux["upper"][:, None])
        rank = rank_within_group(t, src_valid) * rank_stride + rank_offset
        dst, ok = deficit_fill_dests(t, rank,
                                     deficit, headroom,
                                     _dest_eligible(derived))
        return dst, ok & src_valid

    def direct_spec(self, state, derived, constraint, aux, num_topics):
        # Per-topic groups over the [T, B] count plane (the aux the goal
        # already maintains); every existing replica is movable, grouped
        # by its partition's topic.
        group = jnp.broadcast_to(state.topic[:, None],
                                 state.assignment.shape).astype(jnp.int32)
        return (aux["counts"], aux["lower"][:, None].astype(jnp.float32),
                aux["upper"][:, None].astype(jnp.float32), group,
                replica_exists(state))


@dataclasses.dataclass(frozen=True)
class PotentialNwOutGoal(Goal):
    """Keep potential NW-out (all replicas promoted) under the outbound
    capacity limit (PotentialNwOutGoal.java:367LoC)."""

    def _limit(self, state, constraint):
        r = int(Resource.NW_OUT)
        return constraint.capacity_threshold[r] * state.capacity[:, r]

    def broker_violations(self, state, derived, constraint, aux):
        limit = self._limit(state, constraint)
        return jnp.where(derived.alive,
                         jnp.maximum(derived.pot_nw_out - limit, 0.0), 0.0)

    def _pot_delta(self, state, deltas):
        # Moves shift the partition's full leader NW_OUT potential; pure
        # leadership moves don't change which brokers host replicas.
        nw = state.leader_load[deltas.partition, int(Resource.NW_OUT)]
        return jnp.where(deltas.replica_delta > 0, nw, 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        limit = self._limit(state, constraint)
        d = self._pot_delta(state, deltas)
        dst_after = derived.pot_nw_out[deltas.dst_broker] \
            + deltas.pre0("pre_dst_pot") + d
        # Accept if destination stays within limit, or the source was
        # already violating (net improvement allowed).
        src_viol = derived.pot_nw_out[deltas.src_broker] > limit[deltas.src_broker]
        return (dst_after <= limit[deltas.dst_broker] + 1e-6) | (d <= 0) | src_viol

    def improvement(self, state, derived, constraint, aux, deltas):
        limit = self._limit(state, constraint)

        def viol(value, idx):
            return jnp.maximum(value - limit[idx], 0.0)

        return pair_improvement(derived.pot_nw_out, deltas,
                                self._pot_delta(state, deltas), viol)

    def dest_score(self, state, derived, constraint, aux):
        headroom = self._limit(state, constraint) - derived.pot_nw_out
        return jnp.where(derived.allowed_replica_move & (headroom > 0),
                         headroom, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        nw = state.leader_load[:, int(Resource.NW_OUT)]
        return jnp.where(replica_exists(state), nw[:, None], -jnp.inf)


@dataclasses.dataclass(frozen=True)
class LeaderBytesInDistributionGoal(Goal):
    """Balance leader bytes-in across brokers via leadership moves
    (LeaderBytesInDistributionGoal.java:288LoC)."""

    def prepare_partial(self, state, num_topics):
        from ...model.tensors import leader_bytes_in
        return {"lbi": leader_bytes_in(state)}

    def partial_from_agg(self, agg):
        return {"lbi": agg.lbi}

    def finalize_aux(self, partial, state, derived, constraint):
        lbi = partial["lbi"]
        n = jnp.maximum(derived.allowed_leadership.sum(), 1)
        avg = (lbi * derived.allowed_leadership).sum() / n
        return {"lbi": lbi, "avg": avg}

    def _upper(self, aux, constraint):
        t = constraint.resource_balance_threshold[int(Resource.NW_IN)]
        return aux["avg"] * t

    def broker_violations(self, state, derived, constraint, aux):
        upper = self._upper(aux, constraint)
        return jnp.where(derived.alive, jnp.maximum(aux["lbi"] - upper, 0.0), 0.0)

    def _lbi_delta(self, state, deltas):
        nw_in = state.leader_load[deltas.partition, int(Resource.NW_IN)]
        return jnp.where(deltas.leader_delta > 0, nw_in, 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        upper = self._upper(aux, constraint)
        d = self._lbi_delta(state, deltas)
        dst_after = aux["lbi"][deltas.dst_broker] \
            + deltas.pre0("pre_dst_lbi") + d
        src_over = aux["lbi"][deltas.src_broker] > upper
        return (dst_after <= upper + 1e-6) | (d <= 0) | src_over

    def improvement(self, state, derived, constraint, aux, deltas):
        upper = self._upper(aux, constraint)

        def viol(value, idx):
            return jnp.maximum(value - upper, 0.0)

        imp = pair_improvement(aux["lbi"], deltas, self._lbi_delta(state, deltas), viol)
        lbi = aux["lbi"]
        d = self._lbi_delta(state, deltas)
        gap = lbi[deltas.src_broker] - lbi[deltas.dst_broker]
        var_gain = (gap ** 2 - (gap - 2 * d) ** 2) * 1e-6
        return jnp.where(deltas.valid, imp + var_gain, -jnp.inf)

    def dest_score(self, state, derived, constraint, aux):
        headroom = self._upper(aux, constraint) - aux["lbi"]
        return jnp.where(derived.allowed_leadership, headroom, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        nw_in = state.leader_load[:, int(Resource.NW_IN)]
        return jnp.where(is_leader_slot(state), nw_in[:, None], -jnp.inf)


@dataclasses.dataclass(frozen=True)
class PreferredLeaderElectionGoal(Goal):
    """Make the PREFERRED replica the leader everywhere — the first
    replica in list order whose broker is allowed to lead
    (PreferredLeaderElectionGoal.java:232LoC: demoted/excluded brokers are
    skipped, so demotion moves leadership to the next eligible replica,
    not merely to slot 0). Leadership-only."""

    def _preferred_slot(self, state, derived):
        """[P] int32 — first existing slot whose broker may lead;
        ``S`` (out of range) when no slot is eligible."""
        b = state.num_brokers
        exists = replica_exists(state)
        ok = exists & derived.allowed_leadership[
            jnp.clip(state.assignment, 0, b - 1)]
        s = state.max_replication_factor
        slot_ids = jnp.arange(s, dtype=jnp.int32)[None, :]
        return jnp.where(ok, slot_ids, s).min(axis=1)

    def _misled(self, state, derived):
        """[P] bool — leader differs from the preferred eligible slot."""
        pref = self._preferred_slot(state, derived)
        s = state.max_replication_factor
        return state.partition_mask & (pref < s) \
            & (state.leader_slot != pref)

    def broker_violations(self, state, derived, constraint, aux):
        misled = self._misled(state, derived)
        b = state.num_brokers
        lead_b = jnp.take_along_axis(
            state.assignment, jnp.maximum(state.leader_slot, 0)[:, None], axis=1)[:, 0]
        seg = jnp.where(misled, jnp.clip(lead_b, 0, b - 1), b)
        return jax.ops.segment_sum(misled.astype(jnp.float32), seg,
                                   num_segments=b + 1)[:b]

    def improvement(self, state, derived, constraint, aux, deltas):
        pref = self._preferred_slot(state, derived)[deltas.partition]
        is_lead = deltas.replica_delta == 0
        fixes = (deltas.src_slot != pref) & (deltas.dst_slot == pref)
        imp = jnp.where(is_lead & fixes, 1.0, 0.0)
        return jnp.where(deltas.valid, imp, -jnp.inf)

    def dest_score(self, state, derived, constraint, aux):
        return jnp.where(derived.allowed_leadership, 0.0, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        misled = self._misled(state, derived)[:, None]
        return jnp.where(is_leader_slot(state) & misled, 1.0, -jnp.inf)

    def source_score(self, state, derived, constraint, aux):
        return jnp.ones(state.num_brokers)


@dataclasses.dataclass(frozen=True)
class MinTopicLeadersPerBrokerGoal(Goal):
    """Brokers must each host at least ``min_leaders`` leaders of every
    interested topic (MinTopicLeadersPerBrokerGoal.java:465LoC). With the
    default empty interest set this is a no-op, as in the reference."""

    min_leaders: int = 0

    def prepare_partial(self, state, num_topics):
        if self.min_leaders <= 0:
            return None
        return {"leader_counts": topic_broker_leader_counts(state, num_topics)}

    def broker_violations(self, state, derived, constraint, aux):
        if aux is None:
            return jnp.zeros(state.num_brokers)
        deficit = jnp.maximum(self.min_leaders - aux["leader_counts"], 0)
        return jnp.where(derived.alive, deficit.sum(axis=0).astype(jnp.float32), 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        if aux is None:
            return jnp.ones(deltas.valid.shape[0], dtype=bool)
        cnt = aux["leader_counts"][deltas.topic, deltas.src_broker] \
            - deltas.pre0("pre_src_topic_leaders")
        d = deltas.leader_delta
        return (d == 0) | (cnt - d >= self.min_leaders)

    def improvement(self, state, derived, constraint, aux, deltas):
        return jnp.where(deltas.valid, 0.0, -jnp.inf)

    def dest_score(self, state, derived, constraint, aux):
        return jnp.where(derived.allowed_leadership, 0.0, -jnp.inf)
