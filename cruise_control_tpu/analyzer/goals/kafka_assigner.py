"""Kafka-assigner emulation mode.

Reference parity: analyzer/kafkaassigner/ —
KafkaAssignerEvenRackAwareGoal.java:523 (strict rack-awareness PLUS an even
per-broker replica ceiling, the kafka-assigner tool's placement contract)
and KafkaAssignerDiskUsageDistributionGoal.java:722 (disk balance within a
threshold band). The reference's swap-based inner loop is re-expressed as
the batched move search: the conflict-free accept step reaches the same
balance band invariant that the pairwise swaps do, one fused round at a
time (the two halves of a swap land in consecutive rounds).

Reference-parity deviation (deliberate): the reference's swap inner loop
never exceeds the even ceiling at ANY intermediate state, while this
goal's deadlock-breaking acceptance lets a rack-duplicate-fixing move
land on a broker at ceiling+1 transiently (see ``acceptance``); later
rounds shed the overage (2·rack + count strictly decreases, so the
two-step path terminates). Failure mode if the shed move is vetoed by a
stacked goal or the round cap: the final placement can retain a
ceiling+1 broker — the overage is counted in ``broker_violations``, so
the hard goal REPORTS as violated (OptimizationFailureError) rather than
failing silently. Randomized skewed-rack sweeps exercising both the
curated deadlock fixture and the property-level invariant live in
tests/test_kafka_assigner_property.py.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ...common.resources import Resource
from ..candidates import CandidateDeltas
from .base import Goal, pair_improvement
from .rack import RackAwareGoal, _duplicate_mask


@dataclasses.dataclass(frozen=True)
class KafkaAssignerEvenRackAwareGoal(RackAwareGoal):
    """Rack-aware + ceil(total/alive) replica-count ceiling per broker."""

    name: str = "KafkaAssignerEvenRackAwareGoal"
    is_hard: bool = True
    # The reference's inner loop is SWAP-based (per-position exchanges
    # that never disturb per-broker counts); the move search covers most
    # shapes, but max-tight layouts (a rack at exactly B/RF brokers)
    # need a count-preserving exchange: a duplicate leaves its crowded
    # rack for an at-ceiling broker whose own movable replica returns to
    # the freed under-ceiling broker. See swap_improvement/
    # swap_dest_score below.
    supports_swap: bool = True

    def _ceiling(self, derived) -> jnp.ndarray:
        total = (derived.broker_replicas * derived.alive).sum()
        n = jnp.maximum(derived.alive.sum(), 1)
        return jnp.ceil(total / n).astype(jnp.int32)

    def broker_violations(self, state, derived, constraint, aux):
        rack_v = super().broker_violations(state, derived, constraint, aux)
        over = jnp.maximum(
            derived.broker_replicas - self._ceiling(derived), 0)
        return rack_v + jnp.where(derived.alive, over, 0).astype(jnp.float32)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        rack_ok = super().acceptance(state, derived, constraint, aux, deltas)
        cap = self._ceiling(derived)
        dst_after = derived.broker_replicas[deltas.dst_broker] \
            + deltas.pre0("pre_dst_count") + 1
        under_cap = dst_after <= cap
        # Deadlock breaker: a RACK-duplicate-fixing move may overshoot the
        # even ceiling by ONE. On skewed clusters every under-ceiling
        # broker in a partition's free rack can sit exactly at the
        # ceiling, and a pure greedy stalls where the reference's
        # swap-based inner loop (KafkaAssignerEvenRackAwareGoal.java's
        # per-position swaps) proceeds; the overshoot converts the rack
        # violation into a count violation that later rounds shed
        # (improvement weights rack 2x count, so both steps score > 0).
        #
        # Overshoot GUARD (r5 property-sweep finding): an overshoot onto a
        # broker with no shed channel — no hosted replica with a feasible
        # rack-compatible under-cap destination — is a dead end: the
        # ceiling+1 count violation can never be shed, and near-tight
        # layouts (e.g. 9/4/4/1 racks at RF 2) stalled exactly there. The
        # reference never hits this because its swap exchanges the two
        # replicas atomically; here the overshoot leg is only admitted
        # where the shed leg exists, so the two-step path stays live.
        fixes_dup = _duplicate_mask(state)[deltas.partition, deltas.src_slot]
        shed_count = self._shed_count_per_broker(state, derived)
        # COUNT-matched, not boolean: each same-round overshoot onto a
        # broker must claim a DISTINCT shed channel (pre_dst_count is the
        # cumulative same-round inflow, conservatively overcounted), else
        # two overshoots can share one channel and strand a ceiling+1
        # overage on a broker that can no longer shed.
        tolerant = fixes_dup & (dst_after <= cap + 1) \
            & (under_cap
               | (deltas.pre0("pre_dst_count")
                  < shed_count[deltas.dst_broker]))
        is_move = deltas.replica_delta > 0
        return rack_ok & jnp.where(is_move, under_cap | tolerant, True)

    def improvement(self, state, derived, constraint, aux, deltas):
        rack_imp = super().improvement(state, derived, constraint, aux, deltas)
        cap = self._ceiling(derived).astype(jnp.float32)
        counts = derived.broker_replicas.astype(jnp.float32)
        count_imp = pair_improvement(
            counts, deltas, deltas.replica_delta.astype(jnp.float32),
            lambda v, _b: jnp.maximum(v - cap, 0.0))
        # Rack fixes outweigh the count violation they may create (the
        # two-step deadlock-breaking path above must score positive at
        # both steps; terminates because 2*rack + count strictly falls).
        return jnp.where(deltas.valid, 2.0 * rack_imp + count_imp, -jnp.inf)

    def source_score(self, state, derived, constraint, aux):
        return self.broker_violations(state, derived, constraint, aux)

    def dest_score(self, state, derived, constraint, aux):
        cap = self._ceiling(derived)
        room = (cap - derived.broker_replicas).astype(jnp.float32)
        # room >= 0 (not > 0): AT-CAP brokers must stay in the candidate
        # grid — the duplicate-fixing overshoot path in ``acceptance`` is
        # unreachable if dest_score filters them to -inf before scoring.
        return jnp.where(derived.allowed_replica_move & (room >= 0), room,
                         -jnp.inf)

    def swap_leg_acceptance(self, state, derived, constraint, aux, leg):
        # Swaps keep per-broker counts, so only the RACK check applies per
        # leg — the inherited move acceptance (count ceiling) would veto
        # every swap once brokers sit at the even ceiling (the steady state
        # of kafka-assigner mode).
        return RackAwareGoal.acceptance(self, state, derived, constraint,
                                        aux, leg)

    def swap_improvement(self, state, derived, constraint, aux,
                         fwd, rev, net):
        # Each directional leg judged as a rack move (duplicate fixed
        # minus conflict created); counts are swap-invariant so the even
        # ceiling needs no term. A swap that fixes one duplicate while
        # creating another sums to 0 and is never applied.
        imp_f = RackAwareGoal.improvement(self, state, derived, constraint,
                                          aux, fwd)
        imp_r = RackAwareGoal.improvement(self, state, derived, constraint,
                                          aux, rev)
        both = jnp.where(jnp.isfinite(imp_f), imp_f, 0.0) \
            + jnp.where(jnp.isfinite(imp_r), imp_r, 0.0)
        return jnp.where(net.valid, both, -jnp.inf)

    def swap_dest_score(self, state, derived, constraint, aux):
        # Counterparties for the exchange: AT-ceiling brokers with a shed
        # channel (a hosted replica that can move into an under-ceiling
        # rack without creating a duplicate — the replica the reverse leg
        # sends back). dest_score would exclude them all (room <= 0),
        # which is exactly why moves alone stall on max-tight layouts.
        # OVER-ceiling brokers are EXCLUDED: a count-preserving exchange
        # does nothing for their overage but consumes the very replica
        # their shed needs (the measured strand: a ceiling+1 broker whose
        # channel a swap ate). The SOURCE side needs no twin exclusion:
        # move passes run to their fixed point before each swap pass, so
        # a shed-feasible replica on an over broker (duplicate or not)
        # has already been moved out as a plain shed/dup-fix before any
        # swap could trade it away.
        over = derived.broker_replicas > self._ceiling(derived)
        has_shed = (self._shed_count_per_broker(state, derived) > 0
                    ).astype(jnp.float32)
        ok = derived.allowed_replica_move & derived.alive & ~over
        return jnp.where(ok, has_shed + 0.1, -jnp.inf)

    def _shed_count_per_broker(self, state, derived):
        """[B] int32 — number of hosted replicas with a feasible
        rack-compatible strictly-under-cap destination (shed channels);
        shared by the overshoot guard and swap_dest_score."""
        _dup_ok, shed_ok = self._rack_dest_feasibility(state, derived)
        b = state.num_brokers
        seg = jnp.where(state.assignment >= 0, state.assignment, b)
        return jnp.zeros(b + 1, jnp.int32).at[seg].add(
            shed_ok.astype(jnp.int32))[:b]

    def _rack_dest_feasibility(self, state, derived):
        """([P, S] dup-feasible, [P, S] shed-feasible): does a
        rack-compatible destination currently exist for this replica —
        at-cap brokers count for duplicate fixes (the ceiling+1 overshoot
        path), strictly-under-cap for plain count sheds. A replica's
        destination rack may be (a) any rack the partition does not use,
        or (b) its OWN rack (same-rack relocation never creates a
        duplicate). Rack scatter sizes are bounded by B (rack ids < B),
        so everything stays static-shaped."""
        from .rack import _slot_racks
        from ...model.tensors import replica_exists

        b = state.num_brokers
        room = self._ceiling(derived) - derived.broker_replicas
        ok = derived.allowed_replica_move & derived.alive
        under = ok & (room > 0)
        at = ok & (room >= 0)
        rack_of = jnp.clip(state.rack, 0, b - 1)
        n_under_by_rack = jnp.zeros(b, jnp.int32).at[rack_of].add(
            under.astype(jnp.int32))
        n_at_by_rack = jnp.zeros(b, jnp.int32).at[rack_of].add(
            at.astype(jnp.int32))      # [B]-indexed by rack id

        racks = _slot_racks(state)          # [P, S]; empty slots negative
        exists = replica_exists(state)
        same = racks[:, :, None] == racks[:, None, :]
        s = state.max_replication_factor
        earlier = jnp.tril(jnp.ones((s, s), dtype=bool), k=-1)[None]
        first_occ = exists & ~(same & earlier).any(axis=2)
        safe_racks = jnp.clip(racks, 0, b - 1)

        own_broker = jnp.where(state.assignment >= 0, state.assignment, b)

        def feasible(room, n_by_rack):
            # (a) an unused rack with room: #rooms racks > #distinct used
            # rooms racks (used non-room racks never block an unused one).
            has_room = n_by_rack > 0
            n_rooms = has_room.sum()
            used_rooms = (first_occ & has_room[safe_racks]).sum(axis=1)
            unused_rack = (n_rooms > used_rooms)[:, None]         # [P, 1]
            # (b) own-rack relocation: this slot's rack has a room-bearing
            # broker OTHER THAN the replica's own, and no other slot of
            # the partition shares the rack. The own broker must be
            # excluded from its rack's room count: a replica cannot
            # relocate onto the broker already hosting it, and counting
            # it manufactured a self-referential "shed channel" that let
            # the overshoot guard admit a same-round ceiling+1 overshoot
            # (ADVICE round-5 finding).
            sole = ~((same & ~jnp.eye(s, dtype=bool)[None]) & exists[:, None, :]
                     ).any(axis=2)
            self_room = jnp.concatenate(
                [room, jnp.array([False])])[own_broker]
            others_room = n_by_rack[safe_racks] - self_room.astype(jnp.int32)
            own_ok = (others_room > 0) & sole & exists
            return (unused_rack & exists) | own_ok

        return (feasible(at, n_at_by_rack),
                feasible(under, n_under_by_rack))

    def replica_weight(self, state, derived, constraint, aux):
        # Unlike the pure rack goal (which only moves duplicated replicas),
        # the count ceiling needs ordinary replicas movable too. Priority
        # is FEASIBILITY-AWARE (property-sweep finding: on heavily skewed
        # layouts the deterministic top-k filled with currently-unmovable
        # duplicates while the over-cap sheds that would free the needed
        # headroom never surfaced — a stall the reference's swap inner
        # loop sidesteps by exchanging in place):
        #   1. duplicates with a feasible rack-compatible destination,
        #   2. replicas on over-ceiling brokers with a feasible
        #      strictly-under-cap destination (the headroom openers),
        #   3. everything else (retried as feasibility shifts).
        from ...model.tensors import replica_exists, replica_load_total
        dup = _duplicate_mask(state)
        load = replica_load_total(state)
        peak = load.max() + 1.0
        dup_ok, shed_ok = self._rack_dest_feasibility(state, derived)
        over = derived.broker_replicas > self._ceiling(derived)
        b = state.num_brokers
        on_over = jnp.concatenate([over, jnp.array([False])])[
            jnp.where(state.assignment >= 0, state.assignment, b)]
        w = jnp.where(replica_exists(state), peak - load, -jnp.inf)
        # Shed-feasible replicas on NON-over brokers rank LIGHTEST: they
        # are the replicas the swap grid's light-side selection must
        # offer as the exchange's reverse leg (at-ceiling counterparties,
        # swap_dest_score). Move-grid sources are unaffected — their
        # brokers have zero violations, so on_source excludes them.
        w = jnp.where(shed_ok & ~on_over & ~dup, 0.5 * peak - load, w)
        w = jnp.where(on_over & shed_ok & ~dup, 3 * peak + load, w)
        w = jnp.where(dup & dup_ok, 5 * peak + load, w)
        return jnp.where(dup & ~dup_ok, peak + load, w)

    def target_dests(self, state, derived, constraint, aux,
                     cand_p, cand_s, src_valid, rank_stride=1,
                     rank_offset=0):
        from ..fill import class_enabled
        if not class_enabled(self):
            return None
        # Per-card RACK-COMPATIBLE destination: the shared top-num_dests
        # list ranks by count headroom alone, and on skewed layouts every
        # listed destination can be rack-conflicted for the specific
        # partitions that must shed (property-sweep stall: count
        # violations at a fixed point). Choose, per card, the
        # most-headroom broker whose rack hosts no OTHER replica of the
        # card's partition; duplicate-fixing cards may also target at-cap
        # brokers (the ceiling+1 overshoot path in ``acceptance``).
        # O(k·B) mask — kafka-assigner chains run at tool scale.
        b = state.num_brokers
        s = state.max_replication_factor
        assign_p = state.assignment[cand_p]                        # [k, S]
        slot_racks = jnp.where(assign_p >= 0,
                               state.rack[jnp.clip(assign_p, 0, b - 1)], -1)
        not_moving = jnp.arange(s, dtype=jnp.int32)[None, :] \
            != cand_s[:, None]
        used = jnp.where(not_moving & (assign_p >= 0), slot_racks, -1)
        conflict = (state.rack[None, None, :] == used[:, :, None]) \
            .any(axis=1)                                           # [k, B]
        room = (self._ceiling(derived) - derived.broker_replicas) \
            .astype(jnp.float32)                                   # [B]
        fixes_dup = _duplicate_mask(state)[cand_p, cand_s]
        min_room = jnp.where(fixes_dup, 0.0, 1.0)
        score = jnp.where(
            derived.allowed_replica_move[None, :] & derived.alive[None, :]
            & ~conflict & (room[None, :] >= min_room[:, None]),
            room[None, :], -jnp.inf)
        dst = jnp.argmax(score, axis=1).astype(jnp.int32)
        ok = jnp.isfinite(jnp.max(score, axis=1)) & src_valid
        return dst, ok


@dataclasses.dataclass(frozen=True)
class KafkaAssignerDiskUsageDistributionGoal(Goal):
    """Disk usage of every alive broker within
    avg·(1 ± (threshold-1)·margin) (KafkaAssignerDiskUsageDistributionGoal's
    balance band; the reference fixed margin is also 0.9 via
    BALANCE_MARGIN)."""

    name: str = "KafkaAssignerDiskUsageDistributionGoal"
    is_hard: bool = False

    def _band(self, derived, constraint):
        avg = derived.avg_util[Resource.DISK]
        lo_mult, hi_mult = constraint.balance_band(Resource.DISK)
        return avg * lo_mult, avg * hi_mult

    def _util(self, state, derived):
        cap = jnp.maximum(state.capacity[:, Resource.DISK], 1e-9)
        return derived.broker_load[:, Resource.DISK] / cap

    def broker_violations(self, state, derived, constraint, aux):
        lower, upper = self._band(derived, constraint)
        util = self._util(state, derived)
        over = jnp.maximum(util - upper, 0.0) + jnp.maximum(lower - util, 0.0)
        return jnp.where(derived.alive, over, 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        # Destination must stay inside the upper band after the move.
        _lower, upper = self._band(derived, constraint)
        dst_cap = jnp.maximum(state.capacity[deltas.dst_broker, Resource.DISK],
                              1e-9)
        dst_util_after = (derived.broker_load[deltas.dst_broker, Resource.DISK]
                          + deltas.pre_load("pre_dst_load", int(Resource.DISK))
                          + deltas.load_delta[:, Resource.DISK]) / dst_cap
        is_move = deltas.replica_delta > 0
        return jnp.where(is_move, dst_util_after <= upper, True)

    def improvement(self, state, derived, constraint, aux, deltas):
        lower, upper = self._band(derived, constraint)
        load = derived.broker_load[:, Resource.DISK]
        cap = jnp.maximum(state.capacity[:, Resource.DISK], 1e-9)

        def viol(value, broker):
            util = value / cap[broker]
            return jnp.maximum(util - upper, 0.0) + jnp.maximum(lower - util, 0.0)

        return pair_improvement(load, deltas,
                                deltas.load_delta[:, Resource.DISK], viol)

    def source_score(self, state, derived, constraint, aux):
        from .base import donor_widened_shed
        lower, upper = self._band(derived, constraint)
        return donor_widened_shed(self._util(state, derived), lower, upper,
                                  derived)

    def dest_score(self, state, derived, constraint, aux):
        _lower, upper = self._band(derived, constraint)
        util = self._util(state, derived)
        room = upper - util
        return jnp.where(derived.allowed_replica_move & (room > 0), room,
                         -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        from ...model.tensors import replica_load_column
        return replica_load_column(state, int(Resource.DISK))
