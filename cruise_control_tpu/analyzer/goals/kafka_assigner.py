"""Kafka-assigner emulation mode.

Reference parity: analyzer/kafkaassigner/ —
KafkaAssignerEvenRackAwareGoal.java:523 (strict rack-awareness PLUS an even
per-broker replica ceiling, the kafka-assigner tool's placement contract)
and KafkaAssignerDiskUsageDistributionGoal.java:722 (disk balance within a
threshold band). The reference's swap-based inner loop is re-expressed as
the batched move search: the conflict-free accept step reaches the same
balance band invariant that the pairwise swaps do, one fused round at a
time (the two halves of a swap land in consecutive rounds).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ...common.resources import Resource
from ..candidates import CandidateDeltas
from .base import Goal, pair_improvement
from .rack import RackAwareGoal, _duplicate_mask


@dataclasses.dataclass(frozen=True)
class KafkaAssignerEvenRackAwareGoal(RackAwareGoal):
    """Rack-aware + ceil(total/alive) replica-count ceiling per broker."""

    name: str = "KafkaAssignerEvenRackAwareGoal"
    is_hard: bool = True

    def _ceiling(self, derived) -> jnp.ndarray:
        total = (derived.broker_replicas * derived.alive).sum()
        n = jnp.maximum(derived.alive.sum(), 1)
        return jnp.ceil(total / n).astype(jnp.int32)

    def broker_violations(self, state, derived, constraint, aux):
        rack_v = super().broker_violations(state, derived, constraint, aux)
        over = jnp.maximum(
            derived.broker_replicas - self._ceiling(derived), 0)
        return rack_v + jnp.where(derived.alive, over, 0).astype(jnp.float32)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        rack_ok = super().acceptance(state, derived, constraint, aux, deltas)
        cap = self._ceiling(derived)
        dst_after = derived.broker_replicas[deltas.dst_broker] \
            + deltas.pre0("pre_dst_count") + 1
        under_cap = dst_after <= cap
        # Deadlock breaker: a RACK-duplicate-fixing move may overshoot the
        # even ceiling by ONE. On skewed clusters every under-ceiling
        # broker in a partition's free rack can sit exactly at the
        # ceiling, and a pure greedy stalls where the reference's
        # swap-based inner loop (KafkaAssignerEvenRackAwareGoal.java's
        # per-position swaps) proceeds; the overshoot converts the rack
        # violation into a count violation that later rounds shed
        # (improvement weights rack 2x count, so both steps score > 0).
        fixes_dup = _duplicate_mask(state)[deltas.partition, deltas.src_slot]
        tolerant = fixes_dup & (dst_after <= cap + 1)
        is_move = deltas.replica_delta > 0
        return rack_ok & jnp.where(is_move, under_cap | tolerant, True)

    def improvement(self, state, derived, constraint, aux, deltas):
        rack_imp = super().improvement(state, derived, constraint, aux, deltas)
        cap = self._ceiling(derived).astype(jnp.float32)
        counts = derived.broker_replicas.astype(jnp.float32)
        count_imp = pair_improvement(
            counts, deltas, deltas.replica_delta.astype(jnp.float32),
            lambda v, _b: jnp.maximum(v - cap, 0.0))
        # Rack fixes outweigh the count violation they may create (the
        # two-step deadlock-breaking path above must score positive at
        # both steps; terminates because 2*rack + count strictly falls).
        return jnp.where(deltas.valid, 2.0 * rack_imp + count_imp, -jnp.inf)

    def source_score(self, state, derived, constraint, aux):
        return self.broker_violations(state, derived, constraint, aux)

    def dest_score(self, state, derived, constraint, aux):
        cap = self._ceiling(derived)
        room = (cap - derived.broker_replicas).astype(jnp.float32)
        # room >= 0 (not > 0): AT-CAP brokers must stay in the candidate
        # grid — the duplicate-fixing overshoot path in ``acceptance`` is
        # unreachable if dest_score filters them to -inf before scoring.
        return jnp.where(derived.allowed_replica_move & (room >= 0), room,
                         -jnp.inf)

    def swap_leg_acceptance(self, state, derived, constraint, aux, leg):
        # Swaps keep per-broker counts, so only the RACK check applies per
        # leg — the inherited move acceptance (count ceiling) would veto
        # every swap once brokers sit at the even ceiling (the steady state
        # of kafka-assigner mode).
        return RackAwareGoal.acceptance(self, state, derived, constraint,
                                        aux, leg)

    def replica_weight(self, state, derived, constraint, aux):
        # Unlike the pure rack goal (which only moves duplicated replicas),
        # the count ceiling needs ordinary replicas movable too: prioritize
        # rack-duplicates, then lighter replicas (cheaper to relocate).
        from ...model.tensors import replica_exists, replica_load_total
        dup = _duplicate_mask(state)
        load = replica_load_total(state)
        peak = load.max() + 1.0
        return jnp.where(dup, peak + load,
                         jnp.where(replica_exists(state), peak - load, -jnp.inf))


@dataclasses.dataclass(frozen=True)
class KafkaAssignerDiskUsageDistributionGoal(Goal):
    """Disk usage of every alive broker within
    avg·(1 ± (threshold-1)·margin) (KafkaAssignerDiskUsageDistributionGoal's
    balance band; the reference fixed margin is also 0.9 via
    BALANCE_MARGIN)."""

    name: str = "KafkaAssignerDiskUsageDistributionGoal"
    is_hard: bool = False

    def _band(self, derived, constraint):
        avg = derived.avg_util[Resource.DISK]
        lo_mult, hi_mult = constraint.balance_band(Resource.DISK)
        return avg * lo_mult, avg * hi_mult

    def _util(self, state, derived):
        cap = jnp.maximum(state.capacity[:, Resource.DISK], 1e-9)
        return derived.broker_load[:, Resource.DISK] / cap

    def broker_violations(self, state, derived, constraint, aux):
        lower, upper = self._band(derived, constraint)
        util = self._util(state, derived)
        over = jnp.maximum(util - upper, 0.0) + jnp.maximum(lower - util, 0.0)
        return jnp.where(derived.alive, over, 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        # Destination must stay inside the upper band after the move.
        _lower, upper = self._band(derived, constraint)
        dst_cap = jnp.maximum(state.capacity[deltas.dst_broker, Resource.DISK],
                              1e-9)
        dst_util_after = (derived.broker_load[deltas.dst_broker, Resource.DISK]
                          + deltas.pre_load("pre_dst_load", int(Resource.DISK))
                          + deltas.load_delta[:, Resource.DISK]) / dst_cap
        is_move = deltas.replica_delta > 0
        return jnp.where(is_move, dst_util_after <= upper, True)

    def improvement(self, state, derived, constraint, aux, deltas):
        lower, upper = self._band(derived, constraint)
        load = derived.broker_load[:, Resource.DISK]
        cap = jnp.maximum(state.capacity[:, Resource.DISK], 1e-9)

        def viol(value, broker):
            util = value / cap[broker]
            return jnp.maximum(util - upper, 0.0) + jnp.maximum(lower - util, 0.0)

        return pair_improvement(load, deltas,
                                deltas.load_delta[:, Resource.DISK], viol)

    def source_score(self, state, derived, constraint, aux):
        from .base import donor_widened_shed
        lower, upper = self._band(derived, constraint)
        return donor_widened_shed(self._util(state, derived), lower, upper,
                                  derived)

    def dest_score(self, state, derived, constraint, aux):
        _lower, upper = self._band(derived, constraint)
        util = self._util(state, derived)
        room = upper - util
        return jnp.where(derived.allowed_replica_move & (room > 0), room,
                         -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        from ...model.tensors import replica_load_column
        return replica_load_column(state, int(Resource.DISK))
