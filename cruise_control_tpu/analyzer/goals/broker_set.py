"""Broker-set awareness.

Reference parity: analyzer/goals/BrokerSetAwareGoal.java:80 (hard goal:
every topic's replicas confined to ONE broker set, where broker sets come
from brokerSets.json via a pluggable resolver) — the reference resolves a
topic's target set from its current placement and rejects any action that
crosses set boundaries.

The goal instance carries the broker→set mapping as a hashable tuple
(indexed by broker INDEX; the optimizer/facade translates broker ids via
ClusterMeta) so it remains a static jit argument like every other goal.
A topic's home set = the set hosting the majority of its replicas (ties →
lowest set id), computed as a partition-additive [T, num_sets] count so the
sharded search can psum it.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ...model.tensors import replica_exists, replica_load_total
from ..candidates import CandidateDeltas
from .base import Goal


def broker_sets_from_file(path: str, broker_ids: list[int]) -> tuple[int, ...]:
    """Parse brokerSets.json ({"brokerSets": [{"brokerSetId", "brokerIds"}]})
    into the per-broker-index set-id tuple this goal consumes. Brokers not
    named by any set share one implicit trailing set (the reference treats
    unmapped brokers as an error; the implicit set keeps dev clusters
    usable while still confining mapped topics)."""
    import json
    with open(path) as f:
        doc = json.load(f)
    set_of: dict[int, int] = {}
    for k, entry in enumerate(doc.get("brokerSets", [])):
        for bid in entry.get("brokerIds", []):
            set_of[int(bid)] = k
    implicit = len(doc.get("brokerSets", []))
    return tuple(set_of.get(bid, implicit) for bid in broker_ids)


@dataclasses.dataclass(frozen=True)
class BrokerSetAwareGoal(Goal):
    name: str = "BrokerSetAwareGoal"
    is_hard: bool = True
    partition_additive_scores: bool = True
    broker_sets: tuple[int, ...] = ()    # set id per broker index

    def _set_array(self, state) -> jax.Array:
        if self.broker_sets:
            sets = jnp.asarray(self.broker_sets, dtype=jnp.int32)
        else:
            sets = jnp.zeros(state.num_brokers, dtype=jnp.int32)
        return sets

    @property
    def _num_sets(self) -> int:
        return (max(self.broker_sets) + 1) if self.broker_sets else 1

    def _slot_sets(self, state) -> jax.Array:
        """[P, S] set id per replica slot (num_sets for empty)."""
        sets = self._set_array(state)
        pad = jnp.concatenate([sets, jnp.array([self._num_sets], jnp.int32)])
        return pad[jnp.where(state.assignment >= 0, state.assignment,
                             state.num_brokers)]

    def prepare_partial(self, state, num_topics: int):
        """[T, num_sets] replica counts (additive over partitions)."""
        k = self._num_sets
        slot_sets = self._slot_sets(state)
        exists = replica_exists(state)
        seg = jnp.where(exists, state.topic[:, None] * (k + 1)
                        + jnp.minimum(slot_sets, k), num_topics * (k + 1))
        out = jax.ops.segment_sum(exists.astype(jnp.int32).reshape(-1),
                                  seg.reshape(-1),
                                  num_segments=num_topics * (k + 1) + 1)
        return out[:num_topics * (k + 1)].reshape(num_topics, k + 1)[:, :k]

    def finalize_aux(self, partial, state, derived, constraint):
        """aux = (home_set[T], counts[T, K])."""
        return (jnp.argmax(partial, axis=1).astype(jnp.int32), partial)

    def _misplaced(self, state, aux) -> jax.Array:
        """[P, S] bool — replica outside its topic's home set."""
        home, _counts = aux
        slot_sets = self._slot_sets(state)
        topic_home = home[state.topic]          # [P]
        return replica_exists(state) & (slot_sets != topic_home[:, None])

    def broker_violations(self, state, derived, constraint, aux):
        # Excluded-topic replicas are unmovable: not counted as violations
        # (GoalUtils excluded-topic filtering semantics).
        mis = self._misplaced(state, aux) & derived.movable_partition[:, None]
        b = state.num_brokers
        seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
        out = jax.ops.segment_sum(mis.astype(jnp.float32).reshape(-1), seg,
                                  num_segments=b + 1)
        return out[:b]

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        home, _ = aux
        sets = self._set_array(state)
        dst_ok = sets[deltas.dst_broker] == home[deltas.topic]
        is_move = deltas.replica_delta > 0
        return jnp.where(is_move, dst_ok, True)

    def improvement(self, state, derived, constraint, aux, deltas):
        home, _ = aux
        sets = self._set_array(state)
        src_bad = (sets[deltas.src_broker] != home[deltas.topic]).astype(jnp.float32)
        dst_bad = (sets[deltas.dst_broker] != home[deltas.topic]).astype(jnp.float32)
        is_move = deltas.replica_delta > 0
        imp = jnp.where(is_move, src_bad - dst_bad, 0.0)
        return jnp.where(deltas.valid, imp, -jnp.inf)

    def dest_score(self, state, derived, constraint, aux):
        return jnp.where(derived.allowed_replica_move,
                         -derived.broker_replicas.astype(jnp.float32), -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        mis = self._misplaced(state, aux)
        return jnp.where(mis, 1.0 + replica_load_total(state), -jnp.inf)
