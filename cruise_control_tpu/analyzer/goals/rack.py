"""Rack-awareness goals.

Reference parity: analyzer/goals/RackAwareGoal.java (strict: no two replicas
of a partition in one rack) and RackAwareDistributionGoal.java (relaxed:
replicas spread over racks as evenly as possible, allowing more replicas
than racks).

Kernel design: with S = max RF small (≤ 8), per-partition rack duplication
is computed from the [P, S, S] pairwise same-rack comparison instead of a
[P, num_racks] one-hot — O(P·S²) with tiny constants, no T×B style blowup.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ...model.tensors import replica_exists, replica_load_total
from ..candidates import CandidateDeltas
from .base import Goal


def _slot_racks(state):
    """[P, S] rack index per replica slot (num_racks for empty slots)."""
    b = state.num_brokers
    pad_rack = state.rack.max() + 1
    rack_pad = jnp.concatenate([state.rack, jnp.array([pad_rack], dtype=state.rack.dtype)])
    return jnp.where(state.assignment >= 0,
                     rack_pad[jnp.clip(state.assignment, 0, b)], -1 - jnp.arange(
                         state.max_replication_factor, dtype=state.rack.dtype)[None, :])


def _duplicate_mask(state):
    """[P, S] — replica shares its rack with an earlier existing slot of the
    same partition (the 'extra' replicas that violate rack-awareness)."""
    racks = _slot_racks(state)  # [P, S]; empty slots get unique negatives
    same = racks[:, :, None] == racks[:, None, :]  # [P, S, S]
    s = state.max_replication_factor
    earlier = jnp.tril(jnp.ones((s, s), dtype=bool), k=-1)[None]
    exists = replica_exists(state)
    return (same & earlier).any(axis=2) & exists


@dataclasses.dataclass(frozen=True)
class RackAwareGoal(Goal):
    """Strict rack-awareness (RackAwareGoal.java): every replica of a
    partition lives in a distinct rack. Leadership moves always accepted;
    replica moves accepted iff the destination rack hosts no other replica
    of the partition (AbstractRackAwareGoal.java:96-130)."""

    def broker_violations(self, state, derived, constraint, aux):
        # Replicas of EXCLUDED topics cannot be moved, so their rack
        # duplicates are not counted as violations (the reference's rack
        # goal skips excluded topics rather than failing on them —
        # GoalUtils excluded-topic filtering).
        dup = _duplicate_mask(state) & derived.movable_partition[:, None]
        b = state.num_brokers
        seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
        out = jax.ops.segment_sum(dup.astype(jnp.float32).reshape(-1), seg,
                                  num_segments=b + 1)
        return out[:b]

    def _dst_rack_conflict(self, state, deltas: CandidateDeltas):
        """[N] — destination rack already hosts another replica of the
        partition (excluding the moving slot itself)."""
        b = state.num_brokers
        p = deltas.partition
        assign_p = state.assignment[p]  # [N, S]
        rack_pad = jnp.concatenate([state.rack, state.rack[:1]])
        slot_racks = jnp.where(assign_p >= 0, rack_pad[jnp.clip(assign_p, 0, b - 1)], -1)
        dst_rack = state.rack[deltas.dst_broker]
        s = state.max_replication_factor
        not_moving = jnp.arange(s, dtype=jnp.int32)[None, :] != deltas.src_slot[:, None]
        return ((slot_racks == dst_rack[:, None]) & not_moving & (assign_p >= 0)).any(axis=1)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        is_move = deltas.replica_delta > 0
        return jnp.where(is_move, ~self._dst_rack_conflict(state, deltas), True)

    def improvement(self, state, derived, constraint, aux, deltas):
        dup = _duplicate_mask(state)
        # A move improves iff the moving replica currently duplicates a rack
        # and the destination rack is conflict-free; it regresses iff it
        # creates a new conflict.
        cur_dup = dup[deltas.partition, deltas.src_slot].astype(jnp.float32)
        new_conflict = self._dst_rack_conflict(state, deltas).astype(jnp.float32)
        is_move = deltas.replica_delta > 0
        imp = jnp.where(is_move, cur_dup - new_conflict, 0.0)
        return jnp.where(deltas.valid, imp, -jnp.inf)

    def dest_score(self, state, derived, constraint, aux):
        # Prefer emptier allowed brokers; per-partition feasibility is left
        # to acceptance/improvement.
        return jnp.where(derived.allowed_replica_move,
                         -derived.broker_replicas.astype(jnp.float32), -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        dup = _duplicate_mask(state)
        return jnp.where(dup, 1.0 + replica_load_total(state), -jnp.inf)

    def source_score(self, state, derived, constraint, aux):
        # Sources = brokers hosting duplicated replicas.
        return self.broker_violations(state, derived, constraint, aux)


@dataclasses.dataclass(frozen=True)
class RackAwareDistributionGoal(RackAwareGoal):
    """Relaxed rack-awareness (RackAwareDistributionGoal.java:449LoC):
    replicas balanced across racks — a rack may hold at most
    ceil(RF / num_racks) replicas of a partition."""

    def _limits(self, state):
        num_racks = state.rack.max() + 1
        rf = replica_exists(state).sum(axis=1)  # [P]
        return jnp.ceil(rf / jnp.maximum(num_racks, 1)).astype(jnp.int32)

    def _rack_counts_at(self, state, deltas, rack_of_broker):
        b = state.num_brokers
        p = deltas.partition
        assign_p = state.assignment[p]
        slot_racks = jnp.where(assign_p >= 0,
                               jnp.concatenate([state.rack, state.rack[:1]])[
                                   jnp.clip(assign_p, 0, b - 1)], -1)
        not_moving = (jnp.arange(state.max_replication_factor, dtype=jnp.int32)[None, :]
                      != deltas.src_slot[:, None])
        counts = ((slot_racks == rack_of_broker[:, None]) & not_moving
                  & (assign_p >= 0)).sum(axis=1)
        return counts

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        limit = self._limits(state)[deltas.partition]
        dst_rack = state.rack[deltas.dst_broker]
        dst_count = self._rack_counts_at(state, deltas, dst_rack)
        is_move = deltas.replica_delta > 0
        return jnp.where(is_move, dst_count + 1 <= limit, True)

    def improvement(self, state, derived, constraint, aux, deltas):
        limit = self._limits(state)[deltas.partition]
        src_rack = state.rack[deltas.src_broker]
        dst_rack = state.rack[deltas.dst_broker]
        src_count = self._rack_counts_at(state, deltas, src_rack)  # excludes mover
        dst_count = self._rack_counts_at(state, deltas, dst_rack)
        over_before = jnp.maximum(src_count + 1 - limit, 0) + jnp.maximum(dst_count - limit, 0)
        over_after = jnp.maximum(src_count - limit, 0) + jnp.maximum(dst_count + 1 - limit, 0)
        is_move = deltas.replica_delta > 0
        imp = jnp.where(is_move, (over_before - over_after).astype(jnp.float32), 0.0)
        return jnp.where(deltas.valid, imp, -jnp.inf)

    def broker_violations(self, state, derived, constraint, aux):
        # Violation: replicas beyond the per-rack ceiling, attributed to the
        # brokers hosting them (approximated by the strict duplicate count
        # beyond the ceiling).
        limit = self._limits(state)
        racks = _slot_racks(state)
        same = racks[:, :, None] == racks[:, None, :]
        s = state.max_replication_factor
        earlier = jnp.tril(jnp.ones((s, s), dtype=bool), k=0)[None]
        rank_in_rack = (same & earlier).sum(axis=2)  # 1-based occurrence rank
        over = (rank_in_rack > limit[:, None]) & replica_exists(state) \
            & derived.movable_partition[:, None]
        b = state.num_brokers
        seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
        out = jax.ops.segment_sum(over.astype(jnp.float32).reshape(-1), seg,
                                  num_segments=b + 1)
        return out[:b]
