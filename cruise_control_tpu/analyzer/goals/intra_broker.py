"""Intra-broker disk goals.

Reference parity: analyzer/goals/IntraBrokerDiskCapacityGoal.java:316 and
IntraBrokerDiskUsageDistributionGoal.java:509. Unlike the inter-broker
goals these act on the (broker, disk) axis with INTRA_BROKER_REPLICA
actions; brokers are independent, so the whole pass is the [B]-parallel
``balance_intra_broker`` kernel in model/disks.py — each goal object here
binds that kernel to its objective (capacity vs balance band) and reports
violations in the standard goal shape.
"""

from __future__ import annotations

import dataclasses

import jax

from ...model.disks import (
    DiskTensors, balance_intra_broker, intra_broker_violations,
)
from ...model.tensors import ClusterTensors
from ..constraint import BALANCE_MARGIN


@dataclasses.dataclass(frozen=True)
class IntraBrokerDiskCapacityGoal:
    """Hard: no disk above capacity·threshold, nothing on dead disks."""

    name: str = "IntraBrokerDiskCapacityGoal"
    is_hard: bool = True
    capacity_threshold: float = 0.8

    def violations(self, state: ClusterTensors, disks: DiskTensors) -> jax.Array:
        return intra_broker_violations(state, disks, self.capacity_threshold,
                                       balance_band=None)

    def optimize(self, state: ClusterTensors, disks: DiskTensors,
                 max_rounds: int = 64, movable=None) -> DiskTensors:
        return balance_intra_broker(state, disks, self.capacity_threshold,
                                    balance_band=None, max_rounds=max_rounds,
                                    movable=movable)


@dataclasses.dataclass(frozen=True)
class IntraBrokerDiskUsageDistributionGoal:
    """Soft: every disk of a broker within avg·(1 ± (threshold-1)·margin)
    of that broker's mean disk utilization."""

    name: str = "IntraBrokerDiskUsageDistributionGoal"
    is_hard: bool = False
    capacity_threshold: float = 0.8
    balance_threshold: float = 1.1

    def _band(self) -> tuple[float, float]:
        spread = (self.balance_threshold - 1.0) * BALANCE_MARGIN
        return 1.0 - spread, 1.0 + spread

    def violations(self, state: ClusterTensors, disks: DiskTensors) -> jax.Array:
        return intra_broker_violations(state, disks, self.capacity_threshold,
                                       balance_band=self._band())

    def optimize(self, state: ClusterTensors, disks: DiskTensors,
                 max_rounds: int = 64, movable=None) -> DiskTensors:
        return balance_intra_broker(state, disks, self.capacity_threshold,
                                    balance_band=self._band(),
                                    max_rounds=max_rounds, movable=movable)
