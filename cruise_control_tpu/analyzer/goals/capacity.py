"""Hard capacity goals.

Reference parity: analyzer/goals/CapacityGoal.java (+ the four 45-line
specializations DiskCapacityGoal / NetworkInboundCapacityGoal /
NetworkOutboundCapacityGoal / CpuCapacityGoal) and ReplicaCapacityGoal.java.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ...common.resources import Resource
from ...model.tensors import replica_load_column, replica_load_total
from ..candidates import CandidateDeltas
from .base import Goal, pair_improvement


@dataclasses.dataclass(frozen=True)
class ResourceCapacityGoal(Goal):
    """Keep every alive broker's load for one resource under
    capacity × capacity_threshold (CapacityGoal.java)."""

    resource: Resource = Resource.DISK

    def _limit(self, state, constraint):
        r = int(self.resource)
        return constraint.capacity_threshold[r] * state.capacity[:, r]

    def broker_violations(self, state, derived, constraint, aux):
        limit = self._limit(state, constraint)
        load = derived.broker_load[:, int(self.resource)]
        return jnp.where(derived.alive, jnp.maximum(load - limit, 0.0), 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        # isMovementAcceptableForCapacity: destination stays within its
        # capacity limit after receiving the load (including inflow from
        # higher-ranked candidates accepted this round).
        r = int(self.resource)
        limit = self._limit(state, constraint)
        dst_after = derived.broker_load[deltas.dst_broker, r] \
            + deltas.pre_load("pre_dst_load", r) + deltas.load_delta[:, r]
        return dst_after <= limit[deltas.dst_broker] + 1e-6

    def improvement(self, state, derived, constraint, aux, deltas):
        r = int(self.resource)
        limit = self._limit(state, constraint)

        def viol(value, idx):
            return jnp.maximum(value - limit[idx], 0.0)

        return pair_improvement(derived.broker_load[:, r], deltas,
                                deltas.load_delta[:, r], viol)

    def dest_score(self, state, derived, constraint, aux):
        limit = self._limit(state, constraint)
        headroom = limit - derived.broker_load[:, int(self.resource)]
        return jnp.where(derived.allowed_replica_move, headroom, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        return replica_load_column(state, int(self.resource))

    def swap_leg_acceptance(self, state, derived, constraint, aux, leg):
        # Judged on the net transfer only — a leg-wise capacity check would
        # spuriously veto swaps whose net effect is within limits.
        return jnp.ones(leg.valid.shape[0], dtype=bool)

    def swap_net_acceptance(self, state, derived, constraint, aux, net):
        # Net transfer is SIGNED (a swap ranked on another resource can pull
        # load toward the source on this one): bound BOTH endpoints.
        r = int(self.resource)
        limit = self._limit(state, constraint)
        d = net.load_delta[:, r]
        load = derived.broker_load[:, r]
        dst_ok = load[net.dst_broker] + d <= limit[net.dst_broker] + 1e-6
        src_ok = load[net.src_broker] - d <= limit[net.src_broker] + 1e-6
        return dst_ok & src_ok


@dataclasses.dataclass(frozen=True)
class ReplicaCapacityGoal(Goal):
    """Max replicas per alive broker (ReplicaCapacityGoal.java:340LoC)."""

    def broker_violations(self, state, derived, constraint, aux):
        over = derived.broker_replicas - constraint.max_replicas_per_broker
        return jnp.where(derived.alive, jnp.maximum(over, 0).astype(jnp.float32), 0.0)

    def acceptance(self, state, derived, constraint, aux, deltas: CandidateDeltas):
        dst_after = derived.broker_replicas[deltas.dst_broker] \
            + deltas.pre0("pre_dst_count") + deltas.replica_delta
        return dst_after <= constraint.max_replicas_per_broker

    def improvement(self, state, derived, constraint, aux, deltas):
        cap = float(constraint.max_replicas_per_broker)

        def viol(value, idx):
            return jnp.maximum(value - cap, 0.0)

        return pair_improvement(derived.broker_replicas.astype(jnp.float32), deltas,
                                deltas.replica_delta.astype(jnp.float32), viol)

    def dest_score(self, state, derived, constraint, aux):
        headroom = (constraint.max_replicas_per_broker
                    - derived.broker_replicas).astype(jnp.float32)
        return jnp.where(derived.allowed_replica_move & (headroom > 0),
                         headroom, -jnp.inf)

    def replica_weight(self, state, derived, constraint, aux):
        # Any replica works; prefer light ones to minimize load disturbance.
        return -replica_load_total(state)

    def swap_leg_acceptance(self, state, derived, constraint, aux, leg):
        # Swaps never change per-broker replica counts: always acceptable.
        return jnp.ones(leg.valid.shape[0], dtype=bool)


def make_capacity_goals() -> list[Goal]:
    return [
        ResourceCapacityGoal(name="DiskCapacityGoal", is_hard=True,
                             resource=Resource.DISK),
        ResourceCapacityGoal(name="NetworkInboundCapacityGoal", is_hard=True,
                             resource=Resource.NW_IN),
        ResourceCapacityGoal(name="NetworkOutboundCapacityGoal", is_hard=True,
                             include_leadership=True, resource=Resource.NW_OUT),
        ResourceCapacityGoal(name="CpuCapacityGoal", is_hard=True,
                             include_leadership=True, resource=Resource.CPU),
    ]
