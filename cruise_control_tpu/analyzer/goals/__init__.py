"""Goal registry: zero-arg-constructible classes matching the dotted paths
in CruiseControlConfig's default goal chain (AnalyzerConfig goals=...)."""

from __future__ import annotations

from ...common.resources import Resource
from .base import Goal
from .capacity import ReplicaCapacityGoal as _ReplicaCapacityBase, ResourceCapacityGoal
from .distribution import (
    CountDistributionGoal, LeaderBytesInDistributionGoal as _LeaderBytesInBase,
    MinTopicLeadersPerBrokerGoal as _MinTopicLeadersBase,
    PotentialNwOutGoal as _PotentialNwOutBase,
    PreferredLeaderElectionGoal as _PreferredLeaderBase,
    ResourceDistributionGoal, TopicReplicaDistributionGoal as _TopicReplicaBase,
)
from .broker_set import BrokerSetAwareGoal as _BrokerSetAwareBase
from .intra_broker import (
    IntraBrokerDiskCapacityGoal, IntraBrokerDiskUsageDistributionGoal,
)
from .kafka_assigner import (
    KafkaAssignerDiskUsageDistributionGoal as _KafkaAssignerDiskBase,
    KafkaAssignerEvenRackAwareGoal as _KafkaAssignerRackBase,
)
from .rack import RackAwareDistributionGoal as _RackAwareDistBase, RackAwareGoal as _RackAwareBase


def _preset(base, **kwargs):
    """Subclass with baked constructor arguments so config reflection can
    instantiate with no args (getConfiguredInstance contract)."""

    class _Preset(base):
        def __init__(self, **overrides):
            super().__init__(**{**kwargs, **overrides})

    _Preset.__name__ = kwargs.get("name", base.__name__)
    _Preset.__qualname__ = _Preset.__name__
    return _Preset


RackAwareGoal = _preset(_RackAwareBase, name="RackAwareGoal", is_hard=True,
                        partition_additive_scores=True,
                        independent_per_broker=True,
                        prefers_wide_batches=True)
RackAwareDistributionGoal = _preset(_RackAwareDistBase,
                                    name="RackAwareDistributionGoal", is_hard=True,
                                    partition_additive_scores=True,
                                    independent_per_broker=True)
ReplicaCapacityGoal = _preset(_ReplicaCapacityBase, name="ReplicaCapacityGoal",
                              is_hard=True)
DiskCapacityGoal = _preset(ResourceCapacityGoal, name="DiskCapacityGoal",
                           is_hard=True, uses_resource_metrics=True,
                           resource=Resource.DISK)
NetworkInboundCapacityGoal = _preset(ResourceCapacityGoal,
                                     name="NetworkInboundCapacityGoal",
                                     is_hard=True, uses_resource_metrics=True,
                                     resource=Resource.NW_IN)
NetworkOutboundCapacityGoal = _preset(ResourceCapacityGoal,
                                      name="NetworkOutboundCapacityGoal",
                                      is_hard=True, include_leadership=True,
                                      uses_resource_metrics=True,
                                      resource=Resource.NW_OUT)
CpuCapacityGoal = _preset(ResourceCapacityGoal, name="CpuCapacityGoal",
                          is_hard=True, include_leadership=True,
                          uses_resource_metrics=True,
                          resource=Resource.CPU)
DiskUsageDistributionGoal = _preset(ResourceDistributionGoal,
                                    name="DiskUsageDistributionGoal",
                                    supports_swap=True,
                                    uses_resource_metrics=True,
                                    resource=Resource.DISK)
NetworkInboundUsageDistributionGoal = _preset(ResourceDistributionGoal,
                                              name="NetworkInboundUsageDistributionGoal",
                                              supports_swap=True,
                                              uses_resource_metrics=True,
                                              resource=Resource.NW_IN)
NetworkOutboundUsageDistributionGoal = _preset(ResourceDistributionGoal,
                                               name="NetworkOutboundUsageDistributionGoal",
                                               include_leadership=True,
                                               supports_swap=True,
                                               uses_resource_metrics=True,
                                               resource=Resource.NW_OUT)
CpuUsageDistributionGoal = _preset(ResourceDistributionGoal,
                                   name="CpuUsageDistributionGoal",
                                   include_leadership=True,
                                   supports_swap=True,
                                   uses_resource_metrics=True,
                                   resource=Resource.CPU)
ReplicaDistributionGoal = _preset(CountDistributionGoal,
                                  name="ReplicaDistributionGoal", leaders=False,
                                  prefers_wide_batches=True)
LeaderReplicaDistributionGoal = _preset(CountDistributionGoal,
                                        name="LeaderReplicaDistributionGoal",
                                        include_leadership=True, leaders=True,
                                        prefers_wide_batches=True)
TopicReplicaDistributionGoal = _preset(_TopicReplicaBase,
                                       name="TopicReplicaDistributionGoal")
PotentialNwOutGoal = _preset(_PotentialNwOutBase, name="PotentialNwOutGoal",
                             uses_resource_metrics=True)
LeaderBytesInDistributionGoal = _preset(_LeaderBytesInBase,
                                        name="LeaderBytesInDistributionGoal",
                                        include_leadership=True,
                                        uses_resource_metrics=True,
                                        leadership_only=True)
PreferredLeaderElectionGoal = _preset(_PreferredLeaderBase,
                                      name="PreferredLeaderElectionGoal",
                                      include_leadership=True,
                                      leadership_only=True,
                                      partition_additive_scores=True,
                                      independent_per_broker=True)
MinTopicLeadersPerBrokerGoal = _preset(_MinTopicLeadersBase,
                                       name="MinTopicLeadersPerBrokerGoal",
                                       is_hard=True)
BrokerSetAwareGoal = _preset(_BrokerSetAwareBase, name="BrokerSetAwareGoal",
                             is_hard=True, partition_additive_scores=True,
                             independent_per_broker=True)
KafkaAssignerEvenRackAwareGoal = _preset(_KafkaAssignerRackBase,
                                         name="KafkaAssignerEvenRackAwareGoal",
                                         is_hard=True,
                                         partition_additive_scores=True)
KafkaAssignerDiskUsageDistributionGoal = _preset(
    _KafkaAssignerDiskBase, name="KafkaAssignerDiskUsageDistributionGoal",
    supports_swap=True, uses_resource_metrics=True)

ALL_GOALS = {cls.__name__: cls for cls in [
    RackAwareGoal, RackAwareDistributionGoal, ReplicaCapacityGoal,
    DiskCapacityGoal, NetworkInboundCapacityGoal, NetworkOutboundCapacityGoal,
    CpuCapacityGoal, DiskUsageDistributionGoal,
    NetworkInboundUsageDistributionGoal, NetworkOutboundUsageDistributionGoal,
    CpuUsageDistributionGoal, ReplicaDistributionGoal,
    LeaderReplicaDistributionGoal, TopicReplicaDistributionGoal,
    PotentialNwOutGoal, LeaderBytesInDistributionGoal,
    PreferredLeaderElectionGoal, MinTopicLeadersPerBrokerGoal,
    BrokerSetAwareGoal, KafkaAssignerEvenRackAwareGoal,
    KafkaAssignerDiskUsageDistributionGoal,
]}
