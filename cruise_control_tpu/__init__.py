"""cruise-control-tpu: a TPU-native Kafka cluster balancer.

A brand-new framework with the capabilities of Kafka Cruise Control
(reference: cawright-rh/cruise-control), re-designed TPU-first:

- cluster state lives in dense JAX arrays (``model/``),
- goal scoring is a vmap'd kernel over thousands of candidate actions
  (``analyzer/goals/``),
- the rebalance search is a jitted fixed-point loop, shardable over a
  ``jax.sharding.Mesh`` (``analyzer/search.py``, ``parallel/``),
- monitoring, execution, anomaly detection and the REST surface are
  host-side async services around that solver core
  (``monitor/``, ``executor/``, ``detector/``, ``api/``).

Reference layer map: see SURVEY.md §1 (cruise-control/src/main/java/...).
"""

__version__ = "0.3.0"
