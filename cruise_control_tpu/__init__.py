"""cruise-control-tpu: a TPU-native Kafka cluster balancer.

A brand-new framework with the capabilities of Kafka Cruise Control
(reference: cawright-rh/cruise-control), re-designed TPU-first:

- cluster state lives in dense JAX arrays (``model/``),
- goal scoring is a vmap'd kernel over thousands of candidate actions
  (``analyzer/goals/``),
- the rebalance search is a jitted fixed-point loop, shardable over a
  ``jax.sharding.Mesh`` (``analyzer/search.py``, ``parallel/``),
- monitoring, execution, anomaly detection and the REST surface are
  host-side async services around that solver core
  (``monitor/``, ``executor/``, ``detector/``, ``api/``).

Reference layer map: see SURVEY.md §1 (cruise-control/src/main/java/...).
"""

__version__ = "0.4.0"


def enable_persistent_compile_cache(cache_dir: str | None = None,
                                    min_compile_secs: float = 1.0) -> str:
    """Point XLA's persistent compilation cache at ``cache_dir`` (default:
    $JAX_COMPILATION_CACHE_DIR or /tmp/cc_tpu_jax_cache).

    jax 0.9 does NOT honor the JAX_COMPILATION_CACHE_DIR environment
    variable (``jax.config.jax_compilation_cache_dir`` stays None unless
    set programmatically) — every entry point that relied on the env var
    was cold-compiling the full solver chain on every process start
    (~19 min at 7k brokers). Calling this before the first compilation
    makes restarts hit the on-disk cache. Idempotent; safe after jax
    import, must run before the first jit execution to help it.

    The cache is partitioned per host fingerprint (CPU feature flags +
    jaxlib version + requested platform set): XLA:CPU persists AOT
    artifacts compiled against the *builder's* CPU features, and loading
    them on a host with different features emits one ``cpu_aot_loader``
    machine-feature-mismatch error per kernel — enough stderr spam to
    displace every metric line from a log tail (this emptied the round-4
    bench artifact). Entries written on one machine are simply invisible
    to a different machine instead of being loaded and rejected loudly."""
    import os

    import jax

    cache_dir = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                            "/tmp/cc_tpu_jax_cache")
    cache_dir = os.path.join(cache_dir, _host_fingerprint())
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    return cache_dir


def _host_fingerprint() -> str:
    """Stable id for (CPU features, jaxlib, requested platforms) — the
    inputs that decide whether a persisted XLA:CPU AOT artifact is loadable
    on this host. /proc/cpuinfo flags cover the machine-feature axis the
    XLA cache key omits; JAX_PLATFORMS covers cpu-vs-tpu entry points that
    share one cache root."""
    import hashlib
    import os
    import platform as _platform

    flags = _platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = line.strip()
                    break
    except OSError:
        pass
    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "unknown")
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jl = "unknown"
    key = "|".join([flags, jl, os.environ.get("JAX_PLATFORMS", ""),
                    "tunnel" if os.environ.get("PALLAS_AXON_POOL_IPS")
                    else "local"])
    return hashlib.sha256(key.encode()).hexdigest()[:16]
