"""Disk-failure detector.

Reference parity: detector/DiskFailureDetector.java:120 — describe log dirs
across alive brokers, collect offline dirs, emit a DiskFailures anomaly
whose fix is FIX_OFFLINE_REPLICAS. The log-dir describe is an optional
backend capability (JBOD deployments); backends without it report none.
"""

from __future__ import annotations

import logging
from typing import Callable, Mapping, Sequence

from ..executor.admin import AdminBackend
from .anomaly import DiskFailures

LOG = logging.getLogger(__name__)


class DiskFailureDetector:
    #: Heal-ledger all-clear contract (detector/manager.py): a run that
    #: found no offline log dirs re-checked the violation clear.
    CLEARS = ("DISK_FAILURE",)

    def __init__(self, metadata: AdminBackend,
                 report: Callable[[DiskFailures], None]):
        self._metadata = metadata
        self._report = report
        self._last_reported: dict[int, tuple[str, ...]] = {}
        self._last_offline_empty = False

    def all_clear(self) -> bool:
        return self._last_offline_empty

    def _offline_dirs(self) -> Mapping[int, Sequence[str]]:
        describe = getattr(self._metadata, "describe_logdirs", None)
        if describe is None:
            return {}
        offline: dict[int, list[str]] = {}
        for broker, dirs in describe().items():
            bad = [d for d, online in dirs.items() if not online]
            if bad:
                offline[broker] = bad
        return offline

    def run_once(self) -> DiskFailures | None:
        offline = self._offline_dirs()
        snapshot = {b: tuple(sorted(d)) for b, d in offline.items()}
        self._last_offline_empty = not snapshot
        if not snapshot or snapshot == self._last_reported:
            if not snapshot:
                self._last_reported = {}
            return None
        self._last_reported = snapshot
        anomaly = DiskFailures(failed_disks={b: list(d) for b, d in snapshot.items()})
        self._report(anomaly)
        return anomaly
