"""Provisioner SPI + right-sizing recommendation records.

Reference parity: detector/Provisioner.java SPI with
BasicProvisioner/BasicBrokerProvisioner/PartitionProvisioner, and the
ProvisionResponse/ProvisionStatus/ProvisionRecommendation records the
analyzer attaches to optimizer results (analyzer/ProvisionStatus.java).

The under/over-provisioned signal itself comes from the goal kernels: a
capacity goal that cannot place all load ⇒ UNDER_PROVISIONED; every broker
far below the low-utilization threshold ⇒ OVER_PROVISIONED.
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass, field
from typing import Protocol

LOG = logging.getLogger(__name__)


class ProvisionStatus(enum.Enum):
    RIGHT_SIZED = "RIGHT_SIZED"
    UNDER_PROVISIONED = "UNDER_PROVISIONED"
    OVER_PROVISIONED = "OVER_PROVISIONED"
    UNDECIDED = "UNDECIDED"


@dataclass(frozen=True)
class ProvisionRecommendation:
    """ProvisionRecommendation.java — how many brokers/partitions to add
    (positive) or remove (negative), and for which resource/topic."""

    status: ProvisionStatus
    num_brokers: int = 0
    num_partitions: int = 0
    topic: str | None = None
    resource: str | None = None

    def to_dict(self) -> dict:
        return {"status": self.status.value, "numBrokers": self.num_brokers,
                "numPartitions": self.num_partitions, "topic": self.topic,
                "resource": self.resource}


@dataclass
class ProvisionResponse:
    """ProvisionResponse.java — aggregated status + recommendations."""

    status: ProvisionStatus = ProvisionStatus.UNDECIDED
    recommendations: list[ProvisionRecommendation] = field(default_factory=list)

    def aggregate(self, rec: ProvisionRecommendation) -> None:
        # UNDER dominates OVER dominates RIGHT_SIZED (ProvisionResponse.java).
        order = [ProvisionStatus.UNDECIDED, ProvisionStatus.RIGHT_SIZED,
                 ProvisionStatus.OVER_PROVISIONED, ProvisionStatus.UNDER_PROVISIONED]
        if order.index(rec.status) > order.index(self.status):
            self.status = rec.status
        if rec.status is not ProvisionStatus.RIGHT_SIZED:
            self.recommendations.append(rec)


class ProvisionerState(enum.Enum):
    COMPLETED = "COMPLETED"
    COMPLETED_WITH_ERROR = "COMPLETED_WITH_ERROR"
    IN_PROGRESS = "IN_PROGRESS"


class Provisioner(Protocol):
    """Provisioner.java SPI — carry out a rightsize action against the
    deployment substrate (cloud API, k8s operator, ticket queue...)."""

    def rightsize(self, recommendations: list[ProvisionRecommendation],
                  ) -> ProvisionerState: ...


class BasicProvisioner:
    """BasicProvisioner.java — records the actions it would take; concrete
    deployments subclass and call their infra API."""

    def __init__(self):
        self.executed: list[ProvisionRecommendation] = []

    def rightsize(self, recommendations: list[ProvisionRecommendation],
                  ) -> ProvisionerState:
        for rec in recommendations:
            LOG.info("provisioner action: %s", rec.to_dict())
            self.executed.append(rec)
        return ProvisionerState.COMPLETED
