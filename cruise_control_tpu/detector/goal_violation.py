"""Goal-violation detector.

Reference parity: detector/GoalViolationDetector.java — on each interval,
skip when the model generation is unchanged (:136), build a fresh cluster
model, replay the ``anomaly.detection.goals`` WITHOUT executing, classify
violations fixable/unfixable, refresh the cluster balancedness score
(:282-287). The whole detection pass rides the batched TPU optimizer: one
``GoalOptimizer.optimizations`` call scores and (virtually) fixes every
goal at once.
"""

from __future__ import annotations

import logging
from typing import Callable

from ..analyzer.optimizer import (
    GoalOptimizer, OptimizerResult, balancedness_score, goals_by_priority,
)
from ..config.cruise_control_config import CruiseControlConfig
from ..monitor.load_monitor import LoadMonitor, ModelCompletenessRequirements
from .anomaly import GoalViolations

LOG = logging.getLogger(__name__)


class GoalViolationDetector:
    #: Heal-ledger all-clear contract (detector/manager.py): a full
    #: detection pass that found NO violations re-checked the violation
    #: clear. Generation-unchanged or model-unready runs keep the last
    #: full pass's verdict (nothing changed since).
    CLEARS = ("GOAL_VIOLATION",)

    def __init__(self, config: CruiseControlConfig, load_monitor: LoadMonitor,
                 optimizer: GoalOptimizer,
                 report: Callable[[GoalViolations], None]):
        self._config = config
        self._load_monitor = load_monitor
        self._optimizer = optimizer
        self._report = report
        self._goals = goals_by_priority(
            config, config.get_list("anomaly.detection.goals"))
        from ..analyzer.plugins import options_generator_from_config
        self._options_generator = options_generator_from_config(config)
        # The facade wires a snapshot supplier over its recently-removed/
        # demoted broker sets so detection excludes them like the
        # reference's detector does (GoalViolationDetector.java
        # optimizationOptions call). A SUPPLIER, not the live sets: the
        # detection thread iterating a set an API thread is mutating
        # in-place would raise mid-cycle; the facade copies under its own
        # lock.
        self.excluded_brokers_supplier: Callable[
            [], tuple[tuple[int, ...], tuple[int, ...]]] = lambda: ((), ())
        self._last_checked_generation = -1
        self._balancedness_score = 100.0
        self._last_result: OptimizerResult | None = None
        self._last_pass_clear = False
        self._priority_weight = config.get_double("goal.balancedness.priority.weight")
        self._strictness_weight = config.get_double("goal.balancedness.strictness.weight")

    @property
    def balancedness_score(self) -> float:
        """The 0..100 cluster balancedness gauge (:282-287, §A.4)."""
        return self._balancedness_score

    @property
    def last_result(self) -> OptimizerResult | None:
        return self._last_result

    def all_clear(self) -> bool:
        return self._last_pass_clear

    def run_once(self) -> GoalViolations | None:
        gen = self._load_monitor.model_generation
        if gen == self._last_checked_generation:
            LOG.debug("model generation %d unchanged; skipping detection", gen)
            return None
        try:
            state, meta = self._load_monitor.cluster_model(
                ModelCompletenessRequirements(
                    min_valid_windows=1,
                    min_monitored_partitions_percentage=self._config.get(
                        "min.valid.partition.ratio")))
        except Exception as e:
            LOG.info("skipping goal-violation detection: %s", e)
            return None
        self._last_checked_generation = gen

        no_leadership, no_replicas = self.excluded_brokers_supplier()
        options = self._options_generator.for_goal_violation_detection(
            meta.topic_names, (), sorted(no_leadership),
            sorted(no_replicas))
        _final, result = self._optimizer.optimizations(state, meta,
                                                       self._goals, options)
        self._last_result = result
        # Fixable = violated before and satisfiable by the solver; unfixable =
        # still violated after optimization (GoalViolationDetector fixability
        # classification).
        fixable = [g for g in result.violated_goals_before
                   if g not in result.violated_goals_after]
        unfixable = list(result.violated_goals_after)
        self._balancedness_score = balancedness_score(
            self._goals, set(result.violated_goals_before),
            self._priority_weight, self._strictness_weight)
        self._last_pass_clear = not fixable and not unfixable
        if not fixable and not unfixable:
            return None
        violations = GoalViolations(fixable_goals=fixable,
                                    unfixable_goals=unfixable)
        self._report(violations)
        return violations
