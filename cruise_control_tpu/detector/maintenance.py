"""Maintenance-event ingestion.

Reference parity: detector/MaintenanceEventDetector.java +
MaintenanceEventTopicReader.java:350 (consume maintenance plans from a
Kafka topic) + IdempotenceCache.java:106 (drop duplicate plans within a
retention window). The reader is a pluggable source; the default is an
in-memory queue (tests, embedding) and a JSON-lines file reader stands in
for the Kafka topic in file-backed deployments.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Callable, Iterable, Protocol

from .anomaly import MaintenanceEvent, MaintenanceEventType

LOG = logging.getLogger(__name__)


class MaintenanceEventReader(Protocol):
    def read_events(self) -> Iterable[MaintenanceEvent]: ...


class InMemoryMaintenanceEventReader:
    """Test/embedded source: plans are submitted programmatically."""

    def __init__(self):
        self._queue: list[MaintenanceEvent] = []

    def submit(self, event: MaintenanceEvent) -> None:
        self._queue.append(event)

    def read_events(self) -> list[MaintenanceEvent]:
        out, self._queue = self._queue, []
        return out


class FileMaintenanceEventReader:
    """JSON-lines file tail (the file plays the metrics-topic role):
    each line {"type": ..., "brokers": [...], "topics_by_rf": {...}}."""

    def __init__(self, path: str):
        self._path = path
        self._offset = 0

    def read_events(self) -> list[MaintenanceEvent]:
        if not os.path.exists(self._path):
            return []
        events: list[MaintenanceEvent] = []
        with open(self._path) as f:
            f.seek(self._offset)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                    events.append(MaintenanceEvent(
                        event_type=MaintenanceEventType(d["type"]),
                        broker_ids=d.get("brokers", []),
                        topics_by_rf={int(k): v for k, v in
                                      d.get("topics_by_rf", {}).items()}))
                except Exception:
                    LOG.exception("bad maintenance plan line: %r", line)
            self._offset = f.tell()
        return events


class IdempotenceCache:
    """IdempotenceCache.java — drop plans identical to one seen within the
    retention window."""

    def __init__(self, retention_ms: int = 3_600_000,
                 now_ms: Callable[[], int] | None = None):
        self._retention_ms = retention_ms
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._seen: dict[tuple, int] = {}

    def _key(self, e: MaintenanceEvent) -> tuple:
        return (e.event_type.value, tuple(sorted(e.broker_ids)),
                tuple(sorted((rf, tuple(sorted(ts)))
                             for rf, ts in e.topics_by_rf.items())))

    def is_duplicate(self, event: MaintenanceEvent) -> bool:
        now = self._now_ms()
        self._seen = {k: t for k, t in self._seen.items()
                      if now - t < self._retention_ms}
        key = self._key(event)
        if key in self._seen:
            return True
        self._seen[key] = now
        return False


class MaintenanceEventDetector:
    def __init__(self, reader: MaintenanceEventReader,
                 report: Callable[[MaintenanceEvent], None],
                 idempotence_retention_ms: int = 3_600_000,
                 now_ms: Callable[[], int] | None = None):
        # ``now_ms`` is the idempotence window's clock seam: the simulator
        # injects simulated time so duplicate-plan suppression ages out on
        # sim time, not wall time. Default (None) stays wall clock.
        self._reader = reader
        self._report = report
        self._cache = IdempotenceCache(idempotence_retention_ms,
                                       now_ms=now_ms)

    def run_once(self) -> list[MaintenanceEvent]:
        out = []
        for event in self._reader.read_events():
            if self._cache.is_duplicate(event):
                LOG.info("dropping duplicate maintenance plan %s", event.reasons())
                continue
            self._report(event)
            out.append(event)
        return out
