"""Anomaly SPI.

Reference parity: cruise-control-core detector/Anomaly.java,
detector/AnomalyType.java, and the concrete anomaly records under
cruise-control detector/ (GoalViolations.java, BrokerFailures.java,
DiskFailures.java, KafkaMetricAnomaly.java, TopicAnomaly.java,
MaintenanceEvent.java), plus notifier/KafkaAnomalyType.java priorities.

An anomaly is a host-side record; ``fix()`` dispatches the matching
self-healing operation on the facade (the reference's runnables,
AnomalyDetectorManager.java:549). Device math stays inside the detectors
that created the anomaly.
"""

from __future__ import annotations

import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence


class AnomalyType(enum.Enum):
    """Priority-ordered anomaly taxonomy (KafkaAnomalyType.java:62 — lower
    value = higher priority in the handler queue)."""

    BROKER_FAILURE = 0
    DISK_FAILURE = 1
    METRIC_ANOMALY = 2
    GOAL_VIOLATION = 3
    TOPIC_ANOMALY = 4
    MAINTENANCE_EVENT = 5
    # Predictive rebalancing (round 19, no reference analogue — the
    # reference is purely reactive): a goal violation the forecaster
    # PROJECTS within the horizon. Lowest priority: a prediction must
    # never preempt a real anomaly in the fix queue.
    PREDICTED_GOAL_VIOLATION = 6
    # SLO burn (no reference analogue — the reference has no SLO
    # evaluation at all): an objective's fast+slow burn windows both
    # over threshold (utils/slo.py, detector/slo_burn.py). Lowest
    # priority: budget burn is a service-quality signal, never more
    # urgent than a concrete fault.
    SLO_BURN = 7

    @property
    def priority(self) -> int:
        return self.value


_anomaly_seq = itertools.count()


def _now_ms() -> int:
    return int(time.time() * 1000)


@dataclass
class Anomaly:
    """Base anomaly (Anomaly.java). ``fix`` returns True when a fix was
    started (Anomaly.fix contract)."""

    anomaly_type: AnomalyType = AnomalyType.GOAL_VIOLATION
    detection_time_ms: int = field(default_factory=_now_ms)
    anomaly_id: str = field(default_factory=lambda: f"anomaly-{next(_anomaly_seq)}")

    def reasons(self) -> list[str]:
        return []

    def fix(self, facade: Any) -> bool:
        raise NotImplementedError

    def still_valid(self, facade: Any) -> bool:
        """Re-validated when a parked (CHECK_WITH_DELAY) anomaly is re-taken:
        a stale snapshot must not trigger a fix after the condition cleared
        (the reference re-RUNS detection on recheck; here the snapshot
        revalidates against live cluster state)."""
        return True

    @property
    def self_healing_config_key(self) -> str:
        return {
            AnomalyType.BROKER_FAILURE: "self.healing.broker.failure.enabled",
            AnomalyType.DISK_FAILURE: "self.healing.disk.failure.enabled",
            AnomalyType.METRIC_ANOMALY: "self.healing.metric.anomaly.enabled",
            AnomalyType.GOAL_VIOLATION: "self.healing.goal.violation.enabled",
            AnomalyType.TOPIC_ANOMALY: "self.healing.topic.anomaly.enabled",
            AnomalyType.MAINTENANCE_EVENT: "self.healing.maintenance.event.enabled",
            AnomalyType.PREDICTED_GOAL_VIOLATION:
                "self.healing.predicted.violation.enabled",
            AnomalyType.SLO_BURN: "self.healing.slo.burn.enabled",
        }[self.anomaly_type]

    def __lt__(self, other: "Anomaly") -> bool:
        # PriorityBlockingQueue ordering: type priority, then detection time.
        return (self.anomaly_type.priority, self.detection_time_ms) < (
            other.anomaly_type.priority, other.detection_time_ms)


@dataclass
class GoalViolations(Anomaly):
    """detector/GoalViolations.java — fixable/unfixable violated goals from
    one detection pass; fix = self-healing rebalance over the configured
    detection goals."""

    fixable_goals: list[str] = field(default_factory=list)
    unfixable_goals: list[str] = field(default_factory=list)

    def __post_init__(self):
        self.anomaly_type = AnomalyType.GOAL_VIOLATION

    def reasons(self) -> list[str]:
        out = [f"fixable goal violation: {g}" for g in self.fixable_goals]
        out += [f"unfixable goal violation: {g}" for g in self.unfixable_goals]
        return out

    def fix(self, facade: Any) -> bool:
        if not self.fixable_goals:
            return False
        # The self-healing plan must honor the same exclusions detection
        # classified fixability under (self.healing.exclude.recently.*
        # configs) — otherwise a 'fixable' verdict computed with broker 7
        # excluded could be fixed by moving replicas back onto broker 7.
        cfg = getattr(facade, "config", None)
        if not hasattr(cfg, "get_boolean"):  # test doubles without config
            cfg = None
        facade.rebalance(
            goals=None, dryrun=False,
            exclude_recently_demoted_brokers=cfg.get_boolean(
                "self.healing.exclude.recently.demoted.brokers")
            if cfg else True,
            exclude_recently_removed_brokers=cfg.get_boolean(
                "self.healing.exclude.recently.removed.brokers")
            if cfg else True,
            is_triggered_by_user_request=False,
            reason=f"self-healing goal violation {self.fixable_goals}")
        return True


@dataclass
class BrokerFailures(Anomaly):
    """detector/BrokerFailures.java — brokers that left the cluster, with
    first-seen failure times; fix = remove_brokers (self-healing)."""

    failed_brokers: Mapping[int, int] = field(default_factory=dict)  # id → ms

    def __post_init__(self):
        self.anomaly_type = AnomalyType.BROKER_FAILURE

    def reasons(self) -> list[str]:
        return [f"broker {b} failed at {t}" for b, t in
                sorted(self.failed_brokers.items())]

    def fix(self, facade: Any) -> bool:
        if not self.failed_brokers:
            return False
        facade.remove_brokers(sorted(self.failed_brokers), dryrun=False,
                              is_triggered_by_user_request=False,
                              reason="self-healing broker failure")
        return True

    def still_valid(self, facade: Any) -> bool:
        alive_fn = getattr(facade, "alive_brokers", None)
        if alive_fn is None:
            return True
        alive = alive_fn()
        return any(b not in alive for b in self.failed_brokers)


@dataclass
class DiskFailures(Anomaly):
    """detector/DiskFailures.java — offline log dirs per broker; fix =
    fix_offline_replicas."""

    failed_disks: Mapping[int, Sequence[str]] = field(default_factory=dict)

    def __post_init__(self):
        self.anomaly_type = AnomalyType.DISK_FAILURE

    def reasons(self) -> list[str]:
        return [f"broker {b} offline dirs {sorted(d)}"
                for b, d in sorted(self.failed_disks.items())]

    def fix(self, facade: Any) -> bool:
        if not self.failed_disks:
            return False
        facade.fix_offline_replicas(dryrun=False,
                                    is_triggered_by_user_request=False,
                                    reason="self-healing disk failure")
        return True


@dataclass
class MetricAnomaly(Anomaly):
    """detector/KafkaMetricAnomaly.java + SlowBrokerFinder verdicts; fix =
    demote (leadership off) or remove the slow brokers."""

    broker_ids: Sequence[int] = field(default_factory=list)
    metric_name: str = ""
    description: str = ""
    fix_by_removal: bool = False  # SlowBrokerFinder.java:43 remove vs demote

    def __post_init__(self):
        self.anomaly_type = AnomalyType.METRIC_ANOMALY

    def reasons(self) -> list[str]:
        return [f"metric anomaly on broker {b}: {self.metric_name} "
                f"{self.description}" for b in self.broker_ids]

    def fix(self, facade: Any) -> bool:
        if not self.broker_ids:
            return False
        if self.fix_by_removal:
            facade.remove_brokers(list(self.broker_ids), dryrun=False,
                                  is_triggered_by_user_request=False,
                                  reason="self-healing slow broker removal")
        else:
            facade.demote_brokers(list(self.broker_ids), dryrun=False,
                                  is_triggered_by_user_request=False,
                                  reason="self-healing slow broker demotion")
        return True


@dataclass
class TopicAnomaly(Anomaly):
    """detector/TopicAnomaly.java / TopicReplicationFactorAnomalyFinder —
    topics whose RF deviates from the desired value; fix = RF update."""

    topics_by_desired_rf: Mapping[int, Sequence[str]] = field(default_factory=dict)

    def __post_init__(self):
        self.anomaly_type = AnomalyType.TOPIC_ANOMALY

    def reasons(self) -> list[str]:
        return [f"topics needing RF={rf}: {sorted(ts)}"
                for rf, ts in sorted(self.topics_by_desired_rf.items())]

    def fix(self, facade: Any) -> bool:
        if not self.topics_by_desired_rf:
            return False
        skip = facade.config.get_boolean(
            "replication.factor.self.healing.skip.rack.awareness.check")
        for rf, topics in sorted(self.topics_by_desired_rf.items()):
            facade.update_topic_replication_factor(
                list(topics), rf, dryrun=False,
                is_triggered_by_user_request=False,
                skip_rack_awareness_check=skip,
                reason="self-healing topic replication factor")
        return True


@dataclass
class PredictedGoalViolations(Anomaly):
    """Round 19 (no reference analogue): goal violations the forecaster
    PROJECTS ``horizon_s`` seconds ahead — a first-class anomaly whose
    heal-ledger chain carries ``predicted=true``. The fix NEVER
    auto-executes by default: it precomputes the proposal on the
    PROJECTED model (warming the facade's warm-seed store and flagging
    the fleet pacer for an immediate cache fill) so the answer is hot
    the moment the real violation lands. The opt-in
    ``anomaly.detection.predictive.fix.enabled`` gate turns the fix
    into a real proactive rebalance."""

    predicted_goals: list[str] = field(default_factory=list)
    horizon_s: float = 0.0
    confidence_band: float = 0.0   # max residual-RMS band of the fit

    def __post_init__(self):
        self.anomaly_type = AnomalyType.PREDICTED_GOAL_VIOLATION

    def reasons(self) -> list[str]:
        return [f"predicted goal violation in {self.horizon_s:.0f}s: {g}"
                for g in self.predicted_goals]

    def fix(self, facade: Any) -> bool:
        if not self.predicted_goals:
            return False
        cfg = getattr(facade, "config", None)
        if not hasattr(cfg, "get_boolean"):  # test doubles without config
            cfg = None
        fix_fn = getattr(facade, "fix_predicted_violation", None)
        if fix_fn is None:
            return False
        # The fix always solves the PROJECTED model (a current-model
        # rebalance would see nothing wrong yet); the opt-in gate only
        # decides whether those proposals EXECUTE or precompute.
        execute = bool(cfg is not None and cfg.get_boolean(
            "anomaly.detection.predictive.fix.enabled"))
        return fix_fn(
            execute=execute,
            reason=f"proactive predicted violation {self.predicted_goals}",
            anomaly_id=self.anomaly_id)


@dataclass
class SloBurn(Anomaly):
    """SLO burn-rate anomaly (no reference analogue): one objective's
    error budget burning fast enough that BOTH multi-window pairs
    (utils/slo.py) agree. The signature is the OBJECTIVE, so a standing
    burn aliases onto one heal chain; the chain resolves ``cleared``
    when the budget recovers (detector/slo_burn.py). The fix never
    mutates the cluster: it stamps the heal chain and flags the pacer
    for an immediate precompute so a capacity answer is hot — burning
    budget is a service-quality signal, not a placement fault."""

    objective: str = ""
    fast_burn: float = 0.0     # burn rate over the fast (shortest) window
    slow_burn: float = 0.0     # burn rate over the slow-confirm window
    budget_remaining: float = 0.0

    def __post_init__(self):
        self.anomaly_type = AnomalyType.SLO_BURN

    def reasons(self) -> list[str]:
        return [f"SLO burn on objective {self.objective!r}: "
                f"fast burn {self.fast_burn:.1f}x, "
                f"slow burn {self.slow_burn:.1f}x, "
                f"budget remaining {self.budget_remaining:.2f}"]

    def fix(self, facade: Any) -> bool:
        if not self.objective:
            return False
        fix_fn = getattr(facade, "fix_slo_burn", None)
        if fix_fn is None:
            return False
        return fix_fn(
            objective=self.objective,
            reason=f"SLO burn on {self.objective} "
                   f"(fast {self.fast_burn:.1f}x / "
                   f"slow {self.slow_burn:.1f}x)",
            anomaly_id=self.anomaly_id)


class MaintenanceEventType(enum.Enum):
    """MaintenancePlan taxonomy (detector/MaintenanceEventType.java)."""

    ADD_BROKER = "ADD_BROKER"
    REMOVE_BROKER = "REMOVE_BROKER"
    FIX_OFFLINE_REPLICAS = "FIX_OFFLINE_REPLICAS"
    REBALANCE = "REBALANCE"
    DEMOTE_BROKER = "DEMOTE_BROKER"
    TOPIC_REPLICATION_FACTOR = "TOPIC_REPLICATION_FACTOR"


@dataclass
class MaintenanceEvent(Anomaly):
    """detector/MaintenanceEvent.java — an externally submitted maintenance
    plan (the reference reads these from a Kafka topic)."""

    event_type: MaintenanceEventType = MaintenanceEventType.REBALANCE
    broker_ids: Sequence[int] = field(default_factory=list)
    topics_by_rf: Mapping[int, Sequence[str]] = field(default_factory=dict)

    def __post_init__(self):
        self.anomaly_type = AnomalyType.MAINTENANCE_EVENT

    def reasons(self) -> list[str]:
        return [f"maintenance {self.event_type.value} brokers={list(self.broker_ids)}"]

    def fix(self, facade: Any) -> bool:
        t = MaintenanceEventType
        kw = dict(dryrun=False, is_triggered_by_user_request=False,
                  reason=f"maintenance event {self.event_type.value}")
        if self.event_type is t.ADD_BROKER:
            facade.add_brokers(list(self.broker_ids), **kw)
        elif self.event_type is t.REMOVE_BROKER:
            facade.remove_brokers(list(self.broker_ids), **kw)
        elif self.event_type is t.DEMOTE_BROKER:
            facade.demote_brokers(list(self.broker_ids), **kw)
        elif self.event_type is t.FIX_OFFLINE_REPLICAS:
            facade.fix_offline_replicas(**kw)
        elif self.event_type is t.TOPIC_REPLICATION_FACTOR:
            skip = facade.config.get_boolean(
                "replication.factor.self.healing.skip.rack.awareness.check")
            for rf, topics in sorted(self.topics_by_rf.items()):
                facade.update_topic_replication_factor(
                    list(topics), rf, skip_rack_awareness_check=skip, **kw)
        else:
            facade.rebalance(goals=None, **kw)
        return True
