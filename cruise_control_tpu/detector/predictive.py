"""Predictive goal-violation detector (round 19).

The inverse of ``GoalViolationDetector``: instead of replaying the
detection goals on the CURRENT model, run the forecaster's PROJECTED
model — the horizon-peak load planes from ``forecast/engine.py`` —
through the same ONE batched goal-stats program the fingerprint skip
uses (``GoalOptimizer.goal_entry_stats`` → ``chain_all_goal_stats``,
round 18's entry snapshot), and report goals that are clean NOW but
violated AT THE HORIZON as first-class ``PredictedGoalViolations``
anomalies.

Lifecycle honesty (the hit-rate ledger):

- A standing prediction re-reported each interval aliases onto ONE heal
  chain (the manager's signature dedup), stamped ``predicted=true``.
- When the real violation lands within the horizon, the prediction is
  CONFIRMED: its chain resolves ``cleared`` (via=prediction_confirmed,
  the real violation's own chain takes over the heal) and
  ``anomaly_predicted_confirmed`` counts the hit.
- When the deadline passes without the real violation, the prediction
  MISSED: the chain resolves ``self_cleared`` and
  ``anomaly_predicted_missed`` counts the miss — GET /forecast serves
  the running hit rate.

Off means off: with ``forecast.enabled=false`` a detector tick is one
config read (the bench ``forecast_noop_overhead`` probe); serving
behavior is byte-identical to a build without the detector.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

from ..config.cruise_control_config import CruiseControlConfig
from .anomaly import PredictedGoalViolations

LOG = logging.getLogger(__name__)


class PredictiveViolationDetector:
    #: Heal-ledger all-clear seam (detector/manager.py): a full pass
    #: whose horizon shows NO predicted violation re-checked the clear —
    #: but predictions resolve through the confirm/miss bookkeeping
    #: below (self_cleared on a miss, cleared on a confirm), so the
    #: generic all-clear stays out of the way.
    CLEARS = ()

    def __init__(self, config: CruiseControlConfig, engine,
                 optimizer, report: Callable,
                 ledger=None, clock: Callable[[], float] | None = None):
        self._config = config
        self._engine = engine
        self._optimizer = optimizer
        self._report = report
        self._ledger = ledger
        self._clock = clock or time.time
        from ..analyzer.optimizer import goals_by_priority
        self._goals = goals_by_priority(
            config, config.get_list("anomaly.detection.goals"))
        from ..analyzer.plugins import options_generator_from_config
        self._options_generator = options_generator_from_config(config)
        # Same exclusion discipline as GoalViolationDetector: the facade
        # wires a snapshot supplier over its recently-removed/demoted
        # history.
        self.excluded_brokers_supplier: Callable[
            [], tuple[tuple[int, ...], tuple[int, ...]]] = lambda: ((), ())
        self._last_checked_generation = -1
        # Open predictions: anomaly_id -> (deadline_s on the injected
        # clock, frozenset of predicted goal names).
        self._open: dict[str, tuple[float, frozenset]] = {}
        self._last_prediction: list[str] = []
        # Predictions whose own proactive fix EXECUTED (facade
        # fix_predicted_violation(execute=True) marks them): a lapse
        # without the real violation is then an AVERTED heal, not a
        # forecasting miss.
        self._proactive_fixed: set[str] = set()
        self.predictions_made = 0
        self.predictions_confirmed = 0
        self.predictions_missed = 0
        self.predictions_averted = 0

    def note_proactive_fix(self, anomaly_id: str) -> None:
        """Facade callback: this prediction's proactive fix executed —
        a later lapse without the real violation is an averted heal."""
        self._proactive_fixed.add(anomaly_id)

    # -- state (GET /forecast body) ----------------------------------------
    def state(self) -> dict:
        # Hit rate over the settled predictions whose outcome says
        # something about forecast ACCURACY: confirmed + averted are
        # hits (the violation arrived, or the fix we ran on its account
        # removed it), plain lapses are misses.
        hits = self.predictions_confirmed + self.predictions_averted
        total = hits + self.predictions_missed
        return {
            "openPredictions": sorted(
                g for _dl, gs in self._open.values() for g in gs),
            "lastPrediction": list(self._last_prediction),
            "predictionsMade": self.predictions_made,
            "predictionsConfirmed": self.predictions_confirmed,
            "predictionsAverted": self.predictions_averted,
            "predictionsMissed": self.predictions_missed,
            "hitRate": round(hits / total, 3) if total else None,
        }

    # -- the pass ----------------------------------------------------------
    def run_once(self) -> PredictedGoalViolations | None:
        if not self._engine.enabled:
            # Off means off for NEW work — but predictions opened before
            # the flip must still lapse to their terminal, or their heal
            # chains leak open forever. Guarded on _open so the disabled
            # tick stays one config read (the noop-overhead probe).
            if self._open:
                self._settle_open(set(), [])
            return None
        result = self._engine.forecast()
        if result is None:
            # No current forecast (monitor lost its stable windows):
            # nothing backs the "still predicted" claim, so open
            # predictions must lapse on their deadlines rather than be
            # held open forever by the STALE last-prediction list.
            if self._open:
                self._settle_open(set(), [])
            return None
        if result.generation == self._last_checked_generation:
            # Nothing new to predict from, but deadlines still advance
            # on the injected clock: lapsed predictions must resolve.
            if self._open:
                self._settle_open(set(), self._last_prediction)
            return None
        self._last_checked_generation = result.generation

        no_leadership, no_replicas = self.excluded_brokers_supplier()
        options = self._options_generator.for_goal_violation_detection(
            result.meta.topic_names, (), sorted(no_leadership),
            sorted(no_replicas))
        # TWO entry snapshots through the ONE batched stats program
        # (round 18's chain_all_goal_stats): the current model separates
        # "already violated" (the reactive detector's job) from
        # "violated only at the horizon" (ours).
        chain, viol_now, _obj_now, _off_now = \
            self._optimizer.goal_entry_stats(
                result.state, result.meta, self._goals, options)
        _chain, viol_h, _obj_h, _off_h = self._optimizer.goal_entry_stats(
            result.projected_state, result.meta, self._goals, options)
        now_set = {g.name for g, v in zip(chain, viol_now)
                   if float(v) > 1e-6}
        horizon_set = {g.name for g, v in zip(chain, viol_h)
                       if float(v) > 1e-6}
        predicted = sorted(horizon_set - now_set)
        self._last_prediction = predicted
        self._settle_open(now_set, predicted)
        if not predicted:
            return None
        for anomaly_id, (_dl, goals) in self._open.items():
            if goals & set(predicted):
                # The SAME standing incident (any goal overlap — a
                # prediction whose goal set grows is still one
                # incident, not a second chain): absorb the new goals,
                # refresh the deadline (the condition is still
                # forecast, so the horizon slides), and do not
                # re-report — one incident, one chain, one
                # fix/precompute.
                self._open[anomaly_id] = (
                    self._clock() + result.horizon_s,
                    goals | frozenset(predicted))
                return None
        anomaly = PredictedGoalViolations(
            predicted_goals=predicted, horizon_s=result.horizon_s,
            confidence_band=round(float(result.band.max()), 4)
            if result.band.size else 0.0)
        self._report(anomaly)
        self._open[anomaly.anomaly_id] = (
            self._clock() + result.horizon_s, frozenset(predicted))
        self.predictions_made += 1
        from ..utils.sensors import SENSORS
        SENSORS.count("anomaly_predicted_violations")
        if self._ledger is not None:
            # The predicted=true stamp: GET /heals shows the chain as a
            # prediction from its first phase (re-detections alias onto
            # the same chain, so the stamp lands once per incident).
            self._ledger.handle_for(anomaly.anomaly_id).phase(
                "predicted", predicted=True, goals=predicted,
                horizonS=round(result.horizon_s, 3),
                confidenceBand=anomaly.confidence_band)
        return anomaly

    def _settle_open(self, now_violated: set[str],
                     still_predicted: list[str]) -> None:
        """Resolve open predictions: confirmed when the real violation
        landed, missed when the deadline lapsed without it. A prediction
        still inside its window and still forecast stays open (the next
        report aliases onto its chain)."""
        from ..utils.sensors import SENSORS
        now = self._clock()
        pred_set = set(still_predicted)
        for anomaly_id, (deadline, goals) in list(self._open.items()):
            if goals & now_violated:
                del self._open[anomaly_id]
                self._proactive_fixed.discard(anomaly_id)
                self.predictions_confirmed += 1
                SENSORS.count("anomaly_predicted_confirmed")
                if self._ledger is not None:
                    self._ledger.handle_for(anomaly_id).resolve(
                        "cleared", via="prediction_confirmed",
                        predicted=True)
            elif now >= deadline and not (goals & pred_set):
                del self._open[anomaly_id]
                if anomaly_id in self._proactive_fixed:
                    # The prediction's OWN proactive fix executed and
                    # the violation never arrived: averted, the
                    # predictive campaign's win condition.
                    self._proactive_fixed.discard(anomaly_id)
                    self.predictions_averted += 1
                    SENSORS.count("anomaly_predicted_averted")
                    if self._ledger is not None:
                        self._ledger.handle_for(anomaly_id).resolve(
                            "cleared", via="violation_averted",
                            predicted=True)
                else:
                    # Past the horizon AND no longer forecast: the
                    # documented self_cleared terminal for a missed
                    # prediction.
                    self.predictions_missed += 1
                    SENSORS.count("anomaly_predicted_missed")
                    if self._ledger is not None:
                        self._ledger.handle_for(anomaly_id).resolve(
                            "self_cleared", via="prediction_missed",
                            predicted=True)
