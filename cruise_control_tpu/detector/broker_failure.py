"""Broker-failure detector with durable failure records.

Reference parity: detector/AbstractBrokerFailureDetector.java (failure-time
persistence to ``failed.brokers.file.path``:53,92-117 so restarts remember
prior failures) + KafkaBrokerFailureDetector.java (metadata-polling
liveness — the modern replacement for the legacy ZK watcher, which this
framework intentionally does not carry: the metadata backend is the single
source of liveness truth).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Callable

from ..executor.admin import AdminBackend
from .anomaly import BrokerFailures

LOG = logging.getLogger(__name__)


class BrokerFailureDetector:
    #: Heal-ledger all-clear contract (detector/manager.py): a run that
    #: found no failed brokers re-checked the violation clear.
    CLEARS = ("BROKER_FAILURE",)

    def __init__(self, metadata: AdminBackend,
                 report: Callable[[BrokerFailures], None],
                 failed_brokers_file_path: str = "",
                 now_ms: Callable[[], int] | None = None):
        self._metadata = metadata
        self._report = report
        self._path = failed_brokers_file_path
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._failed: dict[int, int] = {}          # broker id → first-seen ms
        self._load_persisted_failures()

    @property
    def failed_brokers(self) -> dict[int, int]:
        return dict(self._failed)

    def all_clear(self) -> bool:
        """True when the last run observed no broker hosting replicas
        while dead — the heal ledger's violation re-check."""
        return not self._failed

    # -- persistence (AbstractBrokerFailureDetector.java:92-117) -----------
    def _load_persisted_failures(self) -> None:
        if not self._path or not os.path.exists(self._path):
            return
        try:
            with open(self._path) as f:
                self._failed = {int(k): int(v) for k, v in json.load(f).items()}
        except Exception:
            LOG.exception("could not parse failed-broker file %s", self._path)

    def _persist(self) -> None:
        if not self._path:
            return
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._failed.items()}, f)
        os.replace(tmp, self._path)

    # -- detection ---------------------------------------------------------
    def _expected_brokers(self) -> set[int]:
        """All brokers hosting replicas per current metadata — a broker is
        'failed' when it holds replicas but is not alive (MonitorUtils)."""
        expected: set[int] = set()
        for st in self._metadata.describe_partitions().values():
            expected |= set(st.replicas)
        return expected

    def run_once(self) -> BrokerFailures | None:
        alive = self._metadata.alive_brokers()
        dead = self._expected_brokers() - alive
        changed = False
        for b in dead:
            if b not in self._failed:
                self._failed[b] = self._now_ms()
                changed = True
        for b in list(self._failed):
            if b not in dead:
                del self._failed[b]
                changed = True
        if changed:
            self._persist()
        if not self._failed:
            return None
        anomaly = BrokerFailures(failed_brokers=dict(self._failed))
        self._report(anomaly)
        return anomaly
