"""Versioned maintenance-plan serde + Kafka-topic reader.

Reference parity: detector/MaintenancePlanSerde.java (JSON envelope with
``planType``/``version``/``crc``; deserialization verifies the type is
known, the version is supported, and the content CRC matches) and
MaintenanceEventTopicReader.java:350 (consume plans submitted by an ops
pipeline from a Kafka topic between poll intervals).

The wire format is a one-line JSON envelope::

    {"planType": "REMOVE_BROKER", "version": 1, "crc": 1234567890,
     "content": {"timeMs": ..., "brokers": [...], "topicsByRF": {...}}}

CRC = crc32 of the canonical (sorted-keys, compact) content JSON — same
role as MaintenancePlanSerde's content crc: a plan corrupted in transit or
hand-edited in place is rejected rather than executed.
"""

from __future__ import annotations

import json
import logging
import time
import zlib
from typing import Callable, Iterable

from .anomaly import MaintenanceEvent, MaintenanceEventType

LOG = logging.getLogger(__name__)

MAINTENANCE_TOPIC = "__CruiseControlMaintenanceEvent"

# Latest supported envelope version per plan type
# (MaintenancePlanSerde.verifyTypeAndVersion: each plan class carries a
# LATEST_SUPPORTED_VERSION; newer producers are rejected, older accepted).
LATEST_SUPPORTED_VERSION: dict[str, int] = {
    t.value: 1 for t in MaintenanceEventType
}


class PlanSerdeError(ValueError):
    """Unknown type, unsupported version, or CRC mismatch."""


def _canonical(content: dict) -> bytes:
    return json.dumps(content, sort_keys=True,
                      separators=(",", ":")).encode()


def serialize_plan(event: MaintenanceEvent, time_ms: int | None = None,
                   version: int = 1) -> bytes:
    content = {
        "timeMs": time_ms if time_ms is not None else int(time.time() * 1000),
        "brokers": sorted(int(b) for b in event.broker_ids),
        "topicsByRF": {str(rf): sorted(ts)
                       for rf, ts in event.topics_by_rf.items()},
    }
    return json.dumps({
        "planType": event.event_type.value,
        "version": version,
        "crc": zlib.crc32(_canonical(content)),
        "content": content,
    }).encode()


def deserialize_plan(payload: bytes) -> MaintenanceEvent:
    try:
        d = json.loads(payload.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise PlanSerdeError(f"undecodable maintenance plan: {e}") from e
    plan_type = d.get("planType")
    latest = LATEST_SUPPORTED_VERSION.get(plan_type)
    if latest is None:
        raise PlanSerdeError(f"unknown maintenance plan type {plan_type!r}")
    version = d.get("version")
    if not isinstance(version, int) or version < 1 or version > latest:
        raise PlanSerdeError(
            f"unsupported {plan_type} plan version {version!r} "
            f"(latest supported {latest})")
    content = d.get("content")
    if not isinstance(content, dict):
        raise PlanSerdeError("maintenance plan without content")
    crc = zlib.crc32(_canonical(content))
    if crc != d.get("crc"):
        raise PlanSerdeError(
            f"maintenance plan crc mismatch: stored {d.get('crc')!r}, "
            f"computed {crc}")
    return MaintenanceEvent(
        event_type=MaintenanceEventType(plan_type),
        broker_ids=list(content.get("brokers", [])),
        topics_by_rf={int(rf): list(ts)
                      for rf, ts in (content.get("topicsByRF") or {}).items()})


def publish_plan(transport, event: MaintenanceEvent,
                 time_ms: int | None = None) -> None:
    """Ops-pipeline producer half: serialize a plan and produce it to the
    maintenance topic through any metrics-shaped transport
    (produce + flush). The reference leaves production to external
    tooling; this is the equivalent one-liner for python pipelines."""
    transport.produce(serialize_plan(event, time_ms=time_ms))
    transport.flush()


class TopicMaintenanceEventReader:
    """MaintenanceEventReader over a maintenance-plan topic.

    ``transport`` needs one method — ``poll(start_ms, end_ms) ->
    Iterable[bytes]`` — the same shape as the metrics-topic transport
    (kafka/transport.py KafkaMetricsTransport), so the live binding and the
    in-memory fake both plug in. Undecodable/corrupt plans are dropped with
    a log line (MaintenanceEventTopicReader skips bad records).

    Poll windows are [last_end, now - settle_ms): the settle buffer keeps
    a plan whose record timestamp ties with the poll instant (or lags it
    under producer clock skew) readable by the NEXT poll instead of being
    skipped forever once last_end advances past it — the role of the
    reference's acceptable consumption lag."""

    def __init__(self, transport, now_ms: Callable[[], int] | None = None,
                 settle_ms: int = 1000):
        self._transport = transport
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._settle_ms = settle_ms
        self._last_poll_ms = 0

    def read_events(self) -> list[MaintenanceEvent]:
        end = max(self._last_poll_ms, self._now_ms() - self._settle_ms)
        payloads: Iterable[bytes] = self._transport.poll(
            self._last_poll_ms, end)
        self._last_poll_ms = end
        events: list[MaintenanceEvent] = []
        for payload in payloads:
            try:
                events.append(deserialize_plan(payload))
            except PlanSerdeError as e:
                LOG.warning("dropping bad maintenance plan: %s", e)
        return events
