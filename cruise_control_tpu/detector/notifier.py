"""Anomaly notifier SPI + self-healing escalation policy.

Reference parity: detector/notifier/AnomalyNotifier.java (SPI),
SelfHealingNotifier.java:59 (graded alert→auto-fix thresholds),
SlackSelfHealingNotifier / MSTeamsSelfHealingNotifier /
AlertaSelfHealingNotifier (webhook fan-outs), NoopNotifier.

Webhook posts go through a pluggable ``http_post`` callable so tests (and
the zero-egress build sandbox) can capture payloads instead of performing
network IO.
"""

from __future__ import annotations

import enum
import json
import logging
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable

from ..config.cruise_control_config import CruiseControlConfig
from .anomaly import Anomaly, AnomalyType

LOG = logging.getLogger(__name__)


class AnomalyNotificationAction(enum.Enum):
    FIX = "FIX"
    CHECK = "CHECK"       # re-check after a delay
    IGNORE = "IGNORE"


@dataclass(frozen=True)
class AnomalyNotificationResult:
    """AnomalyNotificationResult.java — action + optional re-check delay."""

    action: AnomalyNotificationAction
    delay_ms: int = 0

    @staticmethod
    def fix() -> "AnomalyNotificationResult":
        return AnomalyNotificationResult(AnomalyNotificationAction.FIX)

    @staticmethod
    def check(delay_ms: int) -> "AnomalyNotificationResult":
        return AnomalyNotificationResult(AnomalyNotificationAction.CHECK, delay_ms)

    @staticmethod
    def ignore() -> "AnomalyNotificationResult":
        return AnomalyNotificationResult(AnomalyNotificationAction.IGNORE)


class AnomalyNotifier:
    """SPI (AnomalyNotifier.java). One callback per anomaly type; the
    manager consults the result to fix / re-check / drop."""

    def on_anomaly(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        raise NotImplementedError

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        """Admin-endpoint toggle; returns the previous value."""
        return False


class NoopNotifier(AnomalyNotifier):
    """NoopNotifier.java — log and ignore."""

    def on_anomaly(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        LOG.info("anomaly ignored (noop notifier): %s", anomaly.reasons())
        return AnomalyNotificationResult.ignore()


class SelfHealingNotifier(AnomalyNotifier):
    """SelfHealingNotifier.java — per-type enable flags; broker failures
    escalate alert → auto-fix by failure age (broker.failure.alert.threshold.ms
    then self.healing.threshold); other types fix immediately when enabled."""

    def __init__(self, config: CruiseControlConfig | None = None,
                 now_ms: Callable[[], int] | None = None):
        cfg = config or CruiseControlConfig()
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        global_on = cfg.get_boolean("self.healing.enabled")
        self._enabled = {
            t: bool(global_on and cfg.get_boolean(
                Anomaly(anomaly_type=t).self_healing_config_key))
            for t in AnomalyType
        }
        self._alert_threshold_ms = cfg.get_long("broker.failure.alert.threshold.ms")
        self._fix_threshold_ms = cfg.get_long("broker.failure.self.healing.threshold.ms")
        self._alerted: set[int] = set()

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        old = self._enabled[anomaly_type]
        self._enabled[anomaly_type] = enabled
        return old

    # -- alert hook (webhook notifiers override) ---------------------------
    def alert(self, anomaly: Anomaly, auto_fix_triggered: bool) -> None:
        LOG.warning("anomaly alert (auto_fix=%s): %s", auto_fix_triggered,
                    anomaly.reasons())
        # Heal ledger: the escalation outcome lands on the anomaly's
        # correlation chain (the manager consults the notifier inside
        # the ambient heal scope; standalone notifiers record nothing).
        from ..utils.heal_ledger import current_heal
        current_heal().phase("alerted", autoFix=bool(auto_fix_triggered))

    def on_anomaly(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        if anomaly.anomaly_type is AnomalyType.BROKER_FAILURE:
            return self._on_broker_failure(anomaly)
        if not self._enabled[anomaly.anomaly_type]:
            self.alert(anomaly, auto_fix_triggered=False)
            return AnomalyNotificationResult.ignore()
        self.alert(anomaly, auto_fix_triggered=True)
        return AnomalyNotificationResult.fix()

    def _on_broker_failure(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        """Graded escalation (SelfHealingNotifier.java:59): before the alert
        threshold → re-check; between alert and fix thresholds → alert +
        re-check; past the fix threshold → fix (if enabled)."""
        failed = getattr(anomaly, "failed_brokers", {})
        # A broker that recovered leaves the alerted set so its NEXT failure
        # alerts again.
        self._alerted &= set(failed)
        if not failed:
            return AnomalyNotificationResult.ignore()
        earliest = min(failed.values())
        now = self._now_ms()
        alert_at = earliest + self._alert_threshold_ms
        fix_at = earliest + self._fix_threshold_ms
        if now < alert_at:
            return AnomalyNotificationResult.check(alert_at - now)
        if now < fix_at:
            new = set(failed) - self._alerted
            if new:
                self._alerted |= new
                self.alert(anomaly, auto_fix_triggered=False)
            return AnomalyNotificationResult.check(fix_at - now)
        self._alerted -= set(failed)
        if not self._enabled[AnomalyType.BROKER_FAILURE]:
            self.alert(anomaly, auto_fix_triggered=False)
            return AnomalyNotificationResult.ignore()
        self.alert(anomaly, auto_fix_triggered=True)
        return AnomalyNotificationResult.fix()


def _default_http_post(url: str, payload: dict, headers: dict | None = None) -> int:
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=10) as resp:  # pragma: no cover
        return resp.status


class WebhookSelfHealingNotifier(SelfHealingNotifier):
    """Base for the Slack/Teams/Alerta fan-outs: same escalation policy,
    alert() additionally posts a JSON payload to a webhook URL."""

    def __init__(self, config: CruiseControlConfig | None = None,
                 webhook_url: str = "",
                 http_post: Callable[..., int] | None = None, **kw):
        super().__init__(config, **kw)
        self._webhook_url = webhook_url
        self._http_post = http_post or _default_http_post

    def payload(self, anomaly: Anomaly, auto_fix_triggered: bool) -> dict:
        raise NotImplementedError

    def alert(self, anomaly: Anomaly, auto_fix_triggered: bool) -> None:
        super().alert(anomaly, auto_fix_triggered)
        if not self._webhook_url:
            return
        try:
            self._http_post(self._webhook_url,
                            self.payload(anomaly, auto_fix_triggered))
        except Exception:
            LOG.exception("webhook alert failed")


class SlackSelfHealingNotifier(WebhookSelfHealingNotifier):
    """SlackSelfHealingNotifier.java:85 — Slack incoming-webhook message."""

    def payload(self, anomaly: Anomaly, auto_fix_triggered: bool) -> dict:
        return {"text": (f":warning: cruise-control-tpu anomaly "
                         f"{anomaly.anomaly_type.name} "
                         f"(auto-fix: {auto_fix_triggered})\n"
                         + "\n".join(anomaly.reasons()))}


class MSTeamsSelfHealingNotifier(WebhookSelfHealingNotifier):
    """MSTeamsSelfHealingNotifier.java:64 — MessageCard payload."""

    def payload(self, anomaly: Anomaly, auto_fix_triggered: bool) -> dict:
        return {"@type": "MessageCard", "@context": "https://schema.org/extensions",
                "title": f"Anomaly: {anomaly.anomaly_type.name}",
                "text": "; ".join(anomaly.reasons()),
                "themeColor": "FF0000" if not auto_fix_triggered else "FFA500"}


class AlertaSelfHealingNotifier(WebhookSelfHealingNotifier):
    """AlertaSelfHealingNotifier.java:258 — Alerta alert API payload."""

    def __init__(self, *a, environment: str = "Production",
                 api_key: str = "", **kw):
        super().__init__(*a, **kw)
        self._environment = environment
        self._api_key = api_key

    def payload(self, anomaly: Anomaly, auto_fix_triggered: bool) -> dict:
        return {"environment": self._environment,
                "event": anomaly.anomaly_type.name,
                "resource": anomaly.anomaly_id,
                "severity": "critical" if anomaly.anomaly_type in
                (AnomalyType.BROKER_FAILURE, AnomalyType.DISK_FAILURE)
                else "warning",
                "service": ["cruise-control-tpu"],
                "text": "; ".join(anomaly.reasons()),
                "attributes": {"autoFix": auto_fix_triggered}}
