"""SLO burn-rate detector: multi-window budget burn → SLO_BURN anomaly.

The production half of the shared SLO definition (``utils/slo.py``): a
detector tick evaluates every registered objective's multi-window burn
rule and raises a first-class ``SloBurn`` anomaly when an objective is
burning — fast pair (5m/1h) both over the fast threshold, or slow pair
(30m/6h) both over the slow threshold. The anomaly signature is the
OBJECTIVE (detector/manager.py), so a standing burn re-reported each
interval aliases onto ONE heal chain; when the budget recovers the
detector resolves that chain's terminal ``cleared``
(via=budget_recovered) itself.

The tick also feeds the time-to-heal objective: cleared heal-ledger
chains publish their durations (``heal_durations_s``), and the multiset
diff against what this detector already fed becomes
``registry.observe_heal`` events — healing speed is itself an SLO.

Lifecycle (mirrors ``PredictiveViolationDetector``):

- burning & no open chain → report ``SloBurn`` (one per objective),
  stamp the chain's ``burning`` phase with the live rates;
- burning & open chain → nothing (the signature alias absorbs it);
- recovered & open chain → resolve ``cleared`` via=budget_recovered.

Off means off: with ``slo.enabled=false`` a tick is one attribute read
(the bench ``slo_noop_overhead`` probe covers the registry hooks), and
open chains raised before the flip still resolve so no chain leaks.
"""

from __future__ import annotations

import collections
import logging
from typing import Callable

from .anomaly import SloBurn

LOG = logging.getLogger(__name__)


class SloBurnDetector:
    #: Heal-ledger all-clear seam (detector/manager.py): burns resolve
    #: through the detector's OWN budget-recovered bookkeeping below —
    #: the generic all-clear would race it to the terminal with a
    #: different via, so it stays out of the way.
    CLEARS = ()

    def __init__(self, registry, report: Callable, ledger=None):
        self._registry = registry
        self._report = report
        self._ledger = ledger
        # Open burns: objective name -> anomaly_id of its heal chain.
        self._open: dict[str, str] = {}
        # Multiset of heal durations already fed to the time-to-heal
        # objective (heal_durations_s returns the full sorted history;
        # the Counter diff isolates chains cleared since the last tick).
        self._heals_seen: collections.Counter = collections.Counter()
        self.burns_raised = 0
        self.burns_cleared = 0

    # -- state (merged into the GET /slo body) -----------------------------
    def state(self) -> dict:
        return {
            "openBurns": sorted(self._open),
            "burnsRaised": self.burns_raised,
            "burnsCleared": self.burns_cleared,
        }

    # -- the pass ----------------------------------------------------------
    def run_once(self) -> SloBurn | None:
        if not self._registry.enabled:
            # Off means off for NEW burns — but chains opened before the
            # flip must still reach a terminal or they leak open
            # forever. Guarded on _open so the disabled tick stays one
            # attribute read.
            if self._open:
                for objective in list(self._open):
                    self._clear(objective, via="slo_disabled")
            return None
        self._feed_heals()
        raised: SloBurn | None = None
        for obj in self._registry.objectives():
            burning = self._registry.burning(obj.name)
            if burning and obj.name not in self._open:
                rates = self._registry.burn_rates(obj.name)
                w = self._registry.windows_s
                anomaly = SloBurn(
                    objective=obj.name,
                    fast_burn=round(rates.get(w[0], 0.0), 3),
                    slow_burn=round(rates.get(w[3], 0.0), 3),
                    budget_remaining=round(
                        self._registry.budget_remaining(obj.name), 4))
                self._report(anomaly)
                self._open[obj.name] = anomaly.anomaly_id
                self.burns_raised += 1
                from ..utils.sensors import SENSORS
                SENSORS.count("slo_burn_anomalies")
                if self._ledger is not None:
                    # First phase on the chain: the live rates that
                    # crossed the rule (re-detections alias onto this
                    # chain via the objective signature, so the stamp
                    # lands once per incident).
                    self._ledger.handle_for(anomaly.anomaly_id).phase(
                        "burning", objective=obj.name,
                        fastBurn=anomaly.fast_burn,
                        slowBurn=anomaly.slow_burn,
                        budgetRemaining=anomaly.budget_remaining)
                raised = raised or anomaly
            elif not burning and obj.name in self._open:
                self._clear(obj.name, via="budget_recovered")
        return raised

    def _clear(self, objective: str, via: str) -> None:
        anomaly_id = self._open.pop(objective)
        self.burns_cleared += 1
        from ..utils.sensors import SENSORS
        SENSORS.count("slo_burn_cleared")
        if self._ledger is not None:
            self._ledger.handle_for(anomaly_id).resolve(
                "cleared", via=via, objective=objective)

    def _feed_heals(self) -> None:
        """Cleared heal chains → time-to-heal objective events. The
        ledger serves the full sorted duration history; the multiset
        diff against what we already fed isolates the fresh clears."""
        if self._ledger is None:
            return
        durations = collections.Counter(
            round(d, 6) for d in self._ledger.heal_durations_s())
        fresh = durations - self._heals_seen
        self._heals_seen = durations
        for duration_s, n in sorted(fresh.items()):
            for _ in range(n):
                self._registry.observe_heal(duration_s)
