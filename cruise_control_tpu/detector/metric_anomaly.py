"""Metric-anomaly finders: percentile outliers + the slow-broker policy.

Reference parity: cruise-control-core
detector/metricanomaly/PercentileMetricAnomalyFinder.java (a broker's
latest value beyond the upper/lower percentile of its own history) and
detector/SlowBrokerFinder.java:43-109 (log-flush-time p999 judged by an
absolute floor, the broker's own history, and its peers; demote on first
offence, remove when persistently slow with enough traffic).

The percentile math is vectorized with numpy over the broker aggregator's
[E, M, W] window matrix — one pass scores every broker × metric at once
(the reference loops brokers; here the windowed history IS the tensor).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from ..config.cruise_control_config import CruiseControlConfig
from ..metricdef.kafka_metric_def import BrokerMetric, KafkaMetricDef
from ..monitor.aggregator.aggregator import (
    AggregationOptions, Granularity, MetricSampleAggregator,
)
from .anomaly import MetricAnomaly

LOG = logging.getLogger(__name__)


def _broker_history(aggregator: MetricSampleAggregator,
                    ) -> tuple[list[int], np.ndarray] | None:
    """(broker_ids, values[E, M, W]) from the broker aggregator, oldest
    window first; None when no valid windows exist yet."""
    opts = AggregationOptions(min_valid_entity_ratio=0.0, min_valid_windows=1,
                              granularity=Granularity.ENTITY,
                              include_invalid_entities=True)
    try:
        agg = aggregator.aggregate(opts)
    except Exception:
        return None
    if agg.values.shape[2] < 1:
        return None
    return [e.broker_id for e in agg.entities], agg.values


class PercentileMetricAnomalyFinder:
    """A broker's CURRENT (latest-window) value for an interested metric is
    anomalous when it exceeds the upper percentile or undercuts the lower
    percentile of that broker's own history
    (PercentileMetricAnomalyFinder.java)."""

    def __init__(self, config: CruiseControlConfig | None = None,
                 interested_metrics: Sequence[BrokerMetric] | None = None):
        cfg = config or CruiseControlConfig()
        self._upper_pct = cfg.get_double("metric.anomaly.percentile.upper.threshold")
        self._lower_pct = cfg.get_double("metric.anomaly.percentile.lower.threshold")
        self._metrics = list(interested_metrics or [
            BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_999TH,
            BrokerMetric.BROKER_PRODUCE_TOTAL_TIME_MS_999TH,
        ])
        bdef = KafkaMetricDef.broker_metric_def()
        self._metric_ids = np.array([bdef.metric_info(m.name).id
                                     for m in self._metrics])

    def find_anomalies(self, aggregator: MetricSampleAggregator,
                       ) -> list[MetricAnomaly]:
        hist = _broker_history(aggregator)
        if hist is None:
            return []
        broker_ids, values = hist
        if values.shape[2] < 3:       # need history beyond the current window
            return []
        sel = values[:, self._metric_ids, :]          # [E, K, W]
        history, current = sel[:, :, :-1], sel[:, :, -1]
        upper = np.percentile(history, self._upper_pct, axis=2)
        lower = np.percentile(history, self._lower_pct, axis=2)
        anomalies: list[MetricAnomaly] = []
        hot = (current > upper) & (upper > 0)
        cold = (current < lower) & (lower > 0)
        for e, k in zip(*np.nonzero(hot | cold)):
            kind = "above" if hot[e, k] else "below"
            bound = upper[e, k] if hot[e, k] else lower[e, k]
            anomalies.append(MetricAnomaly(
                broker_ids=[broker_ids[e]], metric_name=self._metrics[k].name,
                description=(f"current {current[e, k]:.2f} {kind} "
                             f"{self._upper_pct if hot[e, k] else self._lower_pct}"
                             f"th percentile {bound:.2f}")))
        return anomalies


@dataclass
class SlowBrokerFinder:
    """SlowBrokerFinder.java:43-109. A broker is *slow* this round when its
    latest log-flush p999 (a) exceeds an absolute floor, (b) sticks out vs
    its own history percentile, and (c) sticks out vs the peer percentile.
    A slow-score counter per broker escalates: score ≥ demote threshold →
    demote; score ≥ removal threshold with real traffic → remove."""

    config: CruiseControlConfig = field(default_factory=CruiseControlConfig)
    abs_flush_time_floor_ms: float = 100.0
    history_pct: float = 90.0
    peer_pct: float = 50.0
    peer_margin: float = 3.0          # slow if > margin × peer percentile
    # None → read slow.broker.{demotion,decommission}.score from config.
    demote_score: int | None = None
    removal_score: int | None = None

    def __post_init__(self):
        if self.demote_score is None:
            self.demote_score = self.config.get_int("slow.broker.demotion.score")
        if self.removal_score is None:
            self.removal_score = self.config.get_int(
                "slow.broker.decommission.score")
        bdef = KafkaMetricDef.broker_metric_def()
        self._flush_id = bdef.metric_info(
            BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_999TH.name).id
        from ..metricdef.kafka_metric_def import CommonMetric
        self._bytes_in_id = bdef.metric_info(CommonMetric.LEADER_BYTES_IN.name).id
        self._min_bytes_in = self.config.get_double(
            "slow.broker.bytes.in.rate.detection.threshold")
        self._scores: dict[int, int] = {}

    def find_anomalies(self, aggregator: MetricSampleAggregator,
                       ) -> list[MetricAnomaly]:
        hist = _broker_history(aggregator)
        if hist is None:
            return []
        broker_ids, values = hist
        flush = values[:, self._flush_id, :]          # [E, W]
        bytes_in = values[:, self._bytes_in_id, -1]   # [E]
        current = flush[:, -1]

        slow = current > self.abs_flush_time_floor_ms
        if flush.shape[1] >= 3:
            own = np.percentile(flush[:, :-1], self.history_pct, axis=1)
            slow &= current > own
        if len(broker_ids) >= 2:
            peer = np.percentile(current, self.peer_pct)
            slow &= current > self.peer_margin * max(peer, 1e-9)

        # Score bookkeeping: increment slow brokers, decay the rest (:86).
        for i, b in enumerate(broker_ids):
            if slow[i]:
                self._scores[b] = self._scores.get(b, 0) + 1
            elif b in self._scores:
                self._scores[b] -= 1
                if self._scores[b] <= 0:
                    del self._scores[b]

        to_remove = [b for i, b in enumerate(broker_ids)
                     if self._scores.get(b, 0) >= self.removal_score
                     and bytes_in[i] >= self._min_bytes_in]
        to_demote = [b for b in broker_ids
                     if self.demote_score <= self._scores.get(b, 0)
                     < self.removal_score and b not in to_remove]
        anomalies = []
        if to_remove:
            anomalies.append(MetricAnomaly(
                broker_ids=to_remove, fix_by_removal=True,
                metric_name=BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_999TH.name,
                description="persistently slow; removal"))
        if to_demote:
            anomalies.append(MetricAnomaly(
                broker_ids=to_demote, fix_by_removal=False,
                metric_name=BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_999TH.name,
                description="slow; demotion"))
        return anomalies


class MetricAnomalyDetector:
    """detector/MetricAnomalyDetector.java — runs the configured finders
    over the broker aggregator and reports their anomalies."""

    def __init__(self, broker_aggregator: MetricSampleAggregator,
                 report: Callable[[MetricAnomaly], None],
                 finders: Sequence | None = None,
                 config: CruiseControlConfig | None = None):
        cfg = config or CruiseControlConfig()
        self._aggregator = broker_aggregator
        self._report = report
        self._finders = list(finders) if finders is not None else [
            PercentileMetricAnomalyFinder(cfg), SlowBrokerFinder(cfg)]

    def run_once(self) -> list[MetricAnomaly]:
        out: list[MetricAnomaly] = []
        for finder in self._finders:
            for anomaly in finder.find_anomalies(self._aggregator):
                self._report(anomaly)
                out.append(anomaly)
        return out
