"""Anomaly detector manager: scheduling + the single-consumer fix queue.

Reference parity: detector/AnomalyDetectorManager.java:52-133 (one
scheduled task per anomaly type feeding a PriorityBlockingQueue, one
AnomalyHandlerTask draining it), :343-451 (take → notifier consult →
FIX/CHECK/IGNORE), :513-549 (completeness check then ``anomaly.fix()``),
:190 (self-healing gauges), and AnomalyState bookkeeping
(detector/AnomalyState.java).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..config.cruise_control_config import CruiseControlConfig
from .anomaly import Anomaly, AnomalyType
from .notifier import (
    AnomalyNotificationAction, AnomalyNotifier, SelfHealingNotifier,
)

LOG = logging.getLogger(__name__)


class AnomalyStatus:
    DETECTED = "DETECTED"
    IGNORED = "IGNORED"
    CHECK_WITH_DELAY = "CHECK_WITH_DELAY"
    FIX_STARTED = "FIX_STARTED"
    FIX_FAILED_TO_START = "FIX_FAILED_TO_START"


@dataclass
class AnomalyRecord:
    anomaly: Anomaly
    status: str = AnomalyStatus.DETECTED
    status_time_ms: int = field(default_factory=lambda: int(time.time() * 1000))


class AnomalyDetectorManager:
    """Owns the detector schedule and the fix pipeline. Detectors are any
    objects with ``run_once()``; they report anomalies via the ``report``
    callback handed to them at construction (the queue's producer side)."""

    def __init__(self, config: CruiseControlConfig | None = None,
                 notifier: AnomalyNotifier | None = None,
                 facade: Any = None,
                 clock: "Callable[[], float] | None" = None,
                 ledger: Any = None):
        self._config = config or CruiseControlConfig()
        self._notifier = notifier or SelfHealingNotifier(self._config)
        self._facade = facade
        # Heal ledger (round 16): every reported anomaly opens a
        # correlation chain at detection; the manager records the
        # notifier verdict and the fix dispatch onto it, and enters the
        # ambient heal scope around both so the facade/scheduler/
        # executor phases attribute with zero plumbing. The facade
        # passes ITS ledger (per-facade isolation + shared clock); a
        # bare manager gets its own on the same injected clock.
        from ..utils.heal_ledger import HealLedger
        self.heal_ledger = ledger if ledger is not None else HealLedger(
            clock=clock if clock is not None else time.time)
        # Injectable clock (round 11): every time comparison in the fix
        # pipeline — recheck due times, record timestamps, the detector
        # breaker's recovery window, and run_due() tick scheduling — reads
        # THIS clock, so the digital-twin simulator can drive anomaly
        # detection on simulated time. Default is wall clock: production
        # behavior is unchanged (the scheduler threads still pace
        # themselves on Event.wait).
        self._clock = clock or time.time
        # Detector isolation (round 9): a detector that keeps crashing
        # trips its own breaker and is SKIPPED until the recovery window
        # elapses — one broken detector must neither kill its scheduler
        # thread (the try/except below already prevented that) nor burn
        # its interval stack-tracing forever.
        from ..utils.resilience import CircuitBreaker
        self._detector_breaker = CircuitBreaker.from_config(
            self._config, name="detector",
            clock=clock if clock is not None else time.monotonic)
        self._detectors: list[tuple[Any, float]] = []   # (detector, interval_s)
        self._queue: list[tuple[tuple[int, int], int, Anomaly]] = []
        self._queue_seq = 0
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._history: list[AnomalyRecord] = []
        self._records: dict[str, AnomalyRecord] = {}
        # Per-type self-healing starts (AnomalyDetectorManager.java:190
        # gauges): the unlabeled plain int became a per-type breakdown +
        # the self_healing_started_total{type=} sensor; the state()
        # JSON's numSelfHealingStarted stays the sum.
        self._self_healing_started_by_type: dict[str, int] = {}
        self._num_fix_failures = 0
        self._recheck: list[tuple[float, Anomaly]] = []  # (due time s, anomaly)
        # run_due() schedule: detector index → next due time on the
        # injected clock (the simulator's synchronous replacement for the
        # per-detector scheduler threads).
        self._next_due: dict[int, float] = {}
        # Optional fix-dispatch hook: callable(fn) -> fn's result. A fleet
        # registry points this at the FleetScheduler (SELF_HEALING
        # priority) so one device serves every cluster's fixes in
        # priority order; None = run inline on the handler thread.
        self.fix_runner = None

    # -- wiring ------------------------------------------------------------
    def add_detector(self, detector: Any, interval_ms: int) -> None:
        self._detectors.append((detector, interval_ms / 1000.0))

    @staticmethod
    def _anomaly_signature(anomaly: Anomaly) -> tuple:
        """Incident identity for heal-chain dedup: a detector
        re-reporting the SAME ongoing condition each interval is one
        heal, not many. Types without a natural signature never dedup
        (each report is its own chain)."""
        failed = getattr(anomaly, "failed_brokers", None)
        if failed:
            return tuple(sorted(failed))
        disks = getattr(anomaly, "failed_disks", None)
        if disks:
            return tuple(sorted((b, tuple(sorted(d)))
                                for b, d in disks.items()))
        predicted = getattr(anomaly, "predicted_goals", None)
        if predicted:
            # A standing prediction re-reported each interval is ONE
            # incident (type differs from GOAL_VIOLATION, so a predicted
            # and a real chain over the same goals never alias).
            return tuple(sorted(predicted))
        fixable = getattr(anomaly, "fixable_goals", None)
        unfixable = getattr(anomaly, "unfixable_goals", None)
        if fixable is not None or unfixable is not None:
            return tuple(sorted(fixable or ())) \
                + tuple(sorted(unfixable or ()))
        objective = getattr(anomaly, "objective", None)
        if objective:
            # A standing SLO burn re-reported while still burning is ONE
            # incident per objective (detector/slo_burn.py).
            return (objective,)
        return (anomaly.anomaly_id,)

    def report(self, anomaly: Anomaly) -> None:
        """Producer side (what detectors call). Thread-safe."""
        # Per-type anomaly rate (AnomalyDetectorManager.java:190 sensors).
        from ..utils.sensors import SENSORS
        SENSORS.count("anomaly_detector_anomalies", labels={
            "type": anomaly.anomaly_type.name})
        # Heal ledger: the correlation chain opens HERE, at detection —
        # phase transitions downstream attach to this chain's id.
        self.heal_ledger.open(anomaly.anomaly_type.name,
                              anomaly.anomaly_id,
                              self._anomaly_signature(anomaly))
        rec = AnomalyRecord(anomaly,
                            status_time_ms=int(self._clock() * 1000))
        with self._cv:
            self._records[anomaly.anomaly_id] = rec
            self._history.append(rec)
            for old in self._history[:-200]:
                self._records.pop(old.anomaly.anomaly_id, None)
            del self._history[:-200]
            heapq.heappush(self._queue, (
                (anomaly.anomaly_type.priority, anomaly.detection_time_ms),
                self._queue_seq, anomaly))
            self._queue_seq += 1
            self._cv.notify_all()

    # -- lifecycle ---------------------------------------------------------
    def start_detection(self) -> None:
        """Spawn one scheduler thread per detector + the handler thread
        (AnomalyDetectorManager.startDetection)."""
        self._stop.clear()
        for det, interval_s in self._detectors:
            t = threading.Thread(target=self._detector_loop,
                                 args=(det, interval_s),
                                 name=f"anomaly-detector-{type(det).__name__}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        handler = threading.Thread(target=self._handler_loop,
                                   name="anomaly-handler", daemon=True)
        handler.start()
        self._threads.append(handler)

    def shutdown(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def _detector_loop(self, detector: Any, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.run_detector_once(detector)

    def run_detector_once(self, detector: Any) -> bool:
        """One isolated detector tick (the scheduler-loop body; public so
        tests and embedders drive it synchronously): exceptions are
        contained and counted, and a detector past its breaker's failure
        threshold is SKIPPED until the recovery window elapses. Returns
        True when the detector actually ran and succeeded."""
        name = type(detector).__name__
        breaker = self._detector_breaker
        if breaker is not None and not breaker.allow(name):
            from ..utils.sensors import SENSORS
            SENSORS.count("detector_runs_skipped", labels={"detector": name})
            return False
        try:
            detector.run_once()
        except Exception:
            LOG.exception("detector %s failed", name)
            from ..utils.sensors import SENSORS
            SENSORS.count("detector_failures", labels={"detector": name})
            if breaker is not None:
                breaker.record_failure(name)
            return False
        if breaker is not None:
            breaker.record_success(name)
        # Heal-ledger all-clear seam: a detector that just verified its
        # condition GONE is the violation re-check — open chains of the
        # types it owns resolve as cleared. Detectors opt in by exposing
        # ``CLEARS`` (anomaly type names) + ``all_clear()``.
        clears = getattr(detector, "CLEARS", ())
        probe = getattr(detector, "all_clear", None)
        if clears and probe is not None:
            try:
                if probe():
                    self.heal_ledger.clear_types(clears)
            except Exception:  # noqa: BLE001 — observation must never
                # affect the detection loop
                LOG.debug("heal-ledger all-clear probe failed for %s",
                          name, exc_info=True)
        return True

    # -- simulated-time driving (digital-twin simulator, round 11) ---------
    def run_due(self, now_s: float | None = None) -> int:
        """Run every detector whose interval has elapsed on the injected
        clock — the synchronous replacement for the per-detector scheduler
        threads (testing/simulator.py drives this once per simulated
        tick). First sight of a detector schedules it one interval out,
        matching ``_detector_loop``'s wait-then-run pacing. Returns the
        number of detectors run this call."""
        now = self._clock() if now_s is None else now_s
        ran = 0
        for i, (det, interval_s) in enumerate(self._detectors):
            due = self._next_due.get(i)
            if due is None:
                self._next_due[i] = now + interval_s
                continue
            if now >= due:
                self.run_detector_once(det)
                self._next_due[i] = now + interval_s
                ran += 1
        return ran

    def drain_anomalies(self, max_anomalies: int = 1000) -> int:
        """Synchronously drain due rechecks + the fix queue on the
        injected clock (the handler thread's job, callable without any
        thread for wall-clock-free simulation). Returns the number of
        anomalies handled."""
        handled = 0
        while handled < max_anomalies:
            with self._cv:
                self._promote_due_rechecks(self._clock())
                anomaly = heapq.heappop(self._queue)[2] if self._queue \
                    else None
            if anomaly is None:
                return handled
            try:
                self.handle_anomaly(anomaly)
            except Exception:  # noqa: BLE001 — same contract as the
                # handler loop: one broken anomaly must not stop the drain
                LOG.exception("anomaly handler failed for %s",
                              getattr(anomaly, "anomaly_id", anomaly))
            handled += 1
        return handled

    # -- the handler (AnomalyHandlerTask, :343) ----------------------------
    def _promote_due_rechecks(self, now: float) -> None:
        """Move due CHECK_WITH_DELAY anomalies back onto the queue,
        dropping any whose condition cleared meanwhile (e.g. the failed
        broker recovered) instead of fixing a stale snapshot. Caller must
        hold ``_cv``."""
        while self._recheck and self._recheck[0][0] <= now:
            _due, anomaly = heapq.heappop(self._recheck)
            if self._facade is not None and \
                    not anomaly.still_valid(self._facade):
                rec = self._records.get(anomaly.anomaly_id)
                if rec is not None:
                    rec.status = AnomalyStatus.IGNORED
                # The condition cleared on its own while parked: the
                # documented self_cleared terminal.
                self.heal_ledger.handle_for(anomaly.anomaly_id).resolve(
                    "self_cleared")
                continue
            self.heal_ledger.handle_for(anomaly.anomaly_id).phase(
                "recheck_promoted")
            heapq.heappush(self._queue, (
                (anomaly.anomaly_type.priority, anomaly.detection_time_ms),
                self._queue_seq, anomaly))
            self._queue_seq += 1

    def _take(self, timeout_s: float) -> Anomaly | None:
        deadline = self._clock() + timeout_s
        with self._cv:
            while True:
                now = self._clock()
                self._promote_due_rechecks(now)
                if self._queue:
                    return heapq.heappop(self._queue)[2]
                if self._stop.is_set() or now >= deadline:
                    return None
                wait = deadline - now
                if self._recheck:
                    wait = min(wait, self._recheck[0][0] - now)
                self._cv.wait(timeout=max(wait, 0.01))

    def _handler_loop(self) -> None:
        while not self._stop.is_set():
            anomaly = self._take(timeout_s=0.5)
            if anomaly is None:
                continue
            try:
                self.handle_anomaly(anomaly)
            except Exception:  # noqa: BLE001 — the single fix-queue
                # consumer must survive anything one anomaly throws
                # (handle_anomaly guards the notifier and the fix, but
                # not e.g. a broken anomaly's own accessors).
                LOG.exception("anomaly handler failed for %s",
                              getattr(anomaly, "anomaly_id", anomaly))

    def handle_anomaly(self, anomaly: Anomaly) -> str:
        """One notifier-consult + fix cycle; returns the AnomalyStatus.
        Public so tests and embedded deployments can drive it synchronously."""
        from ..utils.heal_ledger import heal_scope
        rec = self._records.get(anomaly.anomaly_id) or AnomalyRecord(anomaly)
        heal = self.heal_ledger.handle_for(anomaly.anomaly_id)
        try:
            # The notifier consult runs inside the heal scope so its
            # escalation outcomes (alert webhooks) attribute themselves.
            with heal_scope(heal):
                result = self._notifier.on_anomaly(anomaly)
        except Exception:
            LOG.exception("notifier failed; ignoring anomaly")
            rec.status = AnomalyStatus.IGNORED
            heal.resolve("ignored", reason="notifier failed")
            return rec.status
        if result.action is AnomalyNotificationAction.IGNORE:
            rec.status = AnomalyStatus.IGNORED
            heal.resolve("ignored", verdict="IGNORE")
        elif result.action is AnomalyNotificationAction.CHECK:
            rec.status = AnomalyStatus.CHECK_WITH_DELAY
            heal.phase("verdict", action="CHECK", delayMs=result.delay_ms)
            with self._cv:
                heapq.heappush(
                    self._recheck,
                    (self._clock() + result.delay_ms / 1000.0, anomaly))
                self._cv.notify_all()
        else:
            heal.phase("verdict", action="FIX")
            rec.status = self._fix(anomaly, heal=heal)
        rec.status_time_ms = int(self._clock() * 1000)
        return rec.status

    def _fix(self, anomaly: Anomaly, heal: Any = None) -> str:
        """Completeness gate + fix dispatch (:513-549). ``heal`` is the
        chain handle the caller already resolved (handle_anomaly passes
        its own — one lookup, one handle, so the verdict and fix phases
        can never land on different chains across a ring eviction)."""
        from ..utils.heal_ledger import heal_scope
        if heal is None:
            heal = self.heal_ledger.handle_for(anomaly.anomaly_id)
        if self._facade is None:
            heal.resolve("fix_failed_to_start", reason="no facade")
            return AnomalyStatus.FIX_FAILED_TO_START
        ready = getattr(self._facade, "ready_for_self_healing", lambda: True)
        if not ready():
            LOG.info("skipping fix: load model not ready for self-healing")
            heal.resolve("fix_failed_to_start", reason="model not ready")
            return AnomalyStatus.FIX_FAILED_TO_START
        try:
            run = self.fix_runner or (lambda fn: fn())
            # fix_started lands BEFORE the dispatch: time-to-start-fix
            # (AnomalyDetectorState parity) measures detection→dispatch,
            # not detection→completion.
            heal.phase("fix_started")
            with heal_scope(heal):
                started = run(lambda: anomaly.fix(self._facade))
        except Exception as e:
            from ..utils.resilience import BreakerOpenError
            if isinstance(e, BreakerOpenError):
                # The fleet scheduler (or model breaker) failed the fix
                # fast — a documented terminal distinct from a fix that
                # crashed: the heal was never attempted.
                LOG.warning("anomaly fix skipped: breaker open (%s)", e)
                self._num_fix_failures += 1
                heal.resolve("breaker_skipped", reason=str(e),
                             own_fix_started=True)
                return AnomalyStatus.FIX_FAILED_TO_START
            LOG.exception("anomaly fix failed to start")
            self._num_fix_failures += 1
            heal.resolve("fix_failed_to_start",
                         reason=type(e).__name__, own_fix_started=True)
            return AnomalyStatus.FIX_FAILED_TO_START
        if started:
            a_type = anomaly.anomaly_type.name
            with self._cv:
                self._self_healing_started_by_type[a_type] = \
                    self._self_healing_started_by_type.get(a_type, 0) + 1
            from ..utils.sensors import SENSORS
            SENSORS.count("self_healing_started", labels={"type": a_type})
            return AnomalyStatus.FIX_STARTED
        heal.resolve("fix_failed_to_start", reason="fix declined",
                     own_fix_started=True)
        return AnomalyStatus.FIX_FAILED_TO_START

    # -- state (anomaly_detector_state endpoint) ---------------------------
    def set_self_healing_for(self, anomaly_type: AnomalyType,
                             enabled: bool) -> bool:
        return self._notifier.set_self_healing_for(anomaly_type, enabled)

    def state(self) -> dict:
        enabled = self._notifier.self_healing_enabled()
        with self._cv:
            started_by_type = dict(self._self_healing_started_by_type)
        return {
            "selfHealingEnabled": [t.name for t, on in enabled.items() if on],
            "selfHealingDisabled": [t.name for t, on in enabled.items() if not on],
            "recentAnomalies": [
                {"anomalyId": r.anomaly.anomaly_id,
                 "type": r.anomaly.anomaly_type.name,
                 "status": r.status,
                 "statusTimeMs": r.status_time_ms,
                 "reasons": r.anomaly.reasons()}
                for r in self._history[-20:]],
            # Heal-ledger parity fields (AnomalyDetectorState.java:
            # anomaly state history + mean-time-to-start-fix): the last
            # N correlated chains and the detected→fix_started mean.
            "recentHeals": self.heal_ledger.recent_summaries(10),
            "meanTimeToStartFixMs":
                self.heal_ledger.mean_time_to_start_fix_ms(),
            "metrics": {
                # The sum keeps the pre-round-16 JSON field; the per-type
                # breakdown is new (self_healing_started_total{type=} is
                # the sensor twin).
                "numSelfHealingStarted": sum(started_by_type.values()),
                "selfHealingStartedByType": started_by_type,
                "numFixFailures": self._num_fix_failures,
                "queueSize": len(self._queue)},
        }
