"""Topic anomaly finders.

Reference parity: detector/TopicAnomalyDetector.java with
TopicReplicationFactorAnomalyFinder.java:284 (topics matching a pattern
whose RF differs from the desired value, min-ISR-aware) and
PartitionSizeAnomalyFinder.java:127 (partitions larger than a threshold).
"""

from __future__ import annotations

import logging
import re
from typing import Callable

from ..config.cruise_control_config import CruiseControlConfig
from ..executor.admin import AdminBackend
from .anomaly import TopicAnomaly

LOG = logging.getLogger(__name__)


class TopicReplicationFactorAnomalyFinder:
    """Topics whose RF ≠ desired RF. ``topic_pattern`` scopes enforcement
    (self.healing.target.topic.replication.factor analogue)."""

    def __init__(self, desired_rf: int = 3, topic_pattern: str = ".*",
                 ignore_internal: bool = True):
        self._desired_rf = desired_rf
        self._pattern = re.compile(topic_pattern)
        self._ignore_internal = ignore_internal

    def find(self, metadata: AdminBackend) -> TopicAnomaly | None:
        bad: set[str] = set()
        for (topic, _p), st in metadata.describe_partitions().items():
            if self._ignore_internal and topic.startswith("__"):
                continue
            if not self._pattern.fullmatch(topic):
                continue
            if len(st.replicas) != self._desired_rf:
                bad.add(topic)
        if not bad:
            return None
        return TopicAnomaly(topics_by_desired_rf={self._desired_rf: sorted(bad)})


class PartitionSizeAnomalyFinder:
    """Partitions whose disk size exceeds a threshold
    (PartitionSizeAnomalyFinder.java:127). Reported for alerting; there is
    no automated fix (matches the reference, which only notifies)."""

    def __init__(self, max_partition_size_bytes: float = 1 << 40):
        self._threshold = max_partition_size_bytes

    def find_oversized(self, partition_sizes: dict[tuple[str, int], float],
                       ) -> dict[tuple[str, int], float]:
        return {tp: sz for tp, sz in partition_sizes.items()
                if sz > self._threshold}


class TopicAnomalyDetector:
    def __init__(self, metadata: AdminBackend,
                 report: Callable[[TopicAnomaly], None],
                 config: CruiseControlConfig | None = None,
                 desired_rf: int | None = None,
                 topic_pattern: str = ".*"):
        del config  # reserved for finder-class plugin configuration
        self._metadata = metadata
        self._report = report
        self._finder = (TopicReplicationFactorAnomalyFinder(desired_rf, topic_pattern)
                        if desired_rf is not None else None)

    def run_once(self) -> TopicAnomaly | None:
        if self._finder is None:
            return None
        anomaly = self._finder.find(self._metadata)
        if anomaly is not None:
            self._report(anomaly)
        return anomaly
