"""Anomaly detection + self-healing (reference: detector/ + notifier/).

Host-side scheduling around device-side detection math: the goal-violation
pass IS the batched TPU optimizer; slow-broker/percentile finders vectorize
over the broker aggregator's window tensor.
"""

from .anomaly import (
    Anomaly, AnomalyType, BrokerFailures, DiskFailures, GoalViolations,
    MaintenanceEvent, MaintenanceEventType, MetricAnomaly,
    PredictedGoalViolations, TopicAnomaly,
)
from .broker_failure import BrokerFailureDetector
from .disk_failure import DiskFailureDetector
from .goal_violation import GoalViolationDetector
from .maintenance import (
    FileMaintenanceEventReader, IdempotenceCache,
    InMemoryMaintenanceEventReader, MaintenanceEventDetector,
)
from .manager import AnomalyDetectorManager, AnomalyStatus
from .metric_anomaly import (
    MetricAnomalyDetector, PercentileMetricAnomalyFinder, SlowBrokerFinder,
)
from .predictive import PredictiveViolationDetector
from .notifier import (
    AlertaSelfHealingNotifier, AnomalyNotificationAction,
    AnomalyNotificationResult, AnomalyNotifier, MSTeamsSelfHealingNotifier,
    NoopNotifier, SelfHealingNotifier, SlackSelfHealingNotifier,
)
from .provisioner import (
    BasicProvisioner, ProvisionRecommendation, ProvisionResponse,
    ProvisionStatus, Provisioner, ProvisionerState,
)
from .topic_anomaly import (
    PartitionSizeAnomalyFinder, TopicAnomalyDetector,
    TopicReplicationFactorAnomalyFinder,
)

__all__ = [
    "Anomaly", "AnomalyType", "BrokerFailures", "DiskFailures",
    "GoalViolations", "MaintenanceEvent", "MaintenanceEventType",
    "MetricAnomaly", "PredictedGoalViolations", "TopicAnomaly",
    "PredictiveViolationDetector", "BrokerFailureDetector",
    "DiskFailureDetector", "GoalViolationDetector",
    "FileMaintenanceEventReader", "IdempotenceCache",
    "InMemoryMaintenanceEventReader", "MaintenanceEventDetector",
    "AnomalyDetectorManager", "AnomalyStatus", "MetricAnomalyDetector",
    "PercentileMetricAnomalyFinder", "SlowBrokerFinder",
    "AlertaSelfHealingNotifier", "AnomalyNotificationAction",
    "AnomalyNotificationResult", "AnomalyNotifier",
    "MSTeamsSelfHealingNotifier", "NoopNotifier", "SelfHealingNotifier",
    "SlackSelfHealingNotifier", "BasicProvisioner",
    "ProvisionRecommendation", "ProvisionResponse", "ProvisionStatus",
    "Provisioner", "ProvisionerState", "PartitionSizeAnomalyFinder",
    "TopicAnomalyDetector", "TopicReplicationFactorAnomalyFinder",
]
