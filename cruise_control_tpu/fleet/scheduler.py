"""Fair solver-work scheduler: one device (or mesh), many clusters.

All solver work in a fleet funnels through this scheduler, which
decides whose work runs next. Three priority classes — self-healing >
expiring proposal cache > on-demand requests — with round-robin
fairness ACROSS clusters inside each class, and a starvation bound:
any job that has waited longer than the bound runs next regardless of
class, oldest first, so a cluster flooding a higher class can delay
but never indefinitely starve another cluster's work.

Multi-replica control plane (round 23, ``fleet.shard.workers``): N
solver worker threads drain the SAME queue, sharing the process's
persistent AOT cache and shape registry (both are process-global — a
program any worker compiles is warm for all). Placement is
bucket-affine: the first worker to solve a batch key becomes its home,
so a bucket's compiled megabatch program stays hot on the replica that
owns it instead of ping-ponging. Two forms of work-stealing keep the
fairness contract fleet-wide: an OVERDUE job (past the starvation
bound) is taken by whichever worker sees it first regardless of
affinity — the bound is a promise to the cluster, not to a worker —
and an otherwise-idle worker steals affined work rather than sit while
another replica's queue is deep. ``workers=1`` is byte-identical to
the single-worker scheduler of rounds 6-22.

The reference has no analogue (one JVM per cluster = the OS scheduler);
the closest relative is GoalOptimizer's proposal-precompute executor
(GoalOptimizer.java:112-119), which this subsumes fleet-wide: the
pacer enqueues one EXPIRING_CACHE job per cluster at that cluster's
configured cadence (fleet.precompute.cadence.ms) whenever its proposal
cache is no longer fresh.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

from ..utils.resilience import BreakerOpenError, CircuitBreaker

LOG = logging.getLogger(__name__)


class JobKind(enum.IntEnum):
    """Priority classes, lower = more urgent."""

    SELF_HEALING = 0
    EXPIRING_CACHE = 1
    ON_DEMAND = 2


@dataclasses.dataclass
class SolverJob:
    kind: JobKind
    cluster_id: str
    fn: Callable[[], Any]
    future: Future
    enqueued_at: float
    seq: int
    # Coalescing hint (megabatch mode): queued jobs sharing a non-None
    # batch_key are drained together when one of them is picked and
    # solved as ONE batched device program. ``payload`` carries what the
    # batch runner needs (fleet.megabatch.PrecomputePayload); ``fn``
    # stays the solo fallback for inline/shutdown execution.
    batch_key: tuple | None = None
    payload: Any = None
    # Heal-ledger correlation (round 16): the ambient heal handle at
    # submit time (None when no heal in flight). A self-healing fix
    # routed through the scheduler re-enters its heal scope on the
    # worker thread and attributes its queue wait to the chain.
    heal: Any = None


class FleetScheduler:
    """Single-consumer priority queue over the fleet's solver work.

    ``submit`` returns a Future; one worker thread (or a test calling
    ``run_pending`` synchronously) drains the queue. ``clock`` is
    injectable so starvation/fairness behavior is testable without
    real waiting.
    """

    @classmethod
    def from_config(cls, config) -> "FleetScheduler":
        """Build with the configured starvation bound
        (fleet.scheduler.starvation.bound.ms), worker replica count
        (fleet.shard.workers) and the per-cluster circuit breaker
        (resilience.breaker.*)."""
        return cls(
            starvation_bound_s=config.get_long(
                "fleet.scheduler.starvation.bound.ms") / 1000.0,
            breaker=CircuitBreaker.from_config(config, name="fleet"),
            workers=config.get_int("fleet.shard.workers"))

    def __init__(self, starvation_bound_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 breaker: CircuitBreaker | None = None,
                 workers: int = 1):
        self._starvation_bound_s = starvation_bound_s
        self._clock = clock
        self._workers_n = max(1, int(workers))
        # Per-cluster breaker (round 9): a cluster whose jobs keep
        # failing trips open and its queued work is SKIPPED (futures
        # fail fast with BreakerOpenError) instead of burning solver
        # rounds and starving the round-robin for healthy clusters.
        self._breaker = breaker
        self._cond = threading.Condition()
        self._queue: list[SolverJob] = []
        self._seq = 0
        self._picks = 0
        # cluster -> pick-counter value of its last pick, for round-robin
        # fairness inside a priority class (least recently served wins).
        self._last_served: dict[str, int] = {}
        self._stop = threading.Event()
        self._shut = False
        self._worker: threading.Thread | None = None
        self._solvers: list[threading.Thread] = []
        # batch_key -> home worker id (round 23 bucket affinity): set by
        # the first pick of a job carrying that key; later picks prefer
        # the home worker so the bucket's compiled megabatch program
        # stays hot there. Overdue jobs and idle workers steal across it.
        self._affinity: dict[tuple, int] = {}
        self._pacer: threading.Thread | None = None
        self._registry = None
        self._jobs_run = 0
        # Megabatch coalescing (round 14): when a batch runner is
        # attached, a picked job with a batch_key drains every queued
        # job sharing that key and the whole set solves as ONE batched
        # device program. Fairness and the starvation bound apply to
        # BATCHES: the pick that seeds a batch is chosen by the normal
        # priority/fairness/starvation rules, and every coalesced
        # cluster counts as served by that pick.
        self._batch_runner: Callable[[list[SolverJob]], None] | None = None
        # (cluster_id, kind) keys currently executing — a SET because a
        # coalesced megabatch executes many clusters' jobs at once and
        # the pacer must see every one of them as in-flight.
        self._active: set[tuple[str, JobKind]] = set()

    def set_batch_runner(self, runner: "Callable | None") -> None:
        """Attach the megabatch coalescing runner (fleet.megabatch).
        ``runner(jobs)`` receives the drained batch and must resolve
        every job's future; None disables coalescing."""
        with self._cond:
            self._batch_runner = runner

    @property
    def coalescing(self) -> bool:
        return self._batch_runner is not None

    # -- submission --------------------------------------------------------
    def submit(self, cluster_id: str, kind: JobKind,
               fn: Callable[[], Any], batch_key: tuple | None = None,
               payload: Any = None) -> Future:
        from ..utils.heal_ledger import current_heal
        heal = current_heal()
        job = SolverJob(kind=kind, cluster_id=cluster_id, fn=fn,
                        future=Future(), enqueued_at=self._clock(),
                        seq=self._next_seq(), batch_key=batch_key,
                        payload=payload,
                        heal=heal if heal.recording else None)
        with self._cond:
            if self._shut:
                # After shutdown nothing drains the queue; a queued job's
                # .result() would block its caller forever. Run inline —
                # correctness over fairness (mirrors the not-running
                # guards at the call sites).
                inline = True
            else:
                inline = False
                self._queue.append(job)
                self._cond.notify()
        from ..utils.sensors import SENSORS
        SENSORS.count("fleet_scheduler_jobs_submitted",
                      labels={"cluster": cluster_id, "kind": kind.name})
        if inline:
            self._run(job)
        return job.future

    def _next_seq(self) -> int:
        with self._cond:
            self._seq += 1
            return self._seq

    def pending(self, cluster_id: str | None = None,
                kind: JobKind | None = None) -> int:
        with self._cond:
            return sum(1 for j in self._queue
                       if (cluster_id is None or j.cluster_id == cluster_id)
                       and (kind is None or j.kind == kind))

    # -- selection ---------------------------------------------------------
    def _pick_locked(self, worker_id: int = 0) -> SolverJob | None:
        """Next job for ``worker_id`` under priority + fairness + the
        starvation bound + bucket affinity (round 23).
        Caller holds the condition lock."""
        if self._queue and self._breaker is not None:
            # Skip (fail fast) queued jobs for open-breaker clusters —
            # an API caller blocked on the future gets 503 + Retry-After,
            # the pacer's precompute re-enqueues next sweep, and healthy
            # clusters' work proceeds. ``allow`` flips a recovered
            # cluster to half-open, so its next job runs as the probe.
            skipped = [j for j in self._queue
                       if not self._breaker.allow(j.cluster_id)]
            if skipped:
                from ..utils.sensors import SENSORS
                for job in skipped:
                    self._queue.remove(job)
                    SENSORS.count("fleet_jobs_skipped",
                                  labels={"cluster": job.cluster_id,
                                          "kind": job.kind.name})
                    if job.heal is not None:
                        # A fix skipped by an open breaker is a
                        # documented heal terminal — the manager also
                        # resolves breaker_skipped on the raised error,
                        # but the resolve is idempotent (first wins) and
                        # a non-fix correlated job records it here.
                        job.heal.resolve("breaker_skipped",
                                         cluster=job.cluster_id)
                    job.future.set_exception(BreakerOpenError(
                        job.cluster_id,
                        self._breaker.retry_after_s(job.cluster_id)))
        if not self._queue:
            return None
        now = self._clock()
        stolen = False
        overdue = [j for j in self._queue
                   if now - j.enqueued_at >= self._starvation_bound_s]
        if overdue:
            # The bound dominates everything — including affinity: the
            # oldest overdue job runs on WHICHEVER worker sees it first
            # (the bound is a promise to the cluster, not to a worker),
            # so the starvation guarantee holds fleet-wide.
            job = min(overdue, key=lambda j: (j.enqueued_at, j.seq))
            stolen = self._affined_elsewhere(job, worker_id)
        else:
            best_kind = min(j.kind for j in self._queue)
            in_class = [j for j in self._queue if j.kind == best_kind]
            # Bucket affinity (round 23): prefer jobs homed on this
            # worker or not yet homed; an idle worker STEALS an
            # affined-elsewhere job rather than sit while another
            # replica's share is deep (throughput over placement — the
            # shared AOT cache makes a steal a cache miss, not a
            # recompile).
            mine = [j for j in in_class
                    if not self._affined_elsewhere(j, worker_id)]
            pool = mine or in_class
            stolen = not mine
            # Round-robin by cluster: the cluster served longest ago goes
            # first; within a cluster, FIFO.
            job = min(pool, key=lambda j: (
                self._last_served.get(j.cluster_id, 0), j.seq))
        self._queue.remove(job)
        self._picks += 1
        self._last_served[job.cluster_id] = self._picks
        if job.batch_key is not None:
            from ..utils.sensors import SENSORS
            home = self._affinity.get(job.batch_key)
            if home is None:
                # First pick homes the bucket on this worker.
                self._affinity[job.batch_key] = worker_id
            elif home == worker_id:
                SENSORS.count("fleet_shard_affinity_hits")
            if stolen:
                # A steal re-homes the bucket: the stealing worker's
                # dispatch caches are now the warm ones.
                self._affinity[job.batch_key] = worker_id
                SENSORS.count("fleet_shard_steals")
        # Marked active HERE, under the same lock as the dequeue: a
        # pacer sweep must never observe the job as neither queued nor
        # active (the window between dequeue and execution).
        self._active.add((job.cluster_id, job.kind))
        return job

    def _affined_elsewhere(self, job: SolverJob, worker_id: int) -> bool:
        """Whether the job's bucket is homed on a DIFFERENT worker (jobs
        without a batch key are never affined — any worker serves
        them)."""
        if job.batch_key is None:
            return False
        home = self._affinity.get(job.batch_key)
        return home is not None and home != worker_id

    def _take_locked(self, worker_id: int = 0) -> list[SolverJob] | None:
        """Pick the next job, then — in coalescing mode — drain every
        queued job sharing its batch_key into one megabatch. The PICK is
        fairness's unit (priority, round-robin, starvation bound all
        choose the seed job); the drained peers ride along and every
        coalesced cluster counts as served by this pick, so the
        round-robin cannot re-serve a freshly batched cluster ahead of
        one still waiting. Caller holds the condition lock."""
        job = self._pick_locked(worker_id)
        if job is None:
            return None
        batch = [job]
        if self._batch_runner is not None and job.batch_key is not None:
            peers = [j for j in self._queue
                     if j.batch_key == job.batch_key]
            for p in peers:
                self._queue.remove(p)
                self._last_served[p.cluster_id] = self._picks
                self._active.add((p.cluster_id, p.kind))
            batch += peers
        return batch

    def _run(self, job: SolverJob) -> None:
        from ..utils.heal_ledger import heal_scope
        from ..utils.sensors import SENSORS, cluster_label
        from ..utils.tracing import TRACER
        wait_s = max(self._clock() - job.enqueued_at, 0.0)
        SENSORS.record_timer("fleet_scheduler_queue_wait", wait_s,
                             labels={"cluster": job.cluster_id,
                                     "kind": job.kind.name})
        # Queue-wait DISTRIBUTION per priority class: the timer above
        # collapses to count/sum/last/max; fairness regressions live in
        # the tail, which only a histogram preserves.
        SENSORS.observe("fleet_queue_wait_seconds", wait_s,
                        labels={"cluster": job.cluster_id,
                                "kind": job.kind.name})
        if job.heal is not None:
            # Where the heal's time went, scheduler edition: the chain
            # sees how long the fix sat behind other clusters' work.
            job.heal.phase("solver_queued", kind=job.kind.name,
                           waitS=round(wait_s, 6))
        t0 = time.monotonic()
        try:
            # The job's own operation trace (the facade op opens the root
            # span) gets the queue wait attached via the wrapping span —
            # worker threads have no ambient parent, so fleet.job IS the
            # root and the op span nests under it. The heal scope is
            # re-entered explicitly: ContextVars do not cross into the
            # worker thread.
            with cluster_label(job.cluster_id), \
                    TRACER.span("fleet.job", operation=f"fleet.{job.kind.name.lower()}",
                                cluster=job.cluster_id, kind=job.kind.name,
                                queue_wait_s=round(wait_s, 6)), \
                    heal_scope(job.heal):
                result = job.fn()
        except BaseException as e:  # noqa: BLE001 — carried by the future
            if self._breaker is not None:
                self._breaker.record_failure(job.cluster_id)
            job.future.set_exception(e)
        else:
            if self._breaker is not None:
                self._breaker.record_success(job.cluster_id)
            job.future.set_result(result)
        finally:
            with self._cond:
                self._active.discard((job.cluster_id, job.kind))
        self._jobs_run += 1
        SENSORS.record_timer("fleet_scheduler_job",
                             time.monotonic() - t0,
                             labels={"cluster": job.cluster_id,
                                     "kind": job.kind.name})

    def _run_batch(self, jobs: list[SolverJob]) -> None:
        """Execute a coalesced megabatch through the batch runner. The
        runner must resolve every job's future (result or exception);
        anything it leaves unresolved — or a batch-level crash — fails
        the affected futures here so no caller ever blocks forever.
        Per-cluster breaker accounting mirrors ``_run``'s."""
        from ..utils.sensors import SENSORS
        from ..utils.tracing import TRACER
        t0 = time.monotonic()
        for job in jobs:
            wait_s = max(self._clock() - job.enqueued_at, 0.0)
            SENSORS.record_timer("fleet_scheduler_queue_wait", wait_s,
                                 labels={"cluster": job.cluster_id,
                                         "kind": job.kind.name})
            SENSORS.observe("fleet_queue_wait_seconds", wait_s,
                            labels={"cluster": job.cluster_id,
                                    "kind": job.kind.name})
            if job.heal is not None:
                job.heal.phase("solver_queued", kind=job.kind.name,
                               waitS=round(wait_s, 6))
        try:
            # No ambient cluster label: the batch belongs to the FLEET
            # (per-cluster attribution happens inside the runner with
            # explicit labels; an ambient lead-cluster label would
            # mislabel the batch-level occupancy sensors).
            with TRACER.span("fleet.megabatch",
                             operation="fleet.megabatch",
                             clusters=",".join(j.cluster_id
                                               for j in jobs),
                             occupancy=len(jobs)):
                self._batch_runner(jobs)
        except BaseException as e:  # noqa: BLE001 — carried by futures
            for job in jobs:
                if not job.future.done():
                    job.future.set_exception(e)
        finally:
            for job in jobs:
                if not job.future.done():
                    job.future.set_exception(RuntimeError(
                        "megabatch runner left the job unresolved"))
            with self._cond:
                for job in jobs:
                    self._active.discard((job.cluster_id, job.kind))
            self._jobs_run += len(jobs)
        if self._breaker is not None:
            for job in jobs:
                if job.future.cancelled() or \
                        job.future.exception() is not None:
                    self._breaker.record_failure(job.cluster_id)
                else:
                    self._breaker.record_success(job.cluster_id)
        SENSORS.count("fleet_jobs_coalesced", len(jobs))
        SENSORS.record_timer("fleet_scheduler_job",
                             time.monotonic() - t0,
                             labels={"cluster": jobs[0].cluster_id,
                                     "kind": jobs[0].kind.name})

    def run_pending(self, max_jobs: int | None = None,
                    worker_id: int = 0) -> int:
        """Synchronously drain queued jobs on the calling thread (the
        deterministic test driver; also usable by an embedder that wants
        its own loop). ``worker_id`` is the replica identity used for
        bucket affinity — tests drive multi-worker placement by calling
        with different ids. Returns the number of jobs run."""
        ran = 0
        while max_jobs is None or ran < max_jobs:
            with self._cond:
                batch = self._take_locked(worker_id)
            if batch is None:
                break
            if self._batch_runner is not None \
                    and batch[0].batch_key is not None:
                self._run_batch(batch)
            else:
                self._run(batch[0])
            ran += len(batch)
        return ran

    # -- worker + precompute pacer ----------------------------------------
    def bind(self, registry) -> None:
        """Attach the registry whose clusters the pacer sweeps (called by
        FleetRegistry at construction; no threads started)."""
        self._registry = registry

    def start(self, registry=None, pacer_interval_s: float = 1.0,
              pacer: bool = True) -> None:
        """Start the solver worker thread(s) — ``fleet.shard.workers``
        replicas draining the shared queue; with a registry (or one
        already bound), also the precompute pacer that keeps every
        unpaused cluster's proposal cache warm at its configured cadence
        (``pacer=False`` starts the workers alone)."""
        registry = registry or self._registry
        self._registry = registry
        with self._cond:
            self._shut = False
        if not any(t.is_alive() for t in self._solvers):
            self._stop.clear()
            self._solvers = [
                threading.Thread(target=self._worker_loop, args=(i,),
                                 daemon=True, name=f"fleet-solver-{i}")
                for i in range(self._workers_n)]
            for t in self._solvers:
                t.start()
            # ``_worker`` stays an alias of replica 0 for embedders that
            # poke at the single-worker field directly.
            self._worker = self._solvers[0]
            from ..utils.sensors import SENSORS
            SENSORS.gauge("fleet_shard_workers", self._workers_n)
        if pacer and registry is not None and (self._pacer is None
                                               or not self._pacer.is_alive()):
            self._pacer = threading.Thread(
                target=self._pacer_loop, args=(pacer_interval_s,),
                daemon=True, name="fleet-precompute-pacer")
            self._pacer.start()

    def _worker_loop(self, worker_id: int = 0) -> None:
        while not self._stop.is_set():
            with self._cond:
                batch = self._take_locked(worker_id)
                if batch is None:
                    self._cond.wait(timeout=0.2)
                    continue
            if self._batch_runner is not None \
                    and batch[0].batch_key is not None:
                self._run_batch(batch)
            else:
                self._run(batch[0])

    def _pacer_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.pace_once()
            except Exception:  # noqa: BLE001 — pacing must not die
                LOG.exception("fleet precompute pacing failed")

    def pace_once(self) -> int:
        """One pacing sweep: enqueue an EXPIRING_CACHE precompute for
        every unpaused cluster whose cadence has elapsed and that has no
        precompute already queued. Returns the number enqueued."""
        if self._registry is None:
            return 0
        # Prewarm deferral (round 18): while the shared solver's
        # background shape sweep is still compiling, hold paced
        # precomputes back one sweep — racing them would compile the
        # same per-shape programs twice on the startup critical path.
        # Due clusters enqueue on the first sweep after prewarm settles
        # (last_precompute is untouched here).
        from ..warmstart import prewarm_manager
        optimizer = getattr(self._registry, "optimizer", None)
        mgr = prewarm_manager(optimizer) if optimizer is not None else None
        if mgr is not None and mgr.running:
            from ..utils.sensors import SENSORS
            SENSORS.count("fleet_pacer_prewarm_deferrals")
            return 0
        n = 0
        for entry in self._registry.entries():
            if entry.paused:
                continue
            cadence_s = entry.config.get_long(
                "fleet.precompute.cadence.ms") / 1000.0
            now = self._clock()
            # Predicted-violation promotion (round 19): a cluster whose
            # predictive detector just precomputed a projected target is
            # due NOW — its real proposal cache must be hot (and
            # warm-seeded from the predicted target) before the real
            # violation lands, not a cadence later.
            predicted = bool(getattr(entry.cc,
                                     "predicted_precompute_pending", False))
            if not predicted and now - entry.last_precompute < cadence_s:
                continue
            with self._cond:
                # One lock acquisition for BOTH states: a precompute that
                # is queued or still executing must suppress re-enqueue —
                # chaining redundant back-to-back solves would hog the
                # device for any cluster whose precompute outlasts its
                # cadence.
                key = (entry.cluster_id, JobKind.EXPIRING_CACHE)
                busy = key in self._active or any(
                    (j.cluster_id, j.kind) == key for j in self._queue)
            if busy:
                continue
            entry.last_precompute = now
            if predicted:
                entry.cc.predicted_precompute_pending = False
                from ..utils.sensors import SENSORS
                SENSORS.count("fleet_pacer_predicted_promotions",
                              labels={"cluster": entry.cluster_id})
            cc, cid = entry.cc, entry.cluster_id
            # Overlap host-side model assembly with whatever solve is
            # currently holding the device: kick the monitor's background
            # prefetch BEFORE enqueueing, so by the time this cluster's
            # precompute reaches the head of the queue its cluster model
            # is already built (the solve then starts immediately instead
            # of paying the assembly on the device's critical path).
            prefetch = getattr(getattr(cc, "load_monitor", None),
                               "prefetch_model", None)
            if prefetch is not None:
                try:
                    prefetch()
                except Exception:  # noqa: BLE001 — overlap is best-effort
                    LOG.debug("fleet: model prefetch kickoff for %s failed",
                              cid, exc_info=True)
            def precompute(cc=cc, cid=cid):
                opt = getattr(cc, "optimizer", None)
                if opt is None or not hasattr(opt, "thread_dispatch_stats"):
                    return cc.proposals()
                seq0 = opt.thread_pass_seq()
                result = cc.proposals()
                # Megastep dispatch accounting per cluster: the pacer's
                # precompute is the steady-state solve, so its dispatch
                # count / rounds-per-dispatch ARE the fleet's device-link
                # cost profile (and the visible payoff of the optimizer's
                # pass-persistent AdaptiveDispatch budget). Attribution
                # uses the optimizer's THREAD-LOCAL pass record: the
                # solve (if any) ran synchronously on this worker thread
                # inside proposals(), so an advanced thread_pass_seq
                # proves the stats are exactly this precompute's — a
                # cache-served request advances nothing, and passes that
                # other clusters' facade threads start concurrently are
                # invisible here (the shared last_dispatch_stats slot
                # could report either).
                if opt.thread_pass_seq() == seq0:
                    return result
                from ..utils.sensors import SENSORS
                ds = opt.thread_dispatch_stats()
                if ds.get("dispatch_count"):
                    SENSORS.gauge("fleet_precompute_dispatches",
                                  ds["dispatch_count"],
                                  labels={"cluster": cid})
                    SENSORS.gauge("fleet_precompute_rounds_per_dispatch_p50",
                                  ds["rounds_per_dispatch_p50"],
                                  labels={"cluster": cid})
                return result

            # Whole-bucket batch fills (ROADMAP item 3): in coalescing
            # mode every due cluster's precompute carries its bucket's
            # batch key, so a sweep that finds the whole bucket due
            # emits ONE megabatch fill instead of per-cluster solves
            # (the runner reports fleet_precompute_dispatches{cluster=}
            # from the split readback). A cluster with no recorded
            # bucket yet (first build pending) submits solo.
            batch_key = payload = None
            if self._batch_runner is not None:
                from .megabatch import PrecomputePayload, precompute_batch_key
                batch_key = precompute_batch_key(entry)
                if batch_key is not None:
                    payload = PrecomputePayload(cluster_id=cid, cc=cc)
            fut = self.submit(cid, JobKind.EXPIRING_CACHE, precompute,
                              batch_key=batch_key, payload=payload)

            def report(f, cid=cid):
                # The pacer owns this future — surface failures, else a
                # cluster whose precompute consistently fails would serve
                # a cold cache with no trace anywhere.
                exc = None if f.cancelled() else f.exception()
                if exc is not None:
                    LOG.warning("fleet: precompute for %s failed: %s",
                                cid, exc)
                    from ..utils.sensors import SENSORS
                    SENSORS.count("fleet_precompute_failures",
                                  labels={"cluster": cid})

            fut.add_done_callback(report)
            n += 1
        return n

    def shutdown(self) -> None:
        self._stop.set()
        with self._cond:
            self._shut = True
            self._cond.notify_all()
        for t in (*self._solvers, self._pacer):
            if t is not None and t.is_alive():
                t.join(timeout=10.0)
        self._solvers = []
        self._worker = self._pacer = None
        with self._cond:
            leftovers, self._queue = self._queue, []
        for job in leftovers:
            job.future.cancel()

    @property
    def jobs_run(self) -> int:
        return self._jobs_run

    @property
    def breaker(self) -> CircuitBreaker | None:
        """The per-cluster circuit breaker (None = breaking disabled)."""
        return self._breaker

    def ensure_breaker(self, config) -> None:
        """Attach the configured per-cluster breaker when none was
        injected (the FleetRegistry's wiring hook for bare schedulers);
        an existing breaker — including an injected-clock test one — is
        left untouched. Runs on the scheduler's own clock."""
        if self._breaker is None:
            self._breaker = CircuitBreaker.from_config(
                config, name="fleet", clock=self._clock)

    @property
    def running(self) -> bool:
        """True while any worker thread is draining the queue (callers
        that would block on a Future must run inline when nothing
        drains)."""
        return any(t.is_alive() for t in self._solvers)
