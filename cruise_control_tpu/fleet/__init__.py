"""Fleet federation: many Kafka clusters, one solver.

The reference deployment model is one service instance per cluster; fleet
operation at LinkedIn scale (~7K brokers across many clusters, PAPER.md)
is done by hand outside the tool. The TPU formulation makes federation
natural: ``ClusterTensors`` is a frozen pytree and the chain kernels are
shape-polymorphic up to padding, so one device (or mesh) can serve the
proposal/self-healing load of an entire fleet through a handful of
compiled kernels instead of one process per cluster.

- ``bucketing``: shape-bucket padding onto a small geometric grid so N
  clusters reuse a few compiled chain kernels.
- ``registry``: cluster lifecycle (register/deregister/pause) with
  per-cluster config overlays; each cluster owns its monitor/detector/
  executor context while sharing the process-wide solver.
- ``scheduler``: a fair solver-work scheduler multiplexing per-cluster
  precompute, self-healing, and on-demand requests onto the single
  device/mesh with priorities and a starvation bound — plus a megabatch
  coalescing mode that drains compatible queued jobs into one batch.
- ``megabatch``: the megabatch fleet solver (round 14) — same-bucket
  clusters stacked along a cluster axis and solved in ONE donated
  megastep dispatch, one compiled program per bucket shape at any
  occupancy.
"""

from .bucketing import BucketGrid, pad_to_bucket, unpad_state
from .megabatch import MegabatchRunner, PrecomputePayload
from .registry import (
    ClusterPausedError, FleetEntry, FleetRegistry, UnknownClusterError,
)
from .scheduler import FleetScheduler, JobKind

__all__ = [
    "BucketGrid", "pad_to_bucket", "unpad_state",
    "FleetRegistry", "FleetEntry", "UnknownClusterError",
    "ClusterPausedError",
    "FleetScheduler", "JobKind",
    "MegabatchRunner", "PrecomputePayload",
]
