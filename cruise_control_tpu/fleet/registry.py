"""Cluster lifecycle for the fleet: register / deregister / pause.

Each registered cluster owns its FULL single-cluster context — admin
backend, load monitor, anomaly detectors, executor — exactly as a
standalone deployment would, built from the fleet's base config merged
with a per-cluster overlay. What clusters SHARE is the solver: one
``GoalOptimizer`` (and its device/mesh) serves every cluster, with each
cluster's model padded onto the fleet's ``BucketGrid`` so the chain
kernels compile once per bucket shape instead of once per cluster.

Solver work is routed through the ``FleetScheduler`` when one is
attached: proposal precompute via the pacer, self-healing fixes via the
detector manager's ``fix_runner`` hook, on-demand API requests via the
server's fleet routing. A paused cluster keeps sampling metrics and
serving reads but gets NO solver time: paced precompute and self-healing
are skipped and solver-class API endpoints are refused (administrative
toggles — sampling pause/resume, self-healing flags — stay available so
an operator can reconfigure a paused cluster before resuming it).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Mapping

from ..analyzer.optimizer import GoalOptimizer
from ..config.cruise_control_config import CruiseControlConfig
from ..facade import CruiseControl
from .bucketing import BucketGrid
from .scheduler import FleetScheduler, JobKind

LOG = logging.getLogger(__name__)


class UnknownClusterError(KeyError):
    """No such cluster id in the fleet (HTTP 404 at the API layer)."""


class ClusterPausedError(RuntimeError):
    """Operation refused: the cluster is administratively paused."""


@dataclasses.dataclass
class FleetEntry:
    cluster_id: str
    cc: CruiseControl
    config: CruiseControlConfig
    paused: bool = False
    registered_at_ms: int = 0
    # Monotonic timestamp of the last paced precompute (scheduler pacer).
    last_precompute: float = 0.0
    # Last-seen (real_brokers, real_partitions) -> padded bucket shape,
    # recorded by the pad hook on every model build.
    shape: tuple[int, int] | None = None
    bucket: tuple[int, int] | None = None
    # Whether deregister() should shut the facade down (False when the
    # embedder handed us a facade it manages itself).
    owns_cc: bool = True


def _default_factory(config: CruiseControlConfig, admin,
                     optimizer: GoalOptimizer) -> CruiseControl:
    return CruiseControl(config, admin, optimizer=optimizer)


class FleetRegistry:
    """The fleet's cluster table + shared-solver wiring."""

    def __init__(self, base_config: CruiseControlConfig | None = None,
                 optimizer: GoalOptimizer | None = None,
                 scheduler: FleetScheduler | None = None,
                 grid: BucketGrid | None = None,
                 factory: Callable[..., CruiseControl] | None = None):
        self._base = base_config or CruiseControlConfig()
        self._optimizer = optimizer or GoalOptimizer(self._base)
        self._grid = grid or BucketGrid.from_config(self._base)
        self._scheduler = scheduler
        self._megabatch = None
        if scheduler is not None:
            scheduler.bind(self)
            # Embedder handed a bare scheduler: attach the per-cluster
            # breaker from the base config (no-op when one was injected,
            # so injected-clock test breakers stay untouched).
            scheduler.ensure_breaker(self._base)
            # Megabatch coalescing (round 14): same-bucket precomputes
            # drain into one batched device program. An embedder that
            # attached its own batch runner keeps it.
            if self._base.get_boolean("fleet.megabatch.enabled") \
                    and not scheduler.coalescing:
                from .megabatch import MegabatchRunner
                self._megabatch = MegabatchRunner(
                    self._optimizer,
                    width=self._base.get_int("fleet.megabatch.width"))
                scheduler.set_batch_runner(self._megabatch)
        self._factory = factory or _default_factory
        self._entries: dict[str, FleetEntry] = {}
        self._lock = threading.Lock()

    @property
    def optimizer(self) -> GoalOptimizer:
        return self._optimizer

    @property
    def grid(self) -> BucketGrid:
        return self._grid

    @property
    def scheduler(self) -> FleetScheduler | None:
        return self._scheduler

    @property
    def megabatch(self):
        """The megabatch coalescing runner (None = disabled)."""
        return self._megabatch

    # -- lifecycle ---------------------------------------------------------
    def register(self, cluster_id: str, admin=None,
                 overlay: Mapping[str, Any] | None = None,
                 cc: CruiseControl | None = None,
                 start: bool = False, block_on_load: bool = False,
                 ) -> FleetEntry:
        """Add a cluster. Either pass a live ``admin`` backend (the
        registry builds the full per-cluster context from base config +
        ``overlay``) or a prebuilt facade ``cc`` (the embedder keeps
        ownership; its optimizer should be the fleet's for kernel
        sharing). ``start=True`` also starts monitor + detectors — with
        the facade's own precompute loop DISABLED; the fleet scheduler's
        pacer owns precompute cadence."""
        if (admin is None) == (cc is None):
            raise ValueError("register needs exactly one of admin= or cc=")
        if cc is not None and overlay:
            # A prebuilt facade already owns its config; silently dropping
            # the overlay would leave the operator believing a per-cluster
            # override is active.
            raise ValueError(
                "overlay= applies only when the registry builds the "
                "cluster context (admin=); a prebuilt cc= carries its own "
                "config")
        # Reserve the id BEFORE building: a racing duplicate must fail
        # before it constructs (and wires fleet hooks into) a whole
        # facade that would then leak un-shutdown.
        with self._lock:
            if cluster_id in self._entries:
                raise ValueError(f"cluster {cluster_id!r} already registered")
            self._entries[cluster_id] = None  # reservation placeholder
        try:
            owns = cc is None
            if cc is None:
                config = self._overlay_config(overlay)
                cc = self._factory(config, admin, self._optimizer)
            else:
                config = cc.config
            entry = FleetEntry(cluster_id=cluster_id, cc=cc, config=config,
                               registered_at_ms=int(time.time() * 1000),
                               owns_cc=owns)
            self._wire(entry)
            with self._lock:
                self._entries[cluster_id] = entry
        except BaseException:
            with self._lock:
                if self._entries.get(cluster_id) is None:
                    self._entries.pop(cluster_id, None)
            raise
        if start:
            try:
                cc.start_up(block_on_load=block_on_load,
                            start_precompute=False)
            except BaseException:
                # A half-started facade must not stay registered: unwind
                # to the pre-register state so the caller can retry. A
                # registry-built facade is also shut down — its monitor
                # threads may already be sampling, and the reference
                # would otherwise leak with no owner left to stop them.
                with self._lock:
                    self._entries.pop(cluster_id, None)
                cc.load_monitor.model_transform = None
                cc.anomaly_detector.fix_runner = None
                cc.megabatch_solve_width = 0
                if owns:
                    try:
                        cc.shutdown()
                    except Exception:  # noqa: BLE001 — unwind must finish
                        LOG.exception("fleet: unwind shutdown of %s failed",
                                      cluster_id)
                raise
        self._refresh_gauges()
        LOG.info("fleet: registered cluster %s", cluster_id)
        return entry

    def _overlay_config(self, overlay: Mapping[str, Any] | None,
                        ) -> CruiseControlConfig:
        merged = dict(self._base.originals())
        merged.update(overlay or {})
        return CruiseControlConfig(merged)

    def _wire(self, entry: FleetEntry) -> None:
        """Attach the fleet hooks to a cluster's context: grid padding on
        every model build, and self-healing routed through the scheduler
        at top priority."""
        grid = self._grid

        def pad_hook(state, meta, _entry=entry):
            padded, meta = grid.pad_model(state, meta)
            _entry.shape = (state.num_brokers, state.num_partitions)
            _entry.bucket = (padded.num_brokers, padded.num_partitions)
            return padded, meta

        entry.cc.load_monitor.model_transform = pad_hook
        # Megabatch everywhere (ROADMAP item 3c tail): with coalescing
        # on, the facade's own goal-chain solves — self-healing fixes and
        # on-demand operations — run through the batched kernels at
        # occupancy 1, reusing the ONE compiled program per bucket shape
        # the coalesced precompute fills already pay for (per-request
        # exclusion options ride the batched mask assembler).
        if self._megabatch is not None:
            entry.cc.megabatch_solve_width = self._megabatch.width
        if self._scheduler is not None:
            sched, cid = self._scheduler, entry.cluster_id

            def run_fix(fn, _entry=entry):
                if _entry.paused:
                    # Expected administrative state, not a failure: report
                    # "fix did not start" instead of raising, so the
                    # anomaly manager neither stack-traces nor counts a
                    # fix failure for every anomaly on a paused cluster.
                    LOG.debug("fleet: cluster %s paused; self-healing "
                              "fix skipped", cid)
                    return False
                if not sched.running:
                    # No worker draining the queue (not started yet, shut
                    # down, or a run_pending-driven embedder): blocking on
                    # the future would hang the anomaly-handler thread
                    # forever. Run inline — correctness over fairness.
                    return fn()
                from concurrent.futures import CancelledError
                try:
                    return sched.submit(cid, JobKind.SELF_HEALING,
                                        fn).result()
                except CancelledError:
                    # Scheduler shut down underneath us; CancelledError
                    # is a BaseException the anomaly manager's `except
                    # Exception` would NOT catch — translate to "fix did
                    # not start" so the detector thread survives.
                    LOG.info("fleet: self-healing fix for %s cancelled by "
                             "scheduler shutdown", cid)
                    return False

            entry.cc.anomaly_detector.fix_runner = run_fix

    def deregister(self, cluster_id: str) -> None:
        with self._lock:
            entry = self._entries.get(cluster_id)
            if entry is None:
                # Absent, or a mid-register reservation placeholder —
                # popping the placeholder would break the in-flight
                # register's duplicate guard.
                raise UnknownClusterError(cluster_id)
            del self._entries[cluster_id]
        # Unwire the fleet hooks either way: an embedder-owned facade
        # handed back must stop padding onto the fleet grid and stop
        # submitting fixes to a scheduler it no longer belongs to.
        entry.cc.load_monitor.model_transform = None
        entry.cc.anomaly_detector.fix_runner = None
        entry.cc.megabatch_solve_width = 0
        if entry.owns_cc:
            try:
                entry.cc.shutdown()
            except Exception:  # noqa: BLE001 — removal must complete
                LOG.exception("fleet: shutdown of %s failed", cluster_id)
        from ..utils.sensors import SENSORS
        SENSORS.remove_labeled("cluster", cluster_id)
        self._refresh_gauges()
        LOG.info("fleet: deregistered cluster %s", cluster_id)

    def pause(self, cluster_id: str) -> None:
        self.entry(cluster_id).paused = True
        self._refresh_gauges()

    def resume(self, cluster_id: str) -> None:
        self.entry(cluster_id).paused = False
        self._refresh_gauges()

    # -- lookup ------------------------------------------------------------
    def entry(self, cluster_id: str) -> FleetEntry:
        with self._lock:
            entry = self._entries.get(cluster_id)
        if entry is None:
            raise UnknownClusterError(cluster_id)
        return entry

    def get(self, cluster_id: str,
            for_operation: bool = False) -> CruiseControl:
        """The cluster's facade; ``for_operation=True`` additionally
        refuses paused clusters (mutating/solver paths)."""
        entry = self.entry(cluster_id)
        if for_operation and entry.paused:
            raise ClusterPausedError(f"cluster {cluster_id!r} is paused")
        return entry.cc

    def cluster_id_of(self, cc: CruiseControl) -> str | None:
        """Reverse lookup: the cluster id a facade is registered under,
        or None. Lets the API treat a no-?cluster= request against a
        registered default facade as THAT cluster's request (scheduler
        routing + pause semantics apply either way)."""
        with self._lock:
            for cid, e in self._entries.items():
                if e is not None and e.cc is cc:
                    return cid
        return None

    def cluster_ids(self) -> list[str]:
        with self._lock:
            return sorted(cid for cid, e in self._entries.items()
                          if e is not None)

    def entries(self) -> list[FleetEntry]:
        with self._lock:
            return [e for e in self._entries.values() if e is not None]

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values() if e is not None)

    # -- observability -----------------------------------------------------
    def _refresh_gauges(self) -> None:
        from ..utils.sensors import SENSORS
        entries = self.entries()
        SENSORS.gauge("fleet_clusters_registered", len(entries))
        SENSORS.gauge("fleet_clusters_paused",
                      sum(1 for e in entries if e.paused))
        SENSORS.gauge("fleet_bucket_shapes",
                      len({e.bucket for e in entries if e.bucket}))

    def state(self) -> dict:
        """The FLEET endpoint body."""
        clusters = {}
        for e in self.entries():
            row: dict[str, Any] = {
                "paused": e.paused,
                "registeredAtMs": e.registered_at_ms,
            }
            if e.shape is not None:
                row["numBrokers"], row["numPartitions"] = e.shape
            if e.bucket is not None:
                row["bucketBrokers"], row["bucketPartitions"] = e.bucket
            try:
                with e.cc._proposal_lock:
                    row["proposalReady"] = e.cc._proposal_cache is not None
            except Exception:  # noqa: BLE001 — state is best-effort
                row["proposalReady"] = False
            clusters[e.cluster_id] = row
        buckets = sorted({e.bucket for e in self.entries()
                          if e.bucket is not None})
        body = {
            "clusters": clusters,
            "numClusters": len(clusters),
            "bucketShapes": [list(b) for b in buckets],
            "grid": {"brokerBase": self._grid.broker_base,
                     "partitionBase": self._grid.partition_base,
                     "factor": self._grid.factor},
        }
        if self._scheduler is not None:
            body["scheduler"] = {
                "pendingJobs": self._scheduler.pending(),
                "jobsRun": self._scheduler.jobs_run,
            }
        if self._megabatch is not None:
            body["megabatch"] = self._megabatch.stats()
        # Prewarm progress of the SHARED solver (round 18): the fleet's
        # clusters compile once per bucket shape, so one sweep covers
        # them all — horizontal-scaling replicas watch this before
        # taking solver traffic. Absent when prewarm is disabled.
        from ..warmstart import prewarm_status
        pw = prewarm_status(self._optimizer)
        if pw is not None:
            body["prewarm"] = pw
        return body

    def shutdown(self) -> None:
        for cid in self.cluster_ids():
            try:
                self.deregister(cid)
            except UnknownClusterError:
                pass
