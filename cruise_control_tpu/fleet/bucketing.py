"""Shape-bucket padding onto a small geometric grid.

XLA compiles one executable per array shape, and a full-chain compile at
scale is minutes (solver.partition.bucket.size rationale). The builder's
per-cluster bucket multiples keep ONE cluster's shape stable over time;
a fleet needs the stronger property that DIFFERENT clusters land on the
same shape. Rounding (num_brokers, num_partitions) up to a geometric
grid (base x factor^k) quantizes the whole fleet to a handful of shapes
per octave, so N clusters share O(log N) compiled chain kernels.

Padding soundness (why a padded solve is byte-identical to an unpadded
one on the real rows): padded brokers enter DEAD with zero capacity and
``broker_mask`` False, so ``alive_mask`` excludes them, every per-broker
score the candidate generators read is -inf/invalid for them, and they
can be neither source nor destination; padded partitions carry
``assignment = -1`` and ``partition_mask`` False, so ``replica_exists``
masks them out of every reduction and candidate weight. Selection is
score-then-lowest-index, and padding only APPENDS rows, so real rows
keep their indices and the per-round argmax/top-k picks are identical.
The equivalence tests in tests/test_fleet.py pin this byte-for-byte at
two bucket sizes.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..common.broker_state import BrokerState
from ..model.tensors import ClusterMeta, ClusterTensors


def geometric_round_up(n: int, base: int, factor: float) -> int:
    """Smallest grid point ``ceil(base * factor^k) >= n`` (k >= 0)."""
    if n <= 0:
        return max(1, base)
    size = max(1, base)
    while size < n:
        size = max(size + 1, int(np.ceil(size * factor)))
    return size


@dataclasses.dataclass(frozen=True)
class BucketGrid:
    """The fleet's shared shape grid. One instance per process: every
    cluster registered with the fleet is padded onto THIS grid, which is
    what makes their solver kernels shape-compatible."""

    broker_base: int = 4
    partition_base: int = 256
    topic_base: int = 8
    factor: float = 2.0

    @classmethod
    def from_config(cls, config) -> "BucketGrid":
        return cls(
            broker_base=config.get_int("fleet.bucket.broker.base"),
            partition_base=config.get_int("fleet.bucket.partition.base"),
            topic_base=config.get_int("fleet.bucket.topic.base"),
            factor=config.get_double("fleet.bucket.geometric.factor"))

    def bucket_shape(self, num_brokers: int,
                     num_partitions: int) -> tuple[int, int]:
        """(padded_brokers, padded_partitions) for a cluster shape."""
        return (geometric_round_up(num_brokers, self.broker_base, self.factor),
                geometric_round_up(num_partitions, self.partition_base,
                                   self.factor))

    def pad_model(self, state: ClusterTensors, meta: ClusterMeta,
                  ) -> tuple[ClusterTensors, ClusterMeta]:
        """Pad a built model up to its grid bucket (the LoadMonitor
        ``model_transform`` hook). ``meta.num_topics`` — a STATIC solver
        argument sizing the [T, B] topic planes — is quantized onto the
        grid too, else two same-shaped clusters with different topic
        counts would still compile twice; pad topics host zero replicas,
        so their balance bands collapse to [0, 0] and they contribute
        nothing to any goal. The name tables keep naming only REAL rows."""
        nb, npart = self.bucket_shape(state.num_brokers,
                                      state.num_partitions)
        nt = geometric_round_up(meta.num_topics, self.topic_base, self.factor)
        if nt != meta.num_topics:
            meta = dataclasses.replace(meta, num_topics=nt)
        return pad_to_bucket(state, nb, npart,
                             num_hosts=len(meta.host_names)), meta


def pad_to_bucket(state: ClusterTensors, num_brokers: int,
                  num_partitions: int, num_hosts: int = 0) -> ClusterTensors:
    """Append pad rows so ``state`` has exactly (num_brokers,
    num_partitions) — same pad-row encoding as the builder: DEAD
    zero-capacity masked brokers on rack 0 with a private host id,
    masked empty partitions of topic 0. No-op when already at size."""
    import jax.numpy as jnp

    b0, p0 = state.num_brokers, state.num_partitions
    if num_brokers < b0 or num_partitions < p0:
        raise ValueError(
            f"bucket ({num_brokers}, {num_partitions}) smaller than the "
            f"cluster shape ({b0}, {p0})")
    if num_brokers == b0 and num_partitions == p0:
        return state
    db, dp = num_brokers - b0, num_partitions - p0
    rf = state.max_replication_factor

    def pad_rows(a, rows, fill):
        if rows == 0:
            return a
        shape = (rows,) + tuple(a.shape[1:])
        return jnp.concatenate([a, jnp.full(shape, fill, dtype=a.dtype)])

    # Builder pad-row parity: host ids for pad rows are one-past the real
    # host table (each pad broker is its own host) so host-level
    # aggregation never merges them with a real host.
    pad_hosts = jnp.arange(b0, num_brokers, dtype=state.host.dtype) \
        + max(num_hosts, 0)
    return dataclasses.replace(
        state,
        assignment=pad_rows(state.assignment, dp, -1),
        leader_slot=pad_rows(state.leader_slot, dp, -1),
        leader_load=pad_rows(state.leader_load, dp, 0),
        follower_load=pad_rows(state.follower_load, dp, 0),
        topic=pad_rows(state.topic, dp, 0),
        partition_mask=pad_rows(state.partition_mask, dp, False),
        capacity=pad_rows(state.capacity, db, 0),
        rack=pad_rows(state.rack, db, 0),
        broker_state=pad_rows(state.broker_state, db,
                              int(BrokerState.DEAD)),
        broker_mask=pad_rows(state.broker_mask, db, False),
        host=jnp.concatenate([state.host, pad_hosts])
        if db else state.host)


def unpad_state(state: ClusterTensors, num_brokers: int,
                num_partitions: int) -> ClusterTensors:
    """Slice a padded state back to the real shape (padding only appends
    rows, so this is exact — used by the equivalence tests and anywhere a
    real-shaped tensor view is wanted)."""
    return dataclasses.replace(
        state,
        assignment=state.assignment[:num_partitions],
        leader_slot=state.leader_slot[:num_partitions],
        leader_load=state.leader_load[:num_partitions],
        follower_load=state.follower_load[:num_partitions],
        topic=state.topic[:num_partitions],
        partition_mask=state.partition_mask[:num_partitions],
        capacity=state.capacity[:num_brokers],
        rack=state.rack[:num_brokers],
        broker_state=state.broker_state[:num_brokers],
        broker_mask=state.broker_mask[:num_brokers],
        host=state.host[:num_brokers])
