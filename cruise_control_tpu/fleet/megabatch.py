"""Megabatch fleet solver: one donated device program, whole buckets of
clusters.

PR 1's fleet layer multiplexes clusters through a fair scheduler — one
cluster per device program, throughput scaling with threads. This module
is ROADMAP item 3's fix: same-bucket clusters stack along a leading
cluster axis and solve in ONE donated megastep dispatch
(analyzer.chain's ``megabatch_*`` kernels, the Podracer/Anakin
keep-everything-on-device discipline applied fleet-wide). Compile once
per bucket shape, amortize across the fleet; a batched pass costs
max-over-clusters rounds instead of the serial sum.

The pieces:

- ``precompute_batch_key``: the pacer-side coalescing HINT — last-seen
  bucket shape plus a solver-config fingerprint. Exact compatibility is
  re-verified after the models are built (shapes can drift between the
  hint and the build); incompatible stragglers fall back to their own
  batched solve at occupancy 1.
- ``PrecomputePayload``: what a batchable precompute job carries — the
  cluster's facade, whose ``precompute_inputs``/``store_precomputed``
  seams bracket the batched solve exactly like a solo ``proposals()``
  call.
- ``MegabatchRunner``: the scheduler's batch runner. Builds every
  coalesced job's model on the worker thread, groups by ACTUAL
  compatibility — (padded bucket shape incl. the replica-slot axis,
  ``num_topics``, the resolved goal chain, options) — pads each group to
  the configured batch width with inert zero-weight cluster slots (one
  compiled program per bucket shape serves any occupancy), solves via
  ``GoalOptimizer.optimizations_megabatch``, writes each cluster's
  OptimizerResult back into its proposal cache, and splits per-cluster
  dispatch accounting out of the batched readback
  (``fleet_precompute_dispatches{cluster=}``).

Failure containment mirrors the serial scheduler: a cluster whose model
build or solve fails gets exactly its own future failed (and its breaker
debited by the scheduler); batchmates proceed.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any

LOG = logging.getLogger(__name__)


def solver_config_fingerprint(config) -> tuple:
    """The solver-relevant config identity two clusters must share to sit
    in one batch HINT. The shared GoalOptimizer derives the search grid
    from its own base config, so only the goal-chain spec (which per-
    cluster overlays CAN change) needs fingerprinting here; exact chain
    equality — broker-set bindings included — is re-checked per batch by
    ``GoalOptimizer.optimizations_megabatch``."""
    return tuple(str(g) for g in config.get_list("goals"))


def precompute_batch_key(entry) -> tuple | None:
    """Coalescing hint for one cluster's paced precompute, or None when
    the cluster has no recorded bucket yet (its first model build will
    run solo and record one)."""
    if entry.bucket is None:
        return None
    return ("precompute", entry.bucket,
            solver_config_fingerprint(entry.config))


@dataclasses.dataclass
class PrecomputePayload:
    """Batchable precompute job payload (SolverJob.payload)."""

    cluster_id: str
    cc: Any  # CruiseControl


class MegabatchRunner:
    """Executes a coalesced batch of fleet jobs as megabatched solves.

    Attached to the FleetScheduler via ``set_batch_runner``; the
    scheduler guarantees every job's future is resolved even if this
    runner raises. Occupancy statistics feed ``GET /fleet`` and the
    ``solver_megabatch_*`` sensors."""

    def __init__(self, optimizer, width: int = 4):
        self._optimizer = optimizer
        self._width = max(1, int(width))
        self._lock = threading.Lock()
        self.batches_solved = 0
        self.clusters_solved = 0
        self.build_failures = 0
        self.last_occupancy = 0
        self._occupancy_sum = 0

    @property
    def width(self) -> int:
        return self._width

    def stats(self) -> dict:
        """The /fleet dashboard's megabatch section."""
        with self._lock:
            batches = self.batches_solved
            return {
                "width": self._width,
                "batchesSolved": batches,
                "clustersSolved": self.clusters_solved,
                "buildFailures": self.build_failures,
                "lastOccupancy": self.last_occupancy,
                "avgOccupancy": round(self._occupancy_sum / batches, 3)
                if batches else 0.0,
            }

    # -- the batch body ----------------------------------------------------
    def __call__(self, jobs: list) -> None:
        from ..utils.sensors import SENSORS
        prepared: list[tuple] = []
        for job in jobs:
            payload = job.payload
            try:
                chain, state, meta, options, gen = \
                    payload.cc.precompute_inputs()
            except Exception as e:  # noqa: BLE001 — fail THIS job only
                with self._lock:
                    self.build_failures += 1
                job.future.set_exception(e)
                continue
            resolved = tuple(self._optimizer.megabatch_chain(meta, chain))
            key = (state.num_partitions, state.num_brokers,
                   state.max_replication_factor, meta.num_topics,
                   resolved, options)
            prepared.append((job, payload, resolved, state, meta, options,
                            gen, key))

        groups: dict = {}
        for item in prepared:
            groups.setdefault(item[-1], []).append(item)
        for key, members in groups.items():
            for start in range(0, len(members), self._width):
                self._solve_chunk(members[start:start + self._width])
        SENSORS.gauge("fleet_megabatch_width", self._width)

    def _solve_chunk(self, members: list[tuple]) -> None:
        from ..facade import OperationResult
        from ..utils.sensors import SENSORS
        items = [(state, meta, payload.cluster_id)
                 for (_j, payload, _c, state, meta, _o, _g, _k) in members]
        chain = members[0][2]
        options = members[0][5]
        try:
            results = self._optimizer.optimizations_megabatch(
                items, goals=list(chain), options=options,
                width=self._width)
        except Exception as e:  # noqa: BLE001 — a batch-level failure
            # fails exactly the chunk's futures; other chunks proceed
            LOG.warning("fleet: megabatch solve of %d clusters failed: %s",
                        len(members), e)
            for (job, *_rest) in members:
                job.future.set_exception(e)
            return
        split = self._optimizer.last_megabatch_cluster_stats()
        occupancy = len(members)
        with self._lock:
            self.batches_solved += 1
            self.clusters_solved += occupancy
            self.last_occupancy = occupancy
            self._occupancy_sum += occupancy
        SENSORS.count("fleet_megabatch_batches")
        SENSORS.count("fleet_megabatch_clusters", occupancy)
        for (job, payload, _c, _s, _m, _o, gen, _k), res in \
                zip(members, results):
            if isinstance(res, Exception):
                job.future.set_exception(res)
                continue
            _final, result = res
            payload.cc.store_precomputed(gen, result)
            # Per-cluster dispatch accounting, split out of the batched
            # readback — the megabatch analogue of the pacer's
            # thread-local attribution (the batched solve ran on THIS
            # worker thread, so the split is exactly this batch's).
            ds = split.get(payload.cluster_id) or {}
            if ds.get("dispatch_count"):
                SENSORS.gauge("fleet_precompute_dispatches",
                              ds["dispatch_count"],
                              labels={"cluster": payload.cluster_id})
                SENSORS.gauge("fleet_precompute_rounds_per_dispatch_p50",
                              ds["rounds_per_dispatch_p50"],
                              labels={"cluster": payload.cluster_id})
            job.future.set_result(OperationResult(
                "proposals", dryrun=True, optimizer_result=result,
                proposals=result.proposals, reason="megabatch precompute"))
