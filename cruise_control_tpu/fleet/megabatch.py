"""Megabatch fleet solver: one donated device program, whole buckets of
clusters — and, since round 15, whatever ELSE is batchable in the same
scheduler turn.

PR 1's fleet layer multiplexes clusters through a fair scheduler — one
cluster per device program, throughput scaling with threads. This module
is ROADMAP item 3's fix: same-bucket work stacks along a leading cluster
axis and solves in ONE donated megastep dispatch (analyzer.chain's
``megabatch_*`` kernels, the Podracer/Anakin keep-everything-on-device
discipline applied fleet-wide). Compile once per bucket shape, amortize
across the fleet; a batched pass costs max-over-clusters rounds instead
of the serial sum.

The payload protocol (round 15 generalization): a coalesced job's
``payload`` prepares a list of ``SolveItem``s on the worker thread and
reassembles its own result from their outcomes —

- ``payload.prepare(optimizer) -> list[SolveItem]`` builds the models
  (may raise: exactly that job's future fails, batchmates proceed);
- the runner flattens items ACROSS jobs, groups by actual compatibility
  (padded bucket shape, static topic axis, resolved goal chain — options
  are per-item now, carried into per-cluster exclusion masks), chunks to
  the configured width, and solves each chunk through
  ``GoalOptimizer.optimizations_megabatch``;
- ``payload.complete(outcomes, stats) -> result`` receives its items'
  aligned outcomes (``(final_state, OptimizerResult)`` or the per-item
  Exception) plus the split per-item dispatch stats, and returns the
  job future's value (or raises to fail it).

Two payloads ship in-tree: ``PrecomputePayload`` (a paced proposal
precompute — stores a cache entry indistinguishable from a solo
``proposals()`` call) and ``futures.evaluator.FuturesPayload`` (a
COMPARE_FUTURES request whose candidate futures coalesce with the
precomputes sharing the turn — batch occupancy driven by user traffic,
not fleet size).

Failure containment mirrors the serial scheduler: a job whose prepare or
solve fails gets exactly its own future failed (and its breaker debited
by the scheduler); batchmates proceed.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any

LOG = logging.getLogger(__name__)


def solver_config_fingerprint(config) -> tuple:
    """The solver-relevant config identity two clusters must share to sit
    in one batch HINT. The shared GoalOptimizer derives the search grid
    from its own base config, so only the goal-chain spec (which per-
    cluster overlays CAN change) needs fingerprinting here; exact chain
    equality — broker-set bindings included — is re-checked per batch by
    the runner's grouping."""
    return tuple(str(g) for g in config.get_list("goals"))


def precompute_batch_key(entry) -> tuple | None:
    """Coalescing hint for one cluster's paced precompute, or None when
    the cluster has no recorded bucket yet (its first model build will
    run solo and record one). COMPARE_FUTURES jobs reuse the same key so
    a futures request drains into the same runner turn as the bucket's
    precomputes (the runner regroups by ACTUAL compatibility, so the
    futures' twin-shaped models simply form their own chunks)."""
    if entry.bucket is None:
        return None
    return ("precompute", entry.bucket,
            solver_config_fingerprint(entry.config))


@dataclasses.dataclass
class SolveItem:
    """One batched-solve slot a payload contributes: a model, its
    resolved goal chain, and its OWN options (per-item exclusion sets
    ride the batched mask assembler). ``item_id`` labels the slot's
    flight pass / sensors (a cluster id, or ``future:<id>``)."""

    item_id: str
    chain: tuple
    state: Any
    meta: Any
    options: Any = None
    # Round 18 warm starts: the TRUE current model when ``state`` is a
    # warm-seeded search start (the batched solve diffs against it);
    # None = state IS the initial.
    initial_state: Any = None


@dataclasses.dataclass
class PrecomputePayload:
    """Batchable precompute job payload (SolverJob.payload): one cache
    fill, bracketed by the facade's precompute_inputs/store_precomputed
    seams exactly like a solo ``proposals()`` call."""

    cluster_id: str
    cc: Any  # CruiseControl

    def prepare(self, optimizer) -> list[SolveItem]:
        out = self.cc.precompute_inputs()
        chain, state, meta, options, gen = out[:5]
        # 6th element (round 18): the true initial when the facade
        # warm-seeded the search start (older/stub facades return 5).
        initial = out[5] if len(out) > 5 else None
        self._generation = gen
        return [SolveItem(
            item_id=self.cluster_id,
            chain=tuple(optimizer.megabatch_chain(meta, chain)),
            state=state, meta=meta, options=options,
            initial_state=initial)]

    def complete(self, outcomes: list, stats: list):
        from ..facade import OperationResult
        from ..utils.sensors import SENSORS
        res = outcomes[0]
        if isinstance(res, Exception):
            raise res
        _final, result = res
        self.cc.store_precomputed(self._generation, result,
                                  final_state=_final)
        # Per-cluster dispatch accounting, split out of the batched
        # readback — the megabatch analogue of the pacer's thread-local
        # attribution (the batched solve ran on THIS worker thread, so
        # the split is exactly this batch's).
        ds = stats[0] or {}
        if ds.get("dispatch_count"):
            SENSORS.gauge("fleet_precompute_dispatches",
                          ds["dispatch_count"],
                          labels={"cluster": self.cluster_id})
            SENSORS.gauge("fleet_precompute_rounds_per_dispatch_p50",
                          ds["rounds_per_dispatch_p50"],
                          labels={"cluster": self.cluster_id})
        return OperationResult(
            "proposals", dryrun=True, optimizer_result=result,
            proposals=result.proposals, reason="megabatch precompute")


class MegabatchRunner:
    """Executes a coalesced batch of fleet jobs as megabatched solves.

    Attached to the FleetScheduler via ``set_batch_runner``; the
    scheduler guarantees every job's future is resolved even if this
    runner raises. Occupancy statistics feed ``GET /fleet`` and the
    ``solver_megabatch_*`` sensors.

    Batched solves inherit the optimizer's direct-assignment mode
    (``solver.direct.assignment.enabled``, round 17): with it on, count-
    distribution goals run their batched transport pre-pass across the
    whole chunk in one dispatch, and the per-cluster accounting split
    reported back to each payload (and to
    ``fleet_precompute_dispatches{cluster=}``) carries the
    ``direct_dispatches`` tally alongside the greedy dispatch counts —
    per-item stats need no new plumbing here because the split rides
    ``DispatchStats.as_dict`` unchanged."""

    def __init__(self, optimizer, width: int = 4):
        self._optimizer = optimizer
        self._width = max(1, int(width))
        self._lock = threading.Lock()
        self.batches_solved = 0
        self.clusters_solved = 0
        self.build_failures = 0
        self.last_occupancy = 0
        self._occupancy_sum = 0

    @property
    def width(self) -> int:
        return self._width

    def stats(self) -> dict:
        """The /fleet dashboard's megabatch section."""
        with self._lock:
            batches = self.batches_solved
            return {
                "width": self._width,
                "batchesSolved": batches,
                "clustersSolved": self.clusters_solved,
                "buildFailures": self.build_failures,
                "lastOccupancy": self.last_occupancy,
                "avgOccupancy": round(self._occupancy_sum / batches, 3)
                if batches else 0.0,
            }

    # -- the batch body ----------------------------------------------------
    def __call__(self, jobs: list) -> None:
        from ..utils.sensors import SENSORS
        prepared: list[tuple] = []     # (job, payload, outcomes, stats)
        flat: list[tuple] = []         # (prepared_idx, slot, SolveItem)
        for job in jobs:
            heal = getattr(job, "heal", None)
            if heal is not None:
                # Batch-coalescing attribution: a heal-correlated job
                # that drained into a megabatch turn records the batch
                # geometry it actually shared.
                heal.phase("batch_coalesced", occupancy=len(jobs),
                           width=self._width)
        for job in jobs:
            payload = job.payload
            try:
                entries = payload.prepare(self._optimizer)
            except Exception as e:  # noqa: BLE001 — fail THIS job only
                with self._lock:
                    self.build_failures += 1
                job.future.set_exception(e)
                continue
            pidx = len(prepared)
            prepared.append((job, payload,
                             [None] * len(entries), [None] * len(entries)))
            for slot, item in enumerate(entries):
                flat.append((pidx, slot, item))

        groups: dict[tuple, list[tuple]] = {}
        for pidx, slot, item in flat:
            key = (self._shape_key(item.state), item.meta.num_topics,
                   item.chain)
            groups.setdefault(key, []).append((pidx, slot, item))
        for members in groups.values():
            for start in range(0, len(members), self._width):
                self._solve_chunk(prepared, members[start:start + self._width])
        SENSORS.gauge("fleet_megabatch_width", self._width)

        for job, payload, outcomes, stats in prepared:
            try:
                value = payload.complete(outcomes, stats)
            except Exception as e:  # noqa: BLE001 — carried by the future
                job.future.set_exception(e)
            else:
                job.future.set_result(value)

    @staticmethod
    def _shape_key(state) -> tuple:
        import jax
        return tuple(jax.tree_util.tree_leaves(
            jax.tree.map(lambda x: tuple(x.shape), state)))

    def _solve_chunk(self, prepared: list[tuple],
                     members: list[tuple]) -> None:
        from ..utils.sensors import SENSORS
        chain = members[0][2].chain
        items = [(item.state, item.meta, item.item_id, item.options,
                  item.initial_state)
                 for (_p, _s, item) in members]
        try:
            results = self._optimizer.optimizations_megabatch(
                items, goals=list(chain), width=self._width)
            split = self._optimizer.last_megabatch_cluster_stats()
        except Exception as e:  # noqa: BLE001 — a chunk-level failure
            # fails exactly the chunk's slots; other chunks proceed
            LOG.warning("fleet: megabatch solve of %d models failed: %s",
                        len(members), e)
            for (pidx, slot, _item) in members:
                prepared[pidx][2][slot] = e
            return
        occupancy = len(members)
        with self._lock:
            self.batches_solved += 1
            self.clusters_solved += occupancy
            self.last_occupancy = occupancy
            self._occupancy_sum += occupancy
        SENSORS.count("fleet_megabatch_batches")
        SENSORS.count("fleet_megabatch_clusters", occupancy)
        for (pidx, slot, item), res in zip(members, results):
            prepared[pidx][2][slot] = res
            # Per-item stats carry the chunk geometry that ACTUALLY ran
            # (payloads report occupancy from execution, never from a
            # re-derivation that could drift from the runner's chunking).
            prepared[pidx][3][slot] = {
                **(split.get(item.item_id) or {}),
                "batch_occupancy": occupancy,
                "batch_width": self._width,
            }
