"""Bundled single-file operational dashboard (see index.html). Replaced
wholesale by pointing webserver.ui.diskpath at an external UI bundle."""
