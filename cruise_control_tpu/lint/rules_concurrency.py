"""CCSA007: lock discipline on module-level shared mutable state.

A module-level mutable container is process-global: every thread in the
server (fleet scheduler workers, detector loop, HTTP handlers, the bench
watchdog) shares it. The rule flags **runtime mutations** of such
containers — mutator method calls, subscript writes/deletes — performed
inside function bodies without an enclosing ``with <lock>:``.

Import-time initialization (module-scope loops filling a table) is
exempt: the import lock serializes it. Containers that are only ever
read after import are never flagged — the rule keys on the mutation,
not the declaration, so constant registries stay annotation-free.

A deliberate unsynchronized-access tolerance (the PR 5 persistent
dispatch-controller pattern: lock the registry, tolerate racy field
updates on the values) is documented in place with
``# ccsa: ok[CCSA007] <bounded/self-correcting tolerance>`` — which
``python -m tools.ccsa --list-suppressions`` then reports, making every
such tolerance in the tree machine-enumerable.
"""

from __future__ import annotations

import ast

from .core import Finding, FileContext, Rule, register

_CONTAINER_CALLS = ("list", "dict", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter", "ChainMap")
_MUTATORS = ("append", "extend", "add", "update", "insert", "pop",
             "popitem", "remove", "discard", "clear", "setdefault",
             "appendleft", "extendleft", "rotate")


def _is_mutable_container(value: ast.expr) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = Rule.dotted(value.func) or ""
        return name.rpartition(".")[2] in _CONTAINER_CALLS
    return False


def _lockish(expr: ast.expr) -> bool:
    """Heuristic: a with-context guards a critical section when any
    identifier in it contains 'lock' (``self._lock``, ``REG_LOCK``,
    ``lock.acquire…``) or it constructs/calls a threading primitive."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and "lock" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
            return True
    return False


@register
class LockDisciplineRule(Rule):
    rule_id = "CCSA007"
    title = "unlocked mutation of module-level mutable container"

    def check_file(self, ctx: FileContext) -> list[Finding]:
        # Container declarations at MODULE scope — including ones nested
        # under module-level if/try/with blocks (a gate that only looked
        # at tree.body would fail open on those) — but never inside a
        # function or class body.
        containers: set[str] = set()
        stack: list = list(ctx.tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            targets: list[ast.Name] = []
            value = None
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                targets, value = [node.target], node.value
            if value is not None and _is_mutable_container(value):
                containers.update(t.id for t in targets)
            stack.extend(ast.iter_child_nodes(node))
        if not containers:
            return []

        findings: list[Finding] = []
        self._walk(ctx, ctx.tree, containers, in_func=False,
                   in_lock=False, shadowed=frozenset(), findings=findings)
        return findings

    def _walk(self, ctx: FileContext, node: ast.AST, containers: set[str],
              in_func: bool, in_lock: bool, shadowed: frozenset,
              findings: list[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Only names bound in THIS function's own scope shadow the
            # module container here — a nested closure rebinding the
            # name for itself must not hide the outer mutation
            # (own_assigned_names stops at nested def boundaries).
            local = self.own_assigned_names(node)
            declared_global = {n for sub in ast.walk(node)
                              if isinstance(sub, ast.Global)
                              for n in sub.names}
            shadowed = frozenset((local - declared_global)
                                 & containers) | shadowed
            shadowed = frozenset(shadowed - declared_global)
            # A function defined inside a `with lock:` block runs LATER,
            # when the lock is long released — the guard never carries
            # into a nested scope.
            for child in node.body:
                self._walk(ctx, child, containers, True, False,
                           shadowed, findings)
            return
        if isinstance(node, ast.With):
            locked = in_lock or any(_lockish(item.context_expr)
                                    for item in node.items)
            for child in node.body:
                self._walk(ctx, child, containers, in_func, locked,
                           shadowed, findings)
            return
        if in_func and not in_lock:
            hit = self._mutation(node, containers - shadowed)
            if hit is not None:
                findings.append(Finding(
                    self.rule_id, ctx.rel, node.lineno,
                    f"module-level container `{hit}` mutated outside a "
                    "lock — guard with `with <lock>:` or document the "
                    "tolerance: `# ccsa: ok[CCSA007] <why unsynchronized "
                    "access is safe here>`"))
        for child in ast.iter_child_nodes(node):
            self._walk(ctx, child, containers, in_func, in_lock, shadowed,
                       findings)

    @staticmethod
    def _mutation(node: ast.AST, containers: set[str]) -> str | None:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in containers:
            return node.func.value.id
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, ast.Assign) else (
                [node.target] if isinstance(node, ast.AugAssign)
                else node.targets)
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in containers:
                    return t.value.id
        return None
