"""CCSA004: wall-clock and ``hash()`` determinism.

Two sub-checks with different scopes:

- In the **deterministic modules** (the digital twin, the chaos harness,
  the flight recorder — everything whose replay/scoring contract is
  "same seed ⇒ byte-identical output", PR 6): calling ``time.*`` clock
  functions, ``datetime.now``-family constructors, or anything off the
  ``random`` module is banned. *References* stay legal — passing
  ``time.monotonic`` as a default argument IS the injection seam
  (``SimClock`` / ``RetryPolicy(clock=)`` discipline); *calling* it
  inline is the violation.
- **Repo-wide**: the builtin ``hash()`` is banned outside ``__hash__``
  methods. Its value changes per process under PYTHONHASHSEED for
  strings — PR 4 already converted one assignor from ``hash()`` to
  ``zlib.crc32`` after exactly this bit them. In-process-only uses are
  suppressible with that documented contract.
"""

from __future__ import annotations

import ast

from .core import Finding, FileContext, Rule, register


@register
class DeterminismRule(Rule):
    rule_id = "CCSA004"
    title = "wall-clock / hash() in deterministic modules"

    #: Modules under the byte-identical-replay contract.
    DETERMINISTIC_MODULES = (
        "cruise_control_tpu/testing/simulator.py",
        "cruise_control_tpu/testing/chaos.py",
        "cruise_control_tpu/utils/flight_recorder.py",
        # Futures engine (round 15): sampled scenarios are pure in
        # (template, seed) and ranked score JSON is byte-identical per
        # request — the serving contract, not just a test convenience.
        "cruise_control_tpu/futures/generator.py",
        "cruise_control_tpu/futures/evaluator.py",
        # Heal ledger (round 16): chains stamp every phase from the
        # injectable clock seam — a wall-clock call here would desync
        # the twin's cross-validation (ledger durations must equal
        # ScenarioScore time-to-heal on the sim clock).
        "cruise_control_tpu/utils/heal_ledger.py",
        # Always-hot solver (round 18): warm seeds feed SOLVER INPUTS —
        # seeding/validity/fallback must be pure functions of model
        # state (no age-based staleness); the prewarm manager times
        # itself through the injectable ``monotonic`` seam only.
        "cruise_control_tpu/warmstart.py",
        # Predictive rebalancing (round 19): the projection feeds solver
        # inputs and anomaly decisions — the fit must be a pure function
        # of the history tensor (byte-identical twin replays depend on
        # it), and the detector's deadlines ride the injected clock.
        "cruise_control_tpu/forecast/forecaster.py",
        "cruise_control_tpu/forecast/engine.py",
        "cruise_control_tpu/detector/predictive.py",
        # Serving front door (round 20): the loadgen arrival schedule is
        # a pure function of the seed (byte-identical, digest-pinned in
        # bench_baseline.json); the task engine, response cache, and
        # admission controller time themselves only through injected
        # ``monotonic`` seams — an inline clock call in any of them
        # would desync replayed load tests and cache-identity canaries.
        "cruise_control_tpu/serving/tasks.py",
        "cruise_control_tpu/serving/cache.py",
        "cruise_control_tpu/serving/admission.py",
        "cruise_control_tpu/serving/loadgen.py",
        # Sparse transport plan (round 21): the fractional-target
        # rounding draws its uniforms from the crc32-seeded splitmix
        # hash ONLY (sparse_rounding_seed + _hash_uniform) — a global
        # `random` call anywhere in the kernel module would break the
        # byte-identical replan/replay contract (CCSA004 fixture:
        # tests/fixtures/ccsa/bad_direct.py), and an inline clock call
        # would do it through compile-time constant folding. The host
        # driver's flight-telemetry timing is the one documented
        # suppression.
        "cruise_control_tpu/analyzer/direct.py",
        # Journeys + SLO engine (round 18/observability): journey
        # segments and SLO window events stamp from injected
        # monotonic/clock seams only — the twin replays both on the sim
        # clock, and the burn detector's multi-window verdicts must be
        # byte-identical per seed.
        "cruise_control_tpu/serving/journey.py",
        "cruise_control_tpu/utils/slo.py",
        "cruise_control_tpu/detector/slo_burn.py",
        # Red-team miner (round 22): the whole search — sampling,
        # mutation, tie-breaks, frontier order — is crc32-derived from
        # the sweep seed (one seed ⇒ byte-identical frontier JSON), and
        # the wall budget rides the caller-injected ``clock`` callable
        # only. An inline clock or `random` call anywhere here would
        # silently fork the committed regression frontier.
        "cruise_control_tpu/redteam/miner.py",
        "cruise_control_tpu/redteam/frontier.py",
        "cruise_control_tpu/redteam/blindspot.py",
    )

    CLOCK_CALLS = ("time.time", "time.time_ns", "time.monotonic",
                   "time.monotonic_ns", "time.perf_counter",
                   "time.perf_counter_ns", "time.localtime", "time.gmtime",
                   "datetime.now", "datetime.utcnow", "datetime.today",
                   "datetime.datetime.now", "datetime.datetime.utcnow",
                   "datetime.datetime.today", "datetime.date.today")

    def check_file(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        deterministic = ctx.rel in self.DETERMINISTIC_MODULES
        aliases = self._module_aliases(ctx.tree)
        hash_exempt_ranges = self._hash_exempt_ranges(ctx.tree)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.dotted(node.func)
            if name is None:
                continue
            norm = self._normalize(name, aliases)
            if deterministic:
                if norm in self.CLOCK_CALLS:
                    findings.append(Finding(
                        self.rule_id, ctx.rel, node.lineno,
                        f"`{norm}()` called in a deterministic module — "
                        "inject the clock (pass the function, call the "
                        "parameter: the SimClock seam) so same seed stays "
                        "byte-identical"))
                elif norm.startswith("random."):
                    findings.append(Finding(
                        self.rule_id, ctx.rel, node.lineno,
                        f"`{norm}()` in a deterministic module — use "
                        "crc32-seeded derivation (testing.chaos pattern), "
                        "never the global `random` state"))
            if norm == "hash" and isinstance(node.func, ast.Name) \
                    and not self._in_ranges(node.lineno, hash_exempt_ranges):
                findings.append(Finding(
                    self.rule_id, ctx.rel, node.lineno,
                    "builtin `hash()` is PYTHONHASHSEED-randomized for "
                    "strings — use `zlib.crc32` for anything compared, "
                    "persisted, or replayed across processes (PR 4's "
                    "assignor fix); in-process-only uses need "
                    "`# ccsa: ok[CCSA004] <in-process contract>`"))
        return findings

    @staticmethod
    def _module_aliases(tree: ast.Module) -> dict[str, str]:
        """``import time as _t`` → {'_t': 'time'} so aliasing can't dodge
        the ban."""
        out: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module in ("time", "datetime", "random"):
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    @staticmethod
    def _normalize(name: str, aliases: dict[str, str]) -> str:
        head, _, rest = name.partition(".")
        mapped = aliases.get(head)
        if mapped is None:
            return name
        return f"{mapped}.{rest}" if rest else mapped

    @staticmethod
    def _hash_exempt_ranges(tree: ast.Module) -> list[tuple[int, int]]:
        """Line ranges of ``__hash__`` methods — in-process identity is
        the one place builtin ``hash()`` is the right tool."""
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == "__hash__":
                out.append((node.lineno, node.end_lineno or node.lineno))
        return out

    @staticmethod
    def _in_ranges(line: int, ranges: list[tuple[int, int]]) -> bool:
        return any(lo <= line <= hi for lo, hi in ranges)
