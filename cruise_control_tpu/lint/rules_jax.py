"""CCSA001-003: the jax-side invariants — host-sync discipline in the
megastep pump, donation-set exactness, and trace-time purity.

Each of these encodes a contract a prior PR paid for:

- CCSA001: ``run_bounded_pass`` keeps one dispatch in flight; a blocking
  host readback (``float()``/``int()``/``bool()``/``.item()``/
  ``np.asarray``/``.tolist()`` on a device value) inside the pump region
  stalls the pipeline exactly where the overlap is earned, and —
  because AdaptiveDispatch costs dispatches as readback-to-readback
  deltas — double-bills the predecessor's execution into the next
  observation (chain.py's staleness contract, PR 5).
- CCSA002: the donated megastep kernels may donate ONLY the mutable set
  ``{assignment, leader_slot}`` (``strip_mutable``): every other tensor
  is topology, shared across generations by the incremental model
  pipeline's cache — donating a shared buffer lets XLA delete it under
  the cache's feet (model/refresh.py, PR 5).
- CCSA003: functions traced by ``lax.while_loop``/``scan``/``cond``/
  ``switch`` run ONCE at trace time; Python mutation of enclosing state
  inside them happens once per compilation, not once per round — the
  silent-wrong-answer class.
"""

from __future__ import annotations

import ast

from .core import Finding, FileContext, Rule, register

# -- shared donation helpers -------------------------------------------------

#: The exact mutable set of the split state (chain.strip_mutable): the two
#: tensors the search rewrites. Everything else is topology.
MUTABLE_SET = ("assignment", "leader_slot")


def _donate_argnums_of(call: ast.Call) -> ast.expr | None:
    """The ``donate_argnums=`` value of a ``jax.jit(...)`` /
    ``partial(jax.jit, ...)`` call expression, else None."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            return kw.value
    return None


def _const_argnums(value: ast.expr) -> list[object] | None:
    """Literal argnums as a list, or None when not statically resolvable."""
    if isinstance(value, ast.Constant):
        return [value.value]
    if isinstance(value, (ast.Tuple, ast.List)):
        out = []
        for elt in value.elts:
            if not isinstance(elt, ast.Constant):
                return None
            out.append(elt.value)
        return out
    return None


def _positional_params(func: ast.FunctionDef) -> list[str]:
    a = func.args
    return [arg.arg for arg in a.posonlyargs + a.args]


def _is_jit_call(call: ast.Call) -> bool:
    name = Rule.dotted(call.func) or ""
    if name in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return True
    # functools.partial(jax.jit, ...) decorator form
    if name.endswith("partial") and call.args:
        inner = Rule.dotted(call.args[0]) or ""
        return inner in ("jax.jit", "jit", "pjit", "jax.pjit")
    return False


@register
class HostSyncInPumpRule(Rule):
    """CCSA001: no host synchronization inside the async pump or the
    donated chain drivers."""

    rule_id = "CCSA001"
    title = "host-sync leak in the megastep pump / donated drivers"

    #: Files containing the pump machinery. The rule is repo-specific by
    #: design — these are the modules that own the one-behind dispatch
    #: pipelines (single-cluster, sharded, and the fleet megabatch) plus
    #: the direct-assignment transport kernels (round 17: its donated
    #: jits are detected structurally, and any host sync traced into a
    #: sweep body would be a silent per-compile constant). The round-21
    #: sparse-plan kernels ride the same set: the fractional/rounding
    #: planes live in analyzer/direct.py and the mesh rank_stride twins
    #: in parallel/chain_sharded.py — both already pump files, so their
    #: donated forms are regions from the moment they are written.
    PUMP_FILES = ("cruise_control_tpu/analyzer/chain.py",
                  "cruise_control_tpu/analyzer/direct.py",
                  "cruise_control_tpu/parallel/chain_sharded.py",
                  "cruise_control_tpu/fleet/megabatch.py")
    #: Region functions: the pumps themselves, their per-dispatch
    #: ``enqueue`` closures (the megabatch's batched enqueues share the
    #: name, so they are covered structurally), and the async-readback
    #: decode helpers. Donated-jit kernels are detected structurally on
    #: top of this set.
    REGION_FUNCS = ("run_bounded_pass", "run_megabatch_pass", "enqueue",
                    "_chain_infos_from_stats")

    SYNC_BUILTINS = ("float", "int", "bool")
    SYNC_METHODS = ("item", "tolist")
    SYNC_DOTTED = ("np.asarray", "numpy.asarray", "onp.asarray",
                   "jax.device_get")

    def _is_region(self, func: ast.FunctionDef) -> bool:
        if func.name in self.REGION_FUNCS:
            return True
        for dec in func.decorator_list:
            if isinstance(dec, ast.Call) and _donate_argnums_of(dec) \
                    is not None:
                return True
        return False

    def check_file(self, ctx: FileContext) -> list[Finding]:
        if ctx.rel not in self.PUMP_FILES:
            return []
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not self._is_region(node):
                continue
            # Walk this region's OWN subtree, skipping nested functions
            # that are themselves regions — they are visited in their own
            # right, so one violation never reports twice.
            stack: list = list(node.body)
            while stack:
                sub = stack.pop()
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and self._is_region(sub):
                    continue
                if isinstance(sub, ast.Call):
                    hit = self._sync_kind(sub)
                    if hit is not None:
                        findings.append(Finding(
                            self.rule_id, ctx.rel, sub.lineno,
                            f"`{hit}` in pump region `{node.name}` blocks "
                            "on a device value — stalls the one-behind "
                            "pipeline and double-bills AdaptiveDispatch "
                            "(annotate intentional readbacks: "
                            "`# ccsa: ok[CCSA001] <why here>`)"))
                stack.extend(ast.iter_child_nodes(sub))
        return findings

    def _sync_kind(self, call: ast.Call) -> str | None:
        name = self.dotted(call.func)
        if name in self.SYNC_DOTTED:
            return name
        if name in self.SYNC_BUILTINS and len(call.args) == 1 \
                and not call.keywords \
                and not isinstance(call.args[0], ast.Constant):
            return f"{name}()"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in self.SYNC_METHODS \
                and not call.args and not call.keywords:
            return f".{call.func.attr}()"
        return None


@register
class DonationSetRule(Rule):
    """CCSA002: ``donate_argnums`` may only donate the mutable set."""

    rule_id = "CCSA002"
    title = "donation outside the strip_mutable mutable set"

    def check_file(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        defs_by_name: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                defs_by_name.setdefault(node.name, []).append(node)

        decorator_calls: set[int] = set()
        for node in ast.walk(ctx.tree):
            # Decorator form: @partial(jax.jit, donate_argnums=...) /
            # @jax.jit(donate_argnums=...) above a def. The argnums index
            # the DECORATED function's positional parameters.
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and _is_jit_call(dec):
                        decorator_calls.add(id(dec))
                        val = _donate_argnums_of(dec)
                        if val is not None:
                            findings.extend(self._verify(
                                ctx, dec, val, _positional_params(node),
                                node.name))
            # Call form: jax.jit(fn_or_shard_map(fn), donate_argnums=...).
            elif isinstance(node, ast.Call) and _is_jit_call(node) \
                    and id(node) not in decorator_calls:
                val = _donate_argnums_of(node)
                if val is None:
                    continue
                params, label = self._resolve_call_target(node, defs_by_name)
                findings.extend(self._verify(ctx, node, val, params, label))
        return findings

    def _resolve_call_target(self, call: ast.Call,
                             defs_by_name: dict[str, list[ast.FunctionDef]],
                             ) -> tuple[list[str] | None, str]:
        """Positional params of the function a jit call wraps. Unwraps
        transform layers (``jax.jit(shard_map(body, ...), ...)``,
        ``jax.jit(jax.vmap(body), ...)``, and stacks thereof — the
        megabatch kernels resolve their donation set THROUGH vmap, which
        maps each donated argument to the same-position parameter of the
        batched body)."""
        target = call.args[0] if call.args else None
        seen = 0
        while isinstance(target, ast.Call) and target.args and seen < 8:
            target = target.args[0]   # vmap(body)/shard_map(body) -> body
            seen += 1
        if isinstance(target, ast.Name):
            cands = defs_by_name.get(target.id, [])
            if len(cands) == 1:
                return _positional_params(cands[0]), target.id
            return None, target.id
        if isinstance(target, ast.Lambda):
            a = target.args
            return [x.arg for x in a.posonlyargs + a.args], "<lambda>"
        return None, self.dotted(target) or "<expr>"

    def _verify(self, ctx: FileContext, at: ast.AST, val: ast.expr,
                params: list[str] | None, label: str) -> list[Finding]:
        nums = _const_argnums(val)
        if nums is None:
            return [Finding(
                self.rule_id, ctx.rel, at.lineno,
                f"donate_argnums of `{label}` is not a literal — the "
                "donation set cannot be verified against the mutable set "
                f"{set(MUTABLE_SET)}")]
        donated: list[str] = []
        for n in nums:
            if isinstance(n, str):
                donated.append(n)     # donate_argnames
            elif isinstance(n, int) and params is not None:
                donated.append(params[n] if n < len(params)
                               else f"<argnum {n}>")
            elif params is None:
                return [Finding(
                    self.rule_id, ctx.rel, at.lineno,
                    f"cannot resolve the function `{label}` donates into "
                    "— donation set unverifiable (donate via a local "
                    "`def` so ccsa can map argnums to parameter names)")]
        bad = [d for d in donated if d not in MUTABLE_SET]
        if not bad:
            return []
        return [Finding(
            self.rule_id, ctx.rel, at.lineno,
            f"`{label}` donates {bad} — only the strip_mutable mutable "
            f"set {set(MUTABLE_SET)} may be donated; topology tensors "
            "are shared across generations by the refresh cache "
            "(model/refresh.py) and a donated shared buffer is deleted "
            "under the cache's feet")]


@register
class TraceTimeSideEffectRule(Rule):
    """CCSA003: no Python mutation of enclosing state inside ``lax``
    body functions."""

    rule_id = "CCSA003"
    title = "trace-time side effect inside a lax body function"

    MUTATORS = ("append", "extend", "add", "update", "insert", "pop",
                "popitem", "remove", "discard", "clear", "setdefault",
                "appendleft", "extendleft")
    _OPS = {"while_loop": (0, 1), "scan": (0,), "cond": (1, 2),
            "fori_loop": (2,)}

    def check_file(self, ctx: FileContext) -> list[Finding]:
        lax_names = self._lax_imports(ctx.tree)
        defs_by_name: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                defs_by_name.setdefault(node.name, []).append(node)

        findings: list[Finding] = []
        seen: set[int] = set()
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            op = self._lax_op(call, lax_names)
            if op is None:
                continue
            bodies: list[ast.AST] = []
            if op == "switch":
                if len(call.args) >= 2 and isinstance(
                        call.args[1], (ast.List, ast.Tuple)):
                    bodies.extend(call.args[1].elts)
            else:
                for idx in self._OPS[op]:
                    if idx < len(call.args):
                        bodies.append(call.args[idx])
            for body in bodies:
                fn = self._resolve(body, defs_by_name)
                if fn is None or id(fn) in seen:
                    continue
                seen.add(id(fn))
                findings.extend(self._check_body(ctx, fn, op))
        return findings

    @staticmethod
    def _lax_imports(tree: ast.Module) -> set[str]:
        names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) \
                    and (node.module or "").endswith("lax"):
                names.update(a.asname or a.name for a in node.names)
        return names

    def _lax_op(self, call: ast.Call, lax_names: set[str]) -> str | None:
        name = self.dotted(call.func)
        if name is None:
            return None
        head, _, last = name.rpartition(".")
        if last not in self._OPS and last != "switch":
            return None
        if head.endswith("lax") or (not head and name in lax_names):
            return last
        return None

    @staticmethod
    def _resolve(body: ast.AST,
                 defs_by_name: dict[str, list[ast.FunctionDef]],
                 ) -> ast.AST | None:
        if isinstance(body, ast.Lambda):
            return body
        if isinstance(body, ast.Name):
            cands = defs_by_name.get(body.id, [])
            if len(cands) == 1:
                return cands[0]
        # Calls producing bodies (e.g. branch(i) factories) and foreign
        # references are out of reach for a single-file walk.
        return None

    def _check_body(self, ctx: FileContext, fn: ast.AST,
                    op: str) -> list[Finding]:
        findings: list[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            findings.append(Finding(
                self.rule_id, ctx.rel, node.lineno,
                f"{what} inside a `lax.{op}` body function runs ONCE at "
                "trace time, not once per iteration — thread it through "
                "the carry instead (silent-wrong-answer class)"))

        def check_scope(scope: ast.AST, bound: frozenset) -> None:
            """Per-scope walk: ``bound`` accumulates names local to this
            scope or an enclosing one INSIDE the traced body — a nested
            helper's own bindings never leak outward, so a name it
            rebinds stays free (and flaggable) in the outer scope."""
            bound = bound | self.own_assigned_names(scope)
            stack = list(scope.body) if not isinstance(scope, ast.Lambda) \
                else [scope.body]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    check_scope(node, bound)
                    continue
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    flag(node, f"`{type(node).__name__.lower()}` rebinding")
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in self.MUTATORS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id not in bound:
                    flag(node, f"mutation `{node.func.value.id}"
                               f".{node.func.attr}(...)` of enclosing "
                               "state")
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, (ast.Subscript, ast.Attribute)) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id not in bound:
                            flag(node, f"write through enclosing name "
                                       f"`{t.value.id}`")
                stack.extend(ast.iter_child_nodes(node))
            return None

        check_scope(fn, frozenset())
        return findings
