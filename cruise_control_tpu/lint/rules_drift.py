"""CCSA005/006: config-key and sensor-name drift.

Both rules reuse ``tools/gen_docs.py`` — the same registry walk that
GENERATES docs/CONFIGURATION.md and docs/SENSORS.md also verifies them,
so the docs cannot drift from the code without failing lint (previously
they just rotted silently until someone re-ran the generator).

- CCSA005 (file part): every dotted-key string literal passed to a
  config getter (``cfg.get("a.b.c")``, ``get_int``, …) must be declared
  in the ConfigDef registry. An undeclared literal is either a typo'd
  key (returns the None/default silently) or a key someone forgot to
  register + document. Lookups into EXTERNAL key spaces (Kafka
  topic/broker configs share the dotted style) are suppressible with
  that stated contract.
- CCSA005 (tree part): regenerated CONFIGURATION.md must equal the
  committed file.
- CCSA006 (tree part): the sensor-name walk must match docs/SENSORS.md
  in both directions — every registered sensor documented, every
  documented sensor still registered — plus the full-text staleness
  check.
"""

from __future__ import annotations

import ast
import functools
import importlib.util
import pathlib
import re
from typing import Sequence

from .core import Finding, FileContext, Rule, register

_KEY_RE = re.compile(r"^[a-z][a-z0-9]*(\.[a-z0-9]+)+$")
_GETTERS = ("get", "get_int", "get_long", "get_double", "get_boolean",
            "get_string", "get_list", "get_configured_instance",
            "get_configured_instances")


@functools.lru_cache(maxsize=4)
def _load_gen_docs(root: pathlib.Path):
    """Import tools/gen_docs.py by path (works regardless of whether
    ``tools`` is importable as a package from the caller's sys.path).
    Cached per root: CCSA005 and CCSA006 share one module exec per
    process instead of re-executing it per rule."""
    path = root / "tools" / "gen_docs.py"
    if not path.exists():
        return None
    spec = importlib.util.spec_from_file_location("_ccsa_gen_docs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _declared_keys() -> set[str]:
    from ..config.cruise_control_config import _DEFINITION
    return set(_DEFINITION.names)


@register
class ConfigKeyDriftRule(Rule):
    rule_id = "CCSA005"
    title = "config-key drift (undeclared keys / stale CONFIGURATION.md)"

    def check_file(self, ctx: FileContext) -> list[Finding]:
        used: list[tuple[str, int]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _GETTERS and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str) \
                        and _KEY_RE.match(a0.value):
                    used.append((a0.value, node.lineno))
        if not used:
            return []
        declared = _declared_keys()
        return [Finding(
            self.rule_id, ctx.rel, line,
            f"config key `{key}` is not declared in "
            "config/cruise_control_config.py — declare it (and rerun "
            "tools/gen_docs.py), or mark an external key space: "
            "`# ccsa: ok[CCSA005] <whose key this is>`")
            for key, line in used if key not in declared]

    def check_tree(self, root: pathlib.Path,
                   ctxs: Sequence[FileContext]) -> list[Finding]:
        gen = _load_gen_docs(root)
        if gen is None:
            return []
        doc = root / "docs" / "CONFIGURATION.md"
        current = doc.read_text() if doc.exists() else ""
        expected = gen.gen_configuration()
        if current.strip() == expected.strip():
            return []
        return [Finding(
            self.rule_id, "docs/CONFIGURATION.md", 1,
            "stale: does not match the ConfigDef registry — run "
            "`python tools/gen_docs.py` and commit the result")]


@register
class SensorDriftRule(Rule):
    rule_id = "CCSA006"
    title = "sensor-name drift (code registrations vs docs/SENSORS.md)"

    _DOC_ROW = re.compile(r"^\|\s*`kafka_cruisecontrol_([a-z0-9_]+)`")

    def check_tree(self, root: pathlib.Path,
                   ctxs: Sequence[FileContext]) -> list[Finding]:
        gen = _load_gen_docs(root)
        if gen is None:
            return []
        doc = root / "docs" / "SENSORS.md"
        current = doc.read_text() if doc.exists() else ""
        expected = gen.gen_sensors()
        if current.strip() == expected.strip():
            return []

        documented = {m.group(1) for line in current.splitlines()
                      if (m := self._DOC_ROW.match(line.strip()))}
        registered = {m.group(1) for line in expected.splitlines()
                      if (m := self._DOC_ROW.match(line.strip()))}
        findings = [Finding(
            self.rule_id, "docs/SENSORS.md", 1,
            f"sensor `{name}` is registered in code but missing from "
            "docs/SENSORS.md — run `python tools/gen_docs.py`")
            for name in sorted(registered - documented)]
        findings += [Finding(
            self.rule_id, "docs/SENSORS.md", 1,
            f"documented sensor `{name}` is no longer registered anywhere "
            "— run `python tools/gen_docs.py`")
            for name in sorted(documented - registered)]
        if not findings:
            findings.append(Finding(
                self.rule_id, "docs/SENSORS.md", 1,
                "stale: text differs from the generated output — run "
                "`python tools/gen_docs.py` and commit the result"))
        return findings
