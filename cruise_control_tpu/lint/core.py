"""ccsa core: rule registry, per-file context, suppressions, baseline.

Design (mirrors the reference's checkstyle/spotbugs gate semantics):

- A **rule** is a class with a ``rule_id`` (``CCSA0xx``), a one-line
  ``title``, and either ``check_file(ctx)`` (runs per Python file) or
  ``check_tree(root, ctxs)`` (runs once per lint invocation — the doc
  drift rules). Rules register themselves via the ``@register``
  decorator at import time.
- A **suppression** is an inline comment ``# ccsa: ok[CCSA001] reason``
  on the finding's line or on a comment line directly above it. The
  reason is REQUIRED — a reasonless suppression does not suppress and
  additionally raises a CCSA000 meta finding, so every tolerance in the
  tree is documented where it lives. ``ok[CCSA001,CCSA007]`` covers
  several rules with one comment.
- The **baseline** is a committed JSON list of finding fingerprints
  (``.ccsa-baseline.json``): findings in it are reported but do not fail
  the gate, so the linter can land before the last legacy finding is
  fixed. The repo's bias is an EMPTY baseline — fix or suppress instead
  of baselining (ISSUE 9). Fingerprints hash the *normalized line text*,
  not the line number, so unrelated edits don't churn the baseline.

Everything here is stdlib-only; rules that need the config registry or
``tools/gen_docs.py`` import them lazily inside ``check_tree``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize
import zlib
from typing import Iterable, Sequence

#: Repo root derived from this file's location (…/cruise_control_tpu/lint/
#: core.py → two parents up). The CLI can override via --root.
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]

#: Directories never scanned (the ccsa fixture corpus is deliberately
#: violating — scanning it would make the tree red by construction).
EXCLUDED_DIR_PARTS = {"__pycache__", ".git", ".ccsa-fixtures"}
EXCLUDED_REL_PREFIXES = ("tests/fixtures/ccsa",)

#: Default scan targets — the same surface the pyflakes CI gate covers,
#: minus tests (fixture snippets there violate rules on purpose; the
#: test suite lints them explicitly with spoofed paths).
DEFAULT_PATHS = ("cruise_control_tpu", "tools", "bench.py",
                 "__graft_entry__.py")

DEFAULT_BASELINE = ".ccsa-baseline.json"

_SUPPRESS_RE = re.compile(
    r"#\s*ccsa:\s*ok\[\s*([A-Za-z0-9_,\s]+?)\s*\]\s*(.*?)\s*$")

META_RULE = "CCSA000"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # repo-relative posix path
    line: int           # 1-based; 0 = whole file / tree-level
    message: str
    suppressed: bool = False
    reason: str = ""    # the suppression reason when suppressed
    baselined: bool = False

    def with_status(self, *, suppressed: bool = False, reason: str = "",
                    baselined: bool = False) -> "Finding":
        return dataclasses.replace(self, suppressed=suppressed,
                                   reason=reason, baselined=baselined)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "suppressed": self.suppressed,
                "reason": self.reason, "baselined": self.baselined}


@dataclasses.dataclass(frozen=True)
class Suppression:
    path: str
    line: int
    rules: tuple[str, ...]
    reason: str


class FileContext:
    """One parsed Python file: source, AST, and its suppression map."""

    def __init__(self, path: pathlib.Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        # lineno -> {RULE: reason}; reason may be "" (invalid — see
        # suppression_for). Markers are located via REAL comment tokens,
        # not a regex over raw lines: a `# ccsa: ok[...]` inside a string
        # literal or docstring must neither suppress nor show up in
        # --list-suppressions.
        self.suppressions: dict[int, dict[str, str]] = {}
        for lineno, comment in self._comments(source):
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = tuple(r.strip().upper() for r in m.group(1).split(",")
                          if r.strip())
            reason = (m.group(2) or "").strip()
            self.suppressions[lineno] = {r: reason for r in rules}

    @staticmethod
    def _comments(source: str):
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError):
            return   # ast.parse succeeded, so this is effectively dead

    def _comment_only(self, lineno: int) -> bool:
        if not (1 <= lineno <= len(self.lines)):
            return False
        return self.lines[lineno - 1].lstrip().startswith("#")

    def suppression_for(self, line: int, rule: str) -> str | None:
        """The suppression reason covering ``rule`` at ``line``: on the
        line itself, or in the contiguous block of comment-only lines
        directly above it (so reasons may wrap over several comment
        lines — the ``# ccsa:`` marker line starts the block that
        counts). ``None`` when not suppressed; ``""`` when suppressed
        without a reason (invalid)."""
        entry = self.suppressions.get(line)
        if entry is not None and rule in entry:
            return entry[rule]
        cand = line - 1
        while self._comment_only(cand):
            entry = self.suppressions.get(cand)
            if entry is not None and rule in entry:
                return entry[rule]
            # A marker for a DIFFERENT rule doesn't end the walk: stacked
            # single-rule suppressions above one line all apply to it.
            cand -= 1
        return None

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Rule:
    """Base rule. Subclasses set ``rule_id``/``title`` and override one
    or both hooks. ``check_file`` findings are suppressible inline;
    ``check_tree`` findings (doc drift) are not — they point at
    generated files whose fix is regeneration, not annotation."""

    rule_id = "CCSA???"
    title = ""

    def check_file(self, ctx: FileContext) -> list[Finding]:
        return []

    def check_tree(self, root: pathlib.Path,
                   ctxs: Sequence[FileContext]) -> list[Finding]:
        return []

    # -- shared AST helpers -------------------------------------------------
    @staticmethod
    def dotted(node: ast.AST) -> str | None:
        """``a.b.c`` for a Name/Attribute chain, else None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    @staticmethod
    def own_assigned_names(func: ast.AST) -> set[str]:
        """Names bound in ``func``'s OWN scope (params, assignments,
        loop/with/comprehension targets) — bindings inside nested
        functions/lambdas do NOT leak out (Python scoping): a name a
        nested closure rebinds for itself must not count as shadowed in
        the enclosing function, or shadow-aware rules fail open."""
        names: set[str] = set()
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = func.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                names.add(arg.arg)
            stack = list(func.body) if not isinstance(func, ast.Lambda) \
                else [func.body]
        else:
            stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    names.add(node.name)   # the def itself binds its name
                continue                   # nested scope: do not descend
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)
            elif isinstance(node, ast.comprehension):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            stack.extend(ast.iter_child_nodes(node))
        return names


_REGISTRY: dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a rule by its id."""
    inst = cls()
    # ccsa: ok[CCSA007] import-time-only mutation: rule modules register
    # while this package imports, serialized by the interpreter's import
    # lock; the registry is read-only afterwards
    _REGISTRY[inst.rule_id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    return dict(sorted(_REGISTRY.items()))


# -- file collection --------------------------------------------------------

def _excluded(rel: str) -> bool:
    parts = rel.split("/")
    if any(p in EXCLUDED_DIR_PARTS for p in parts):
        return True
    return any(rel == pre or rel.startswith(pre + "/")
               for pre in EXCLUDED_REL_PREFIXES)


def collect_files(paths: Iterable[str | pathlib.Path],
                  root: pathlib.Path) -> list[pathlib.Path]:
    """Expand ``paths`` to .py files. The exclusion list applies only to
    directory EXPANSION — a path the caller names explicitly (or whose
    given root already sits inside an excluded prefix, e.g. the ccsa
    fixture corpus in the CI red-gate step) is always scanned."""
    out: list[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            forced = _excluded(_relpath(p, root))
            out.extend(sorted(
                f for f in p.rglob("*.py")
                if forced or not _excluded(_relpath(f, root))))
        elif p.suffix == ".py" and p.exists():
            out.append(p)
    uniq: list[pathlib.Path] = []
    seen: set[pathlib.Path] = set()
    for f in out:
        f = f.resolve()
        if f in seen:
            continue
        seen.add(f)
        uniq.append(f)
    return uniq


def _relpath(path: pathlib.Path, root: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


# -- baseline ---------------------------------------------------------------

def fingerprint(finding: Finding, line_text: str) -> str:
    """Stable id for baselining: rule + path + crc32 of the normalized
    line text. Line numbers deliberately excluded so edits elsewhere in
    the file don't churn the baseline; two identical lines in one file
    share a fingerprint (collapsing them in the baseline is acceptable —
    the baseline's target size is zero)."""
    norm = " ".join(line_text.split())
    return f"{finding.rule}:{finding.path}:{zlib.crc32(norm.encode()):08x}"


def load_baseline(path: pathlib.Path) -> set[str]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return set(data.get("fingerprints", []))


def write_baseline(path: pathlib.Path, fingerprints: Iterable[str]) -> None:
    path.write_text(json.dumps(
        {"comment": "ccsa accepted-finding fingerprints — keep EMPTY; "
                    "fix or `# ccsa: ok[RULE] reason`-suppress instead "
                    "(docs/STATIC_ANALYSIS.md)",
         "fingerprints": sorted(set(fingerprints))}, indent=2) + "\n")


# -- runner -----------------------------------------------------------------

@dataclasses.dataclass
class LintResult:
    new: list[Finding]
    baselined: list[Finding]
    suppressed: list[Finding]
    errors: list[Finding]       # CCSA000 meta findings (always gate-failing)
    files_scanned: int
    #: The parsed contexts of the run (path-keyed consumers — baseline
    #: writing — reuse these instead of re-collecting + re-parsing).
    contexts: list = dataclasses.field(default_factory=list)

    @property
    def failed(self) -> bool:
        return bool(self.new or self.errors)

    def counts(self) -> dict[str, dict[str, int]]:
        table: dict[str, dict[str, int]] = {}
        for bucket, items in (("new", self.new + self.errors),
                              ("baselined", self.baselined),
                              ("suppressed", self.suppressed)):
            for f in items:
                row = table.setdefault(
                    f.rule, {"new": 0, "baselined": 0, "suppressed": 0})
                row[bucket] += 1
        return dict(sorted(table.items()))


def iter_suppressions(ctxs: Sequence[FileContext]) -> list[Suppression]:
    """Every inline suppression in the scanned tree — the machine-readable
    registry of documented tolerances (``--list-suppressions``)."""
    out: list[Suppression] = []
    for ctx in ctxs:
        for line, entry in sorted(ctx.suppressions.items()):
            reasons = set(entry.values())
            out.append(Suppression(ctx.rel, line, tuple(sorted(entry)),
                                   next(iter(reasons)) if reasons else ""))
    return out


def build_contexts(files: Sequence[pathlib.Path], root: pathlib.Path,
                   ) -> tuple[list[FileContext], list[Finding]]:
    ctxs: list[FileContext] = []
    errors: list[Finding] = []
    for f in files:
        rel = _relpath(f, root)
        try:
            source = f.read_text()
        except OSError as exc:
            errors.append(Finding(META_RULE, rel, 0, f"unreadable: {exc}"))
            continue
        try:
            ctxs.append(FileContext(f, rel, source))
        except SyntaxError as exc:
            errors.append(Finding(META_RULE, rel, exc.lineno or 0,
                                  f"syntax error: {exc.msg}"))
    return ctxs, errors


def run_lint(paths: Sequence[str | pathlib.Path] | None = None,
             root: pathlib.Path | None = None,
             rules: Sequence[str] | None = None,
             baseline: set[str] | None = None) -> LintResult:
    """Run the gate. ``rules`` filters by id (None = all); ``baseline``
    is the accepted-fingerprint set (None = empty)."""
    root = (root or REPO_ROOT).resolve()
    errors: list[Finding] = []
    files: list[pathlib.Path] = []
    for p in (paths or DEFAULT_PATHS):
        matched = collect_files([p], root)
        if not matched:
            # A typo'd path silently expanding to zero files would make
            # the gate pass vacuously — that is a gate failure, not a
            # clean run.
            errors.append(Finding(META_RULE, str(p), 0,
                                  "path matched no Python files"))
        files.extend(matched)
    files = list(dict.fromkeys(files))   # overlapping paths: scan once
    ctxs, ctx_errors = build_contexts(files, root)
    errors.extend(ctx_errors)
    baseline = baseline or set()
    active = all_rules()
    if rules is not None:
        wanted = {r.upper() for r in rules}
        unknown = wanted - set(active)
        for r in sorted(unknown):
            errors.append(Finding(META_RULE, "", 0, f"unknown rule: {r}"))
        active = {k: v for k, v in active.items() if k in wanted}

    raw: list[Finding] = []
    ctx_by_rel = {c.rel: c for c in ctxs}
    for rule in active.values():
        for ctx in ctxs:
            raw.extend(rule.check_file(ctx))
        raw.extend(rule.check_tree(root, ctxs))

    new: list[Finding] = []
    baselined: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        ctx = ctx_by_rel.get(f.path)
        reason = ctx.suppression_for(f.line, f.rule) if ctx else None
        if reason is not None:
            if not reason:
                errors.append(Finding(
                    META_RULE, f.path, f.line,
                    f"suppression for {f.rule} has no reason — "
                    "`# ccsa: ok[RULE] <why this is safe>` is required"))
                new.append(f)
            else:
                suppressed.append(f.with_status(suppressed=True,
                                                reason=reason))
            continue
        line_text = ctx.line_text(f.line) if ctx else ""
        if fingerprint(f, line_text) in baseline:
            baselined.append(f.with_status(baselined=True))
        else:
            new.append(f)

    order = (lambda f: (f.path, f.line, f.rule))
    return LintResult(sorted(new, key=order), sorted(baselined, key=order),
                      sorted(suppressed, key=order), errors, len(ctxs),
                      contexts=ctxs)
