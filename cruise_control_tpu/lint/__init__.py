"""ccsa — the cruise-control-tpu static-analysis gate.

The reference gates every build on spotbugs + checkstyle before the test
suite (build.gradle:83-132); this package is the analogous gate for the
invariants THIS repo has paid for the hard way: donation-set exactness
(PR 5), host-sync discipline in the async pump (PR 5), trace-time purity
of ``lax`` body functions, wall-clock-free determinism in the digital
twin (PR 6) and the PYTHONHASHSEED rule (PR 4), config-key / sensor-name
doc drift (tools/gen_docs.py), and lock discipline on module-level
shared state.

Pure-stdlib ``ast`` walking — importing this package never imports jax.
The doc-drift tree rules import the (stdlib-only) config registry and
``tools/gen_docs.py`` lazily when they run.

CLI: ``python -m tools.ccsa`` (see docs/STATIC_ANALYSIS.md).
"""

from .core import (  # noqa: F401
    Finding, FileContext, LintResult, Rule, all_rules, build_contexts,
    collect_files, iter_suppressions, load_baseline, run_lint,
    write_baseline,
)

# Importing the rule modules registers every rule with the core registry.
from . import rules_jax  # noqa: F401
from . import rules_determinism  # noqa: F401
from . import rules_drift  # noqa: F401
from . import rules_concurrency  # noqa: F401
