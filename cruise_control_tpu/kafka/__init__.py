"""Real-Kafka bindings (import-gated — no Kafka client ships in every
environment).

The framework's external boundaries are protocols with in-memory
implementations used by tests and the demo mode:

- ``executor.admin.AdminBackend``      ← ``KafkaAdminBackend`` (here)
- ``monitor.sampling.MetricsTransport`` ← ``KafkaMetricsTransport`` (here)
- ``monitor.sampling.SampleStore``      ← ``KafkaSampleStore`` (here)

This package implements those protocols over ``kafka-python``
(KafkaAdminClient / KafkaConsumer / KafkaProducer). Importing the package
always succeeds; constructing any binding without kafka-python installed
raises ``KafkaClientUnavailableError`` with install guidance. Reference
parity: executor/ExecutionUtils.java:433,483 (electLeaders /
alterPartitionReassignments), monitor/sampling/
CruiseControlMetricsReporterSampler.java (metrics-topic consumer),
monitor/sampling/KafkaSampleStore.java:94-204 (sample topics + replay).
"""

from __future__ import annotations

try:  # pragma: no cover - exercised only where kafka-python is installed
    import kafka  # noqa: F401  (kafka-python)
    HAVE_KAFKA = True
except ImportError:
    HAVE_KAFKA = False


class KafkaClientUnavailableError(ImportError):
    """kafka-python is not installed in this environment."""

    def __init__(self, what: str):
        super().__init__(
            f"{what} needs the kafka-python client "
            "(pip install kafka-python>=2.1); this environment has no "
            "Kafka client, so only the in-memory backends are available.")


def require_kafka(what: str) -> None:
    if not HAVE_KAFKA:
        raise KafkaClientUnavailableError(what)


from .admin import KafkaAdminBackend            # noqa: E402
from .sample_store import KafkaSampleStore      # noqa: E402
from .transport import KafkaMetricsTransport    # noqa: E402

__all__ = [
    "HAVE_KAFKA", "KafkaClientUnavailableError", "require_kafka",
    "KafkaAdminBackend", "KafkaMetricsTransport", "KafkaSampleStore",
]
