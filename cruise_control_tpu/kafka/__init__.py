"""Kafka bindings over the framework's OWN wire-protocol client.

The framework's external boundaries are protocols with in-memory
implementations used by unit tests and the demo mode:

- ``executor.admin.AdminBackend``       ← ``KafkaAdminBackend``
- ``monitor.sampling.MetricsTransport`` ← ``KafkaMetricsTransport``
- ``monitor.sampling.SampleStore``      ← ``KafkaSampleStore``

Unlike round 2 (which wrapped kafka-python and could only ever run where
that library was installed), these bindings speak the wire protocol
directly (``kafka.wire``) — zero external dependencies, and integration-
tested in every environment against the embedded wire-conformant broker
(``kafka.wire.broker.EmbeddedKafkaCluster``), the stand-in for the
reference's CCKafkaIntegrationTestHarness.

Reference parity: executor/ExecutionUtils.java:433,483 (electLeaders /
alterPartitionReassignments), monitor/sampling/
CruiseControlMetricsReporterSampler.java (metrics-topic consumer),
monitor/sampling/KafkaSampleStore.java:94-204 (sample topics + replay).
"""

from __future__ import annotations

# The client is self-contained; it is always available.
HAVE_KAFKA = True


class KafkaClientUnavailableError(ImportError):
    """Kept for API compatibility; never raised by the wire bindings."""


def require_kafka(what: str) -> None:  # pragma: no cover - compat shim
    return None


from .admin import KafkaAdminBackend            # noqa: E402
from .sample_store import KafkaSampleStore      # noqa: E402
from .transport import KafkaMetricsTransport    # noqa: E402
from .wire.client import WireClient             # noqa: E402

__all__ = [
    "HAVE_KAFKA", "KafkaClientUnavailableError", "require_kafka",
    "KafkaAdminBackend", "KafkaMetricsTransport", "KafkaSampleStore",
    "WireClient",
]
