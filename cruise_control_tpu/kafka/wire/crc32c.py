"""CRC-32C (Castagnoli) — the record-batch v2 checksum (KIP-98).

Python's ``zlib.crc32`` is CRC-32 (IEEE), not CRC-32C, so the polynomial
is implemented here: a C fast path compiled on first use (8-way
slicing-by-8 would be overkill; the simple table loop in C is ~20×
the pure-Python loop), with a table-driven pure-Python fallback when no
compiler is available.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile

LOG = logging.getLogger(__name__)

_POLY = 0x82F63B78  # reversed Castagnoli polynomial

_TABLE: list[int] = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)

_C_SRC = r"""
#include <stdint.h>
#include <stddef.h>

static uint32_t table[256];
static int init_done = 0;

static void init_table(void) {
    for (uint32_t n = 0; n < 256; n++) {
        uint32_t c = n;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        table[n] = c;
    }
    init_done = 1;
}

uint32_t cc_crc32c(uint32_t crc, const unsigned char *buf, size_t len) {
    if (!init_done) init_table();
    crc = ~crc;
    for (size_t i = 0; i < len; i++)
        crc = table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
    return ~crc;
}
"""

_clib = None
_clib_tried = False


def _load_native():
    """Compile + dlopen the C kernel once per interpreter; any failure
    (no compiler, read-only tmp) falls back to pure Python silently."""
    global _clib, _clib_tried
    if _clib_tried:
        return _clib
    _clib_tried = True
    try:
        # Per-user 0700 cache dir, ownership-verified before any dlopen: a
        # world-writable shared path would let another local user plant a
        # malicious .so under the predictable name.
        cache = os.path.join(tempfile.gettempdir(),
                             f"cc_tpu_native_{os.getuid()}")
        os.makedirs(cache, mode=0o700, exist_ok=True)
        st = os.stat(cache)
        if st.st_uid != os.getuid() or st.st_mode & 0o022:
            cache = tempfile.mkdtemp(prefix="cc_tpu_native_")
        so_path = os.path.join(cache, "libcccrc32c.so")
        if not os.path.exists(so_path):
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".c", dir=cache, delete=False) as f:
                f.write(_C_SRC)
                c_path = f.name
            subprocess.run(
                ["cc", "-O2", "-shared", "-fPIC", "-o", so_path, c_path],
                check=True, capture_output=True, timeout=60)
            os.unlink(c_path)
        lib = ctypes.CDLL(so_path)
        lib.cc_crc32c.restype = ctypes.c_uint32
        lib.cc_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_char_p,
                                  ctypes.c_size_t]
        _clib = lib
    except Exception:  # noqa: BLE001 — optional acceleration only
        LOG.debug("native crc32c unavailable; using pure-Python table",
                  exc_info=True)
        _clib = None
    return _clib


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load_native()
    if lib is not None:
        return lib.cc_crc32c(crc, data, len(data))
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF
