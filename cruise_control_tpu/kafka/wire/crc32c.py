"""CRC-32C (Castagnoli) — the record-batch v2 checksum (KIP-98).

Python's ``zlib.crc32`` is CRC-32 (IEEE), not CRC-32C, so the polynomial
is implemented here: the native runtime library's C kernel when available
(native/ccnative.c — shared with the record-batch index parser), with a
table-driven pure-Python fallback when no compiler is available.
"""

from __future__ import annotations

_POLY = 0x82F63B78  # reversed Castagnoli polynomial

_TABLE: list[int] = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    from ...native import lib

    handle = lib()
    if handle is not None:
        return handle.cc_crc32c(crc, data, len(data))
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF
