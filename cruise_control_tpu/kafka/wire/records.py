"""Record batch v2 (magic 2) serde — the on-wire/on-disk record format
since Kafka 0.11 (KIP-98).

Layout (all big-endian):

    baseOffset:          int64
    batchLength:         int32   (bytes after this field)
    partitionLeaderEpoch:int32
    magic:               int8    (= 2)
    crc:                 uint32  (CRC-32C of everything after this field)
    attributes:          int16   (compression in bits 0-2; 0 = none)
    lastOffsetDelta:     int32
    baseTimestamp:       int64
    maxTimestamp:        int64
    producerId:          int64   (-1 when idempotence unused)
    producerEpoch:       int16
    baseSequence:        int32
    recordCount:         int32
    records:             Record[recordCount]

Each Record is varint-framed:

    length:              varint  (bytes after this field)
    attributes:          int8
    timestampDelta:      varlong
    offsetDelta:         varint
    key:                 varint length (-1 = null) + bytes
    value:               varint length (-1 = null) + bytes
    headers:             varint count, each (varint-len key, varint-len value)

Compression is intentionally unsupported (attributes must be 0): the
framework's own topics are small JSON/binary payloads and the embedded
broker mirrors that — an unsupported codec raises instead of corrupting.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .crc32c import crc32c
from .types import VarInt

NO_PRODUCER_ID = -1
_HEADER_FMT = ">qiibIhiqqqhii"
_HEADER_SIZE = struct.calcsize(_HEADER_FMT)  # 61
_CRC_OFFSET = 8 + 4 + 4 + 1  # baseOffset + batchLength + leaderEpoch + magic
_AFTER_CRC = _CRC_OFFSET + 4
# Smallest legal batchLength: epoch+magic+crc (9) + the 40-byte after-crc
# fixed head (mirrors MIN_BATCH_LEN in native/ccnative.c).
_MIN_BATCH_LEN = 49


@dataclass
class Record:
    offset: int
    timestamp_ms: int
    key: bytes | None
    value: bytes | None
    headers: list[tuple[str, bytes]] = field(default_factory=list)


def _write_varbytes(out: bytearray, data: bytes | None) -> None:
    if data is None:
        VarInt.write(out, -1)
    else:
        VarInt.write(out, len(data))
        out += data


def _read_varbytes(buf: memoryview, pos: int):
    n, pos = VarInt.read(buf, pos)
    if n < 0:
        return None, pos
    return bytes(buf[pos:pos + n]), pos + n


def encode_batch(records: list[Record], base_offset: int | None = None,
                 partition_leader_epoch: int = 0) -> bytes:
    """Encode one batch; record offsets/timestamps are taken from the
    records themselves (base = first record)."""
    if not records:
        raise ValueError("empty record batch")
    base = records[0].offset if base_offset is None else base_offset
    base_ts = records[0].timestamp_ms
    max_ts = max(r.timestamp_ms for r in records)

    body = bytearray()
    for r in records:
        rec = bytearray()
        rec.append(0)  # record attributes (unused)
        VarInt.write(rec, r.timestamp_ms - base_ts)
        VarInt.write(rec, r.offset - base)
        _write_varbytes(rec, r.key)
        _write_varbytes(rec, r.value)
        VarInt.write(rec, len(r.headers))
        for hk, hv in r.headers:
            raw = hk.encode("utf-8")
            VarInt.write(rec, len(raw))
            rec += raw
            _write_varbytes(rec, hv)
        VarInt.write(body, len(rec))
        body += rec

    last_delta = records[-1].offset - base
    # after-crc section: attributes .. recordCount, then records
    after = struct.pack(">hiqqqhii", 0, last_delta, base_ts, max_ts,
                        NO_PRODUCER_ID, -1, -1, len(records)) + bytes(body)
    batch_length = 4 + 1 + 4 + len(after)  # leaderEpoch + magic + crc + rest
    head = struct.pack(">qiibI", base, batch_length, partition_leader_epoch,
                       2, crc32c(after))
    return head + after


def decode_batches(data: bytes | memoryview,
                   verify_crc: bool = True) -> list[Record]:
    """Decode a concatenation of record batches (a fetch response's record
    set); a trailing partial batch (broker-side truncation at the fetch
    byte limit) is dropped, matching client semantics.

    Fast path: the native index parser (native/ccnative.c) does the varint
    walk in one C pass; Python only slices spans out of the buffer. Falls
    back to the pure-Python walk below when the native library is
    unavailable. Both paths are fuzzed against each other
    (tests/test_native.py)."""
    from ...native import index_records, lib

    if lib() is not None:
        # The bytes copy (ctypes needs contiguous bytes) happens ONLY once
        # the library is known to be loadable — a compiler-less host must
        # not pay a full record-set copy just to fall through.
        raw = data if isinstance(data, bytes) else bytes(data)
        idx = index_records(raw, verify_crc)
    else:
        idx = None
    if idx is not None:
        out = []
        mv = memoryview(raw)
        for off, ts, koff, klen, voff, vlen, hoff, hcount in idx.tolist():
            key = raw[koff:koff + klen] if koff >= 0 else None
            value = raw[voff:voff + vlen] if voff >= 0 else None
            headers: list[tuple[str, bytes]] = []
            if hcount:
                hpos = hoff
                for _ in range(hcount):
                    hklen, hpos = VarInt.read(raw, hpos)
                    hk = raw[hpos:hpos + hklen].decode("utf-8")
                    hpos += hklen
                    hv, hpos = _read_varbytes(mv, hpos)
                    headers.append((hk, hv))
            out.append(Record(offset=off, timestamp_ms=ts, key=key,
                              value=value, headers=headers))
        return out
    buf = memoryview(data)
    out: list[Record] = []
    pos = 0
    while pos + 12 <= len(buf):
        base, batch_length = struct.unpack_from(">qi", buf, pos)
        end = pos + 12 + batch_length
        if batch_length >= 0 and end > len(buf):
            break  # partial trailing batch (fields untrusted — no checks)
        if batch_length < _MIN_BATCH_LEN:
            # Matches the native decoder's CC_ERR_MALFORMED for a complete
            # batch whose length cannot hold the fixed header (ADVICE r3:
            # the two decoders must agree on every input).
            raise ValueError(f"malformed record batch length {batch_length}")
        magic = buf[pos + 16]
        if magic != 2:
            raise ValueError(f"unsupported record-batch magic {magic}")
        try:
            (crc,) = struct.unpack_from(">I", buf, pos + _CRC_OFFSET)
            after = buf[pos + _AFTER_CRC:end]
            if verify_crc and crc32c(bytes(after)) != crc:
                raise ValueError(
                    f"record batch CRC mismatch at offset {base}")
            attrs, _last_delta, base_ts, _max_ts, _pid, _pep, _seq, count = \
                struct.unpack_from(">hiqqqhii", after, 0)
            if attrs & 0x07:
                raise ValueError(
                    f"unsupported compression codec {attrs & 0x07}")
            rpos = struct.calcsize(">hiqqqhii")
            for _ in range(count):
                length, rpos = VarInt.read(after, rpos)
                rend = rpos + length
                rpos += 1  # record attributes
                ts_delta, rpos = VarInt.read(after, rpos)
                off_delta, rpos = VarInt.read(after, rpos)
                key, rpos = _read_varbytes(after, rpos)
                value, rpos = _read_varbytes(after, rpos)
                n_headers, rpos = VarInt.read(after, rpos)
                headers = []
                for _ in range(n_headers):
                    klen, rpos = VarInt.read(after, rpos)
                    hk = bytes(after[rpos:rpos + klen]).decode("utf-8")
                    rpos += klen
                    hv, rpos = _read_varbytes(after, rpos)
                    headers.append((hk, hv))
                if rpos != rend:
                    raise ValueError("record length mismatch")
                out.append(Record(offset=base + off_delta,
                                  timestamp_ms=base_ts + ts_delta,
                                  key=key, value=value, headers=headers))
        except (IndexError, struct.error) as e:
            # A truncated varint / span in a malformed batch must surface
            # as the parser's error class, not an internal IndexError
            # (the native parser returns MALFORMED for the same inputs).
            raise ValueError(f"malformed record batch at offset {base}: "
                             f"{e}") from e
        pos = end
    return out
