"""Self-contained Kafka wire-protocol implementation.

The framework's own Kafka client — no external client library. The
reference links the official Java AdminClient/Consumer/Producer
(ExecutorAdminUtils.java, KafkaSampleStore.java:94,
CruiseControlMetricsReporterSampler.java); this environment has no Kafka
client at all, so the binding implements the protocol itself:

- ``types``    — primitive + composite codecs (incl. flexible/compact
                 encodings and tagged fields, KIP-482).
- ``records``  — record-batch v2 serde (varint records, CRC32C framing).
- ``messages`` — request/response schemas for the APIs the framework
                 uses (metadata, configs, reassignment, leader election,
                 log dirs, produce/fetch/list-offsets, create-topics).
- ``client``   — blocking client: connection pool, correlation,
                 metadata routing, produce/fetch/admin calls.
- ``broker``   — an EMBEDDED in-process broker speaking the same wire
                 format, the integration-test tier standing in for the
                 reference's CCKafkaIntegrationTestHarness (real sockets,
                 real bytes, no external processes).
"""

