"""Blocking wire client: connections, correlation, metadata routing.

A deliberately small synchronous client — the framework's Kafka traffic is
low-rate control-plane calls (admin ops, metric/sample topics), not a
streaming data plane, so one in-flight request per connection with
correlation-id verification is the right simplicity/safety trade-off.

Reference parity: the Java AdminClient/Producer/Consumer surface used by
ExecutorAdminUtils.java, CruiseControlMetricsReporter.java:241,
KafkaSampleStore.java:94-204 — collapsed to the calls the framework makes.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Iterable, Mapping, Sequence

from . import messages as m
from .records import Record, decode_batches, encode_batch
from .types import NullableString, TaggedFields, decode, encode

LOG = logging.getLogger(__name__)


class ConnectionError_(ConnectionError):
    pass


class BrokerConnection:
    """One TCP connection; thread-safe, one request in flight."""

    def __init__(self, host: str, port: int, client_id: str,
                 timeout_s: float = 30.0):
        self._addr = (host, port)
        self._client_id = client_id
        self._timeout = timeout_s
        self._sock: socket.socket | None = None
        self._correlation = 0
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self._addr, timeout=self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _read_exact(self, n: int) -> bytes:
        sock = self._sock
        assert sock is not None
        chunks = []
        while n:
            chunk = sock.recv(n)
            if not chunk:
                raise ConnectionError_(f"connection to {self._addr} closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def send(self, api: m.Api, body: dict) -> dict:
        with self._lock:
            self._correlation += 1
            corr = self._correlation
            # Request header v2 for flexible APIs, v1 otherwise.
            head = bytearray(struct.pack(">hhi", api.key, api.version, corr))
            NullableString.write(head, self._client_id)
            if api.flexible:
                TaggedFields.write(head, None)
            payload = bytes(head) + encode(api.request, body)
            try:
                sock = self._connect()
                sock.sendall(struct.pack(">i", len(payload)) + payload)
                (size,) = struct.unpack(">i", self._read_exact(4))
                frame = self._read_exact(size)
            except (OSError, ConnectionError) as e:
                self.close()
                raise ConnectionError_(
                    f"request to {self._addr} failed: {e}") from e
            (rcorr,) = struct.unpack_from(">i", frame, 0)
            if rcorr != corr:
                self.close()
                raise ConnectionError_(
                    f"correlation mismatch from {self._addr}: "
                    f"sent {corr}, got {rcorr}")
            pos = 4
            if api.flexible:  # response header v1 carries tagged fields
                _tags, pos = TaggedFields.read(memoryview(frame), pos)
            return decode(api.response, memoryview(frame)[pos:])

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


DEFAULT_PORT = 9092


def _parse_bootstrap(servers: str | Sequence[str]) -> list[tuple[str, int]]:
    if isinstance(servers, str):
        servers = [s for s in servers.split(",") if s.strip()]
    out = []
    for s in servers:
        host, sep, port = s.strip().rpartition(":")
        if not sep:
            out.append((s.strip(), DEFAULT_PORT))
            continue
        try:
            out.append((host or "localhost", int(port)))
        except ValueError:
            raise ValueError(
                f"malformed bootstrap server {s!r}: expected host[:port]"
            ) from None
    return out


class WireClient:
    """Cluster-level operations over per-broker connections."""

    def __init__(self, bootstrap_servers: str | Sequence[str],
                 client_id: str = "cruise-control-tpu",
                 timeout_s: float = 30.0, metadata_ttl_s: float = 5.0):
        self._bootstrap = _parse_bootstrap(bootstrap_servers)
        if not self._bootstrap:
            raise ValueError("empty bootstrap server list")
        self._client_id = client_id
        self._timeout = timeout_s
        self._meta_ttl = metadata_ttl_s
        self._conns: dict[int, BrokerConnection] = {}
        self._boot_conn: BrokerConnection | None = None
        self._brokers: dict[int, tuple[str, int]] = {}
        self._topic_meta: dict[str, tuple[float, dict[int, dict]]] = {}
        self._controller_id: int | None = None
        self._lock = threading.Lock()

    # ---- connection management -------------------------------------------
    def _bootstrap_connection(self) -> BrokerConnection:
        if self._boot_conn is None:
            errors = []
            # Configured servers first (short, operator-chosen), then the
            # brokers learned from metadata (they may outlive a stale
            # bootstrap list). Deduplicated; each connect pays the full
            # timeout, so the known list must not come first on a large
            # cluster full of unreachable nodes.
            candidates = list(dict.fromkeys(
                self._bootstrap + list(self._brokers.values())))
            for host, port in candidates:
                conn = BrokerConnection(host, port, self._client_id,
                                        self._timeout)
                try:
                    conn.send(m.API_VERSIONS, {})
                    self._boot_conn = conn
                    break
                except ConnectionError as e:  # try next server
                    errors.append(str(e))
            else:
                raise ConnectionError_(
                    f"no bootstrap server reachable: {errors}")
        return self._boot_conn

    def _boot_send(self, api: m.Api, body: dict) -> dict:
        """Send via the bootstrap connection, failing over across the
        server list once: a died bootstrap broker must not pin the client
        to a dead address while the rest of the cluster is healthy."""
        try:
            return self._bootstrap_connection().send(api, body)
        except ConnectionError:
            self._boot_conn = None
            return self._bootstrap_connection().send(api, body)

    def connection(self, node_id: int) -> BrokerConnection:
        with self._lock:
            conn = self._conns.get(node_id)
        if conn is not None:
            return conn
        if node_id not in self._brokers:
            self.metadata()
        if node_id not in self._brokers:
            raise ConnectionError_(f"unknown broker id {node_id}")
        host, port = self._brokers[node_id]
        conn = BrokerConnection(host, port, self._client_id, self._timeout)
        with self._lock:
            self._conns.setdefault(node_id, conn)
            return self._conns[node_id]

    def controller(self) -> BrokerConnection:
        if self._controller_id is None:
            self.metadata()
        assert self._controller_id is not None
        return self.connection(self._controller_id)

    def _controller_send(self, api: m.Api, body: dict) -> dict:
        """Send to the controller, re-resolving it once on NOT_CONTROLLER
        or a connection error: the controller moves on broker restart and
        the cached id must not wedge every admin call until some unrelated
        metadata refresh happens."""
        try:
            resp = self._controller_send_once(api, body)
        except ConnectionError:
            self._controller_id = None
            return self._controller_send_once(api, body)
        if resp.get("error_code") == m.NOT_CONTROLLER:
            self._controller_id = None
            return self._controller_send_once(api, body)
        return resp

    def _controller_send_once(self, api: m.Api, body: dict) -> dict:
        return self.controller().send(api, body)

    def close(self) -> None:
        with self._lock:
            for conn in self._conns.values():
                conn.close()
            self._conns.clear()
            if self._boot_conn is not None:
                self._boot_conn.close()
                self._boot_conn = None

    # ---- metadata --------------------------------------------------------
    def api_versions(self) -> dict[int, tuple[int, int]]:
        resp = self._boot_send(m.API_VERSIONS, {})
        return {e["api_key"]: (e["min_version"], e["max_version"])
                for e in resp["api_keys"]}

    def metadata(self, topics: Sequence[str] | None = None) -> dict:
        resp = self._boot_send(
            m.METADATA, {"topics": list(topics) if topics is not None
                         else None})
        self._brokers = {b["node_id"]: (b["host"], b["port"])
                         for b in resp["brokers"]}
        self._controller_id = resp["controller_id"]
        now = time.monotonic()
        for t in resp["topics"]:
            if t["error_code"] == m.NONE:
                self._topic_meta[t["name"]] = (
                    now, {p["index"]: p for p in t["partitions"]})
        return resp

    def alive_broker_ids(self) -> set[int]:
        self.metadata(topics=[])
        return set(self._brokers)

    def invalidate_topic(self, topic: str) -> None:
        self._topic_meta.pop(topic, None)

    def partitions_for(self, topic: str) -> dict[int, dict]:
        """Partition metadata, cached for ``metadata_ttl_s``: the data-plane
        hot paths (one fetch per batch per partition) must not pay a full
        Metadata round-trip each call. Stale leadership degrades to a
        NOT_LEADER error, which invalidates + retries (``_leader_call``)."""
        hit = self._topic_meta.get(topic)
        if hit is not None and time.monotonic() - hit[0] <= self._meta_ttl:
            return hit[1]
        meta = self.metadata([topic])
        for t in meta["topics"]:
            if t["name"] == topic:
                if t["error_code"] != m.NONE:
                    raise m.KafkaProtocolError(t["error_code"], topic)
                return {p["index"]: p for p in t["partitions"]}
        return {}

    def leader_of(self, topic: str, partition: int) -> int:
        parts = self.partitions_for(topic)
        if partition not in parts:
            raise m.KafkaProtocolError(m.UNKNOWN_TOPIC_OR_PARTITION,
                                       f"{topic}-{partition}")
        return parts[partition]["leader"]

    def _leader_call(self, topic: str, partition: int, call,
                     retry_conn_error: bool = True):
        """Run ``call(leader_connection)``; on stale-leadership (or, when
        ``retry_conn_error``, connection) errors, refresh the topic's
        metadata once and retry. Produce passes ``retry_conn_error=False``:
        a connection that died AFTER the broker committed the batch would
        make the blind re-send a duplicate append — the caller owns that
        at-least-once decision, not this helper."""
        try:
            return call(self.connection(self.leader_of(topic, partition)))
        except m.KafkaProtocolError as e:
            if e.code not in (m.NOT_LEADER_OR_FOLLOWER,
                              m.UNKNOWN_TOPIC_OR_PARTITION):
                raise
            self.invalidate_topic(topic)
        except ConnectionError:
            self.invalidate_topic(topic)
            if not retry_conn_error:
                raise
        return call(self.connection(self.leader_of(topic, partition)))

    # ---- admin -----------------------------------------------------------
    def create_topic(self, name: str, num_partitions: int,
                     replication_factor: int = 1,
                     configs: Mapping[str, str] | None = None,
                     error_ok: tuple[int, ...] = (m.TOPIC_ALREADY_EXISTS,),
                     ) -> int:
        body = {
            "topics": [{"name": name, "num_partitions": num_partitions,
                        "replication_factor": replication_factor,
                        "assignments": [],
                        "configs": [{"name": k, "value": v}
                                    for k, v in (configs or {}).items()]}],
            "timeout_ms": int(self._timeout * 1000)}
        resp = self._controller_send(m.CREATE_TOPICS, body)
        code = resp["topics"][0]["error_code"]
        if code == m.NOT_CONTROLLER:
            # CreateTopics carries error codes per topic, not top-level, so
            # _controller_send cannot see a stale-controller answer itself.
            self._controller_id = None
            resp = self._controller_send(m.CREATE_TOPICS, body)
            code = resp["topics"][0]["error_code"]
        if code not in (m.NONE, *error_ok):
            raise m.KafkaProtocolError(code, f"create_topic({name})")
        return code

    def describe_configs(self, resource_type: int, names: Iterable,
                         ) -> dict[str, dict[str, str]]:
        """name -> {config: value}. One BATCHED request per destination —
        the request schema takes an array of resources, and a per-name
        round-trip would turn a whole-cluster topic-config sweep into
        thousands of sequential RPCs. BROKER resources are still routed to
        the broker itself (broker configs are broker-local state)."""
        out: dict[str, dict[str, str]] = {}
        names = list(names)
        if resource_type == m.RESOURCE_BROKER:
            batches = [(self.connection(int(n)), [n]) for n in names]
        else:
            batches = [(None, names)] if names else []
        for conn, batch in batches:
            body = {"resources": [
                {"resource_type": resource_type, "resource_name": str(n),
                 "configuration_keys": None} for n in batch]}
            resp = (conn.send(m.DESCRIBE_CONFIGS, body) if conn is not None
                    else self._boot_send(m.DESCRIBE_CONFIGS, body))
            for r in resp["results"]:
                if r["error_code"] != m.NONE:
                    raise m.KafkaProtocolError(
                        r["error_code"],
                        f"describe_configs({r['resource_name']})")
                out[r["resource_name"]] = {
                    c["name"]: c["value"] for c in r["configs"]
                    if c["value"] is not None}
        return out

    def incremental_alter_configs(
            self, resource_type: int,
            updates: Mapping[object, Mapping[str, str | None]]) -> None:
        """{resource_name: {key: value-or-None}}; None deletes the key
        (real KIP-339 semantics — no describe-merge round trip)."""
        for name, kv in updates.items():
            body = {
                "resources": [{
                    "resource_type": resource_type,
                    "resource_name": str(name),
                    "configs": [
                        {"name": k,
                         "config_operation": m.OP_DELETE if v is None
                         else m.OP_SET,
                         "value": None if v is None else str(v)}
                        for k, v in kv.items()]}],
                "validate_only": False}
            if resource_type == m.RESOURCE_BROKER:
                resp = self.connection(int(name)).send(
                    m.INCREMENTAL_ALTER_CONFIGS, body)
            else:
                # Topic configs: any broker accepts and forwards.
                resp = self._boot_send(m.INCREMENTAL_ALTER_CONFIGS, body)
            for r in resp["responses"]:
                if r["error_code"] != m.NONE:
                    raise m.KafkaProtocolError(
                        r["error_code"],
                        f"alter_configs({r['resource_name']}): "
                        f"{r['error_message']}")

    def alter_partition_reassignments(
            self, targets: Mapping[tuple[str, int],
                                   Sequence[int] | None]) -> None:
        by_topic: dict[str, list[dict]] = {}
        for (topic, part), replicas in targets.items():
            by_topic.setdefault(topic, []).append({
                "partition_index": part,
                "replicas": list(replicas) if replicas is not None else None})
        resp = self._controller_send(m.ALTER_PARTITION_REASSIGNMENTS, {
            "timeout_ms": int(self._timeout * 1000),
            "topics": [{"name": t, "partitions": ps}
                       for t, ps in by_topic.items()]})
        for t in by_topic:  # replica sets are changing: drop cached views
            self.invalidate_topic(t)
        if resp["error_code"] != m.NONE:
            raise m.KafkaProtocolError(resp["error_code"],
                                       "alter_partition_reassignments")
        for t in resp["responses"] or []:
            for p in t["partitions"] or []:
                # Cancelling nothing is success for our callers' purposes.
                if p["error_code"] not in (m.NONE,
                                           m.NO_REASSIGNMENT_IN_PROGRESS):
                    raise m.KafkaProtocolError(
                        p["error_code"],
                        f"{t['name']}-{p['partition_index']}: "
                        f"{p['error_message']}")

    def list_partition_reassignments(self) -> dict[tuple[str, int], dict]:
        resp = self._controller_send(m.LIST_PARTITION_REASSIGNMENTS, {
            "timeout_ms": int(self._timeout * 1000), "topics": None})
        if resp["error_code"] != m.NONE:
            raise m.KafkaProtocolError(resp["error_code"],
                                       "list_partition_reassignments")
        out = {}
        for t in resp["topics"] or []:
            for p in t["partitions"] or []:
                out[(t["name"], p["partition_index"])] = {
                    "replicas": p["replicas"] or [],
                    "adding": p["adding_replicas"] or [],
                    "removing": p["removing_replicas"] or []}
        return out

    def elect_leaders(self, partitions: Iterable[tuple[str, int]],
                      election_type: int = m.ELECTION_PREFERRED,
                      ) -> list[tuple[str, int, int]]:
        """Returns per-partition failures as (topic, partition, error_code)
        — a degraded partition (e.g. preferred replica out of ISR during
        broker recovery) must not abort the rest of the batch; the caller
        decides per task (the executor dead-marks it and moves on)."""
        by_topic: dict[str, list[int]] = {}
        for topic, part in partitions:
            by_topic.setdefault(topic, []).append(part)
        resp = self._controller_send(m.ELECT_LEADERS, {
            "election_type": election_type,
            "topic_partitions": [{"topic": t, "partitions": ps}
                                 for t, ps in by_topic.items()],
            "timeout_ms": int(self._timeout * 1000)})
        for t in by_topic:  # leadership is changing: drop cached views
            self.invalidate_topic(t)
        if resp["error_code"] != m.NONE:
            raise m.KafkaProtocolError(resp["error_code"], "elect_leaders")
        failed = []
        for t in resp["replica_election_results"]:
            for p in t["partition_results"]:
                if p["error_code"] not in (m.NONE, m.ELECTION_NOT_NEEDED):
                    failed.append((t["topic"], p["partition_id"],
                                   p["error_code"]))
        return failed

    def describe_log_dirs(self, node_id: int) -> list[dict]:
        resp = self.connection(node_id).send(m.DESCRIBE_LOG_DIRS,
                                             {"topics": None})
        return resp["results"]

    def alter_replica_log_dirs(
            self, node_id: int,
            moves: Mapping[str, Mapping[str, Sequence[int]]],
            ) -> list[tuple[str, int, int]]:
        """{dst_dir: {topic: [partition]}} for one broker; returns
        [(topic, partition, error_code)] for rejected moves."""
        resp = self.connection(node_id).send(m.ALTER_REPLICA_LOG_DIRS, {
            "dirs": [{"path": path,
                      "topics": [{"name": t, "partitions": list(ps)}
                                 for t, ps in topics.items()]}
                     for path, topics in moves.items()]})
        failed = []
        for t in resp["results"]:
            for p in t["partitions"]:
                if p["error_code"] != m.NONE:
                    failed.append((t["topic_name"], p["partition_index"],
                                   p["error_code"]))
        return failed

    # ---- data plane ------------------------------------------------------
    def produce(self, topic: str, partition: int, records: list[Record],
                acks: int = 1) -> int:
        """Append records to the partition leader; returns base offset."""
        batch = encode_batch(records, base_offset=0)

        def call(conn):
            resp = conn.send(m.PRODUCE, {
                "transactional_id": None, "acks": acks,
                "timeout_ms": int(self._timeout * 1000),
                "topics": [{"name": topic, "partitions": [
                    {"index": partition, "records": batch}]}]})
            p = resp["topics"][0]["partitions"][0]
            if p["error_code"] != m.NONE:
                raise m.KafkaProtocolError(p["error_code"],
                                           f"produce({topic}-{partition})")
            return p["base_offset"]

        return self._leader_call(topic, partition, call,
                                 retry_conn_error=False)

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 8 << 20) -> tuple[list[Record], int]:
        """Returns (records from ``offset``, high watermark).

        Decode runs OUTSIDE ``_leader_call`` on purpose: its retry +
        leader-refresh handling is for transport/leadership errors, and a
        batch that fails CRC/framing came from a completed fetch — the
        likely cause is payload corruption, which a blind refetch from the
        same leader mostly repeats. One decode retry with a fresh metadata
        refresh covers the transient cases (mid-truncation read, stale
        leader serving a partial segment); a second failure surfaces to
        the caller (transport.poll isolates per-partition errors)."""
        batch, hw = self.fetch_raw(topic, partition, offset, max_bytes)
        try:
            records = decode_batches(batch)
        except ValueError:
            self.invalidate_topic(topic)
            batch, hw = self.fetch_raw(topic, partition, offset, max_bytes)
            records = decode_batches(batch)
        return ([r for r in records if r.offset >= offset], hw)

    def fetch_raw(self, topic: str, partition: int, offset: int,
                  max_bytes: int = 8 << 20) -> tuple[bytes, int]:
        """(raw record-set bytes, high watermark) — the undecoded fetch for
        columnar consumers (native.index_records + vectorized value
        parsing), skipping per-record Python objects entirely."""

        def call(conn):
            resp = conn.send(m.FETCH, {
                "replica_id": -1, "max_wait_ms": 100, "min_bytes": 1,
                "max_bytes": max_bytes, "isolation_level": 0,
                "topics": [{"name": topic, "partitions": [
                    {"index": partition, "fetch_offset": offset,
                     "max_bytes": max_bytes}]}]})
            p = resp["topics"][0]["partitions"][0]
            if p["error_code"] != m.NONE:
                raise m.KafkaProtocolError(p["error_code"],
                                           f"fetch({topic}-{partition})")
            return p["records"] or b"", p["high_watermark"]

        return self._leader_call(topic, partition, call)

    def list_offsets(self, topic: str, partition: int,
                     timestamp_ms: int) -> tuple[int, int]:
        """(offset, timestamp) of the first record at/after timestamp_ms;
        (-1, -1) when none. Special timestamps: -1 latest, -2 earliest."""

        def call(conn):
            resp = conn.send(m.LIST_OFFSETS, {
                "replica_id": -1,
                "topics": [{"name": topic, "partitions": [
                    {"index": partition, "timestamp_ms": timestamp_ms}]}]})
            p = resp["topics"][0]["partitions"][0]
            if p["error_code"] != m.NONE:
                raise m.KafkaProtocolError(
                    p["error_code"], f"list_offsets({topic}-{partition})")
            return p["offset"], p["timestamp_ms"]

        return self._leader_call(topic, partition, call)
