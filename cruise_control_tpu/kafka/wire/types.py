"""Wire-format codecs.

Kafka's protocol primitives (KIP-482 for the flexible/compact variants):
big-endian fixed-width ints, length-prefixed strings/bytes (int16/int32
classic, uvarint(N+1) compact), zigzag varints inside record batches, and
tagged fields on flexible message versions.

Every codec is a singleton with ``write(out: bytearray, value)`` and
``read(buf: memoryview, pos: int) -> (value, pos)``; ``Struct`` composes
them over dicts keyed by field name — messages stay declarative data, not
classes (the schema IS the documentation).
"""

from __future__ import annotations

import struct as _struct


class Codec:
    def write(self, out: bytearray, value) -> None:  # pragma: no cover
        raise NotImplementedError

    def read(self, buf: memoryview, pos: int):  # pragma: no cover
        raise NotImplementedError


class _Fixed(Codec):
    def __init__(self, fmt: str):
        self._fmt = ">" + fmt
        self._size = _struct.calcsize(fmt)

    def write(self, out: bytearray, value) -> None:
        out += _struct.pack(self._fmt, value)

    def read(self, buf: memoryview, pos: int):
        (v,) = _struct.unpack_from(self._fmt, buf, pos)
        return v, pos + self._size


Int8 = _Fixed("b")
Int16 = _Fixed("h")
Int32 = _Fixed("i")
Int64 = _Fixed("q")
UInt32 = _Fixed("I")
Float64 = _Fixed("d")


class _Boolean(Codec):
    def write(self, out: bytearray, value) -> None:
        out.append(1 if value else 0)

    def read(self, buf: memoryview, pos: int):
        return buf[pos] != 0, pos + 1


Boolean = _Boolean()


class _UVarInt(Codec):
    """Unsigned LEB128 (compact lengths, tagged-field tags/sizes)."""

    def write(self, out: bytearray, value) -> None:
        v = value
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return

    def read(self, buf: memoryview, pos: int):
        shift, v = 0, 0
        while True:
            b = buf[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v, pos
            shift += 7


UVarInt = _UVarInt()


class _VarInt(Codec):
    """Zigzag-encoded signed varint (record-batch internals)."""

    def write(self, out: bytearray, value) -> None:
        UVarInt.write(out, (value << 1) ^ (value >> 63))

    def read(self, buf: memoryview, pos: int):
        v, pos = UVarInt.read(buf, pos)
        return (v >> 1) ^ -(v & 1), pos


VarInt = _VarInt()


class _String(Codec):
    """Classic non-nullable string: int16 length + utf8."""

    def write(self, out: bytearray, value) -> None:
        raw = value.encode("utf-8")
        Int16.write(out, len(raw))
        out += raw

    def read(self, buf: memoryview, pos: int):
        n, pos = Int16.read(buf, pos)
        if n < 0:
            raise ValueError("null for non-nullable string")
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n


String = _String()


class _NullableString(Codec):
    def write(self, out: bytearray, value) -> None:
        if value is None:
            Int16.write(out, -1)
        else:
            String.write(out, value)

    def read(self, buf: memoryview, pos: int):
        n, pos = Int16.read(buf, pos)
        if n < 0:
            return None, pos
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n


NullableString = _NullableString()


class _CompactString(Codec):
    """Flexible-version string: uvarint(len+1) + utf8; 0 = null."""

    def __init__(self, nullable: bool):
        self._nullable = nullable

    def write(self, out: bytearray, value) -> None:
        if value is None:
            if not self._nullable:
                raise ValueError("null for non-nullable compact string")
            UVarInt.write(out, 0)
            return
        raw = value.encode("utf-8")
        UVarInt.write(out, len(raw) + 1)
        out += raw

    def read(self, buf: memoryview, pos: int):
        n, pos = UVarInt.read(buf, pos)
        if n == 0:
            return None, pos
        n -= 1
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n


CompactString = _CompactString(nullable=False)
CompactNullableString = _CompactString(nullable=True)


class _Bytes(Codec):
    """Classic nullable bytes: int32 length (-1 = null) + raw."""

    def write(self, out: bytearray, value) -> None:
        if value is None:
            Int32.write(out, -1)
            return
        Int32.write(out, len(value))
        out += value

    def read(self, buf: memoryview, pos: int):
        n, pos = Int32.read(buf, pos)
        if n < 0:
            return None, pos
        return bytes(buf[pos:pos + n]), pos + n


Bytes = _Bytes()


class _CompactBytes(Codec):
    def write(self, out: bytearray, value) -> None:
        if value is None:
            UVarInt.write(out, 0)
            return
        UVarInt.write(out, len(value) + 1)
        out += value

    def read(self, buf: memoryview, pos: int):
        n, pos = UVarInt.read(buf, pos)
        if n == 0:
            return None, pos
        n -= 1
        return bytes(buf[pos:pos + n]), pos + n


CompactBytes = _CompactBytes()


class Array(Codec):
    """Classic nullable array: int32 count (-1 = null)."""

    def __init__(self, element: Codec):
        self._element = element

    def write(self, out: bytearray, value) -> None:
        if value is None:
            Int32.write(out, -1)
            return
        Int32.write(out, len(value))
        for item in value:
            self._element.write(out, item)

    def read(self, buf: memoryview, pos: int):
        n, pos = Int32.read(buf, pos)
        if n < 0:
            return None, pos
        out = []
        for _ in range(n):
            item, pos = self._element.read(buf, pos)
            out.append(item)
        return out, pos


class CompactArray(Codec):
    """Flexible-version array: uvarint(count+1); 0 = null."""

    def __init__(self, element: Codec):
        self._element = element

    def write(self, out: bytearray, value) -> None:
        if value is None:
            UVarInt.write(out, 0)
            return
        UVarInt.write(out, len(value) + 1)
        for item in value:
            self._element.write(out, item)

    def read(self, buf: memoryview, pos: int):
        n, pos = UVarInt.read(buf, pos)
        if n == 0:
            return None, pos
        out = []
        for _ in range(n - 1):
            item, pos = self._element.read(buf, pos)
            out.append(item)
        return out, pos


class _TaggedFields(Codec):
    """KIP-482 tagged fields. None of the APIs this client speaks carries
    tags it needs, so writes emit the empty set and reads skip unknown
    tags (the forward-compatibility contract)."""

    def write(self, out: bytearray, value) -> None:
        UVarInt.write(out, 0 if not value else len(value))
        if value:
            for tag in sorted(value):
                UVarInt.write(out, tag)
                UVarInt.write(out, len(value[tag]))
                out += value[tag]

    def read(self, buf: memoryview, pos: int):
        n, pos = UVarInt.read(buf, pos)
        out = {}
        for _ in range(n):
            tag, pos = UVarInt.read(buf, pos)
            size, pos = UVarInt.read(buf, pos)
            out[tag] = bytes(buf[pos:pos + size])
            pos += size
        return out, pos


TaggedFields = _TaggedFields()


class Struct(Codec):
    """Named-field composite; values are plain dicts.

    ``flexible=True`` appends the struct's trailing tagged-fields block
    (every nested struct in a flexible message version has one)."""

    def __init__(self, *fields: tuple[str, Codec], flexible: bool = False):
        self.fields = fields
        self.flexible = flexible

    def write(self, out: bytearray, value) -> None:
        for name, codec in self.fields:
            try:
                codec.write(out, value[name])
            except KeyError:
                raise ValueError(f"missing field {name!r}") from None
        if self.flexible:
            TaggedFields.write(out, value.get("_tags"))

    def read(self, buf: memoryview, pos: int):
        out = {}
        for name, codec in self.fields:
            out[name], pos = codec.read(buf, pos)
        if self.flexible:
            tags, pos = TaggedFields.read(buf, pos)
            if tags:
                out["_tags"] = tags
        return out, pos


def encode(codec: Codec, value) -> bytes:
    out = bytearray()
    codec.write(out, value)
    return bytes(out)


def decode(codec: Codec, data: bytes | memoryview):
    buf = memoryview(data)
    value, pos = codec.read(buf, 0)
    if pos != len(buf):
        raise ValueError(f"{len(buf) - pos} trailing bytes after decode")
    return value
