"""Request/response schemas for the APIs the framework speaks.

One pinned version per API — the lowest version carrying the semantics the
framework needs (the embedded broker advertises exactly these, and real
brokers ≥2.4 support all of them):

======================== === === ========================================
API                      key ver why this version
======================== === === ========================================
Produce                    0   3 record-batch v2 (magic 2) required
Fetch                      1   4 record-batch v2 + isolation level
ListOffsets                2   1 timestamp-indexed lookup (KIP-79)
Metadata                   3   1 rack + controller + is_internal
ApiVersions               18   0 bootstrap negotiation
CreateTopics              19   0 topic auto-creation
DescribeConfigs           32   0 throttle/config reads
AlterConfigs              33   0 legacy full-replace (kept for parity)
AlterReplicaLogDirs       34   0 JBOD intra-broker moves
DescribeLogDirs           35   0 disk failure detection + JBOD state
ElectLeaders              43   1 PREFERRED/UNCLEAN election types
IncrementalAlterConfigs   44   0 real incremental throttle updates
AlterPartitionReassign.   45   0 KIP-455 reassignment (flexible)
ListPartitionReassign.    46   0 KIP-455 in-flight view (flexible)
======================== === === ========================================

Keys 45/46 have only flexible versions (born at 2.4 post-KIP-482), so
their schemas use compact encodings + tagged fields; everything else is
pinned to classic encodings.

Reference parity: ExecutorAdminUtils.java (the Java AdminClient calls
these same APIs), CruiseControlMetricsReporter.java:241 (produce),
KafkaSampleStore.java:204 (fetch/list-offsets replay).
"""

from __future__ import annotations

from dataclasses import dataclass

from .types import (
    Array, Boolean, Bytes, Codec, CompactArray, CompactNullableString,
    CompactString, Int8, Int16, Int32, Int64, NullableString, String, Struct,
)

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_API_VERSIONS = 18
API_CREATE_TOPICS = 19
API_DESCRIBE_CONFIGS = 32
API_ALTER_CONFIGS = 33
API_ALTER_REPLICA_LOG_DIRS = 34
API_DESCRIBE_LOG_DIRS = 35
API_ELECT_LEADERS = 43
API_INCREMENTAL_ALTER_CONFIGS = 44
API_ALTER_PARTITION_REASSIGNMENTS = 45
API_LIST_PARTITION_REASSIGNMENTS = 46

# Special ListOffsets timestamps (KIP-79).
LATEST_TIMESTAMP = -1
EARLIEST_TIMESTAMP = -2

# Config resource types (shared with DescribeConfigs/AlterConfigs).
RESOURCE_TOPIC = 2
RESOURCE_BROKER = 4

ELECTION_PREFERRED = 0
ELECTION_UNCLEAN = 1


@dataclass(frozen=True)
class Api:
    key: int
    version: int
    request: Codec
    response: Codec
    flexible: bool = False


def _arr(*fields: tuple[str, Codec]) -> Array:
    return Array(Struct(*fields))


def _carr(*fields: tuple[str, Codec]) -> CompactArray:
    return CompactArray(Struct(*fields, flexible=True))


PRODUCE = Api(API_PRODUCE, 3, request=Struct(
    ("transactional_id", NullableString),
    ("acks", Int16),
    ("timeout_ms", Int32),
    ("topics", _arr(
        ("name", String),
        ("partitions", _arr(
            ("index", Int32),
            ("records", Bytes))))),
), response=Struct(
    ("topics", _arr(
        ("name", String),
        ("partitions", _arr(
            ("index", Int32),
            ("error_code", Int16),
            ("base_offset", Int64),
            ("log_append_time_ms", Int64))))),
    ("throttle_time_ms", Int32),
))

FETCH = Api(API_FETCH, 4, request=Struct(
    ("replica_id", Int32),
    ("max_wait_ms", Int32),
    ("min_bytes", Int32),
    ("max_bytes", Int32),
    ("isolation_level", Int8),
    ("topics", _arr(
        ("name", String),
        ("partitions", _arr(
            ("index", Int32),
            ("fetch_offset", Int64),
            ("max_bytes", Int32))))),
), response=Struct(
    ("throttle_time_ms", Int32),
    ("topics", _arr(
        ("name", String),
        ("partitions", _arr(
            ("index", Int32),
            ("error_code", Int16),
            ("high_watermark", Int64),
            ("last_stable_offset", Int64),
            ("aborted_transactions", _arr(
                ("producer_id", Int64),
                ("first_offset", Int64))),
            ("records", Bytes))))),
))

LIST_OFFSETS = Api(API_LIST_OFFSETS, 1, request=Struct(
    ("replica_id", Int32),
    ("topics", _arr(
        ("name", String),
        ("partitions", _arr(
            ("index", Int32),
            ("timestamp_ms", Int64))))),
), response=Struct(
    ("topics", _arr(
        ("name", String),
        ("partitions", _arr(
            ("index", Int32),
            ("error_code", Int16),
            ("timestamp_ms", Int64),
            ("offset", Int64))))),
))

METADATA = Api(API_METADATA, 1, request=Struct(
    ("topics", Array(String)),  # null = all topics
), response=Struct(
    ("brokers", _arr(
        ("node_id", Int32),
        ("host", String),
        ("port", Int32),
        ("rack", NullableString))),
    ("controller_id", Int32),
    ("topics", _arr(
        ("error_code", Int16),
        ("name", String),
        ("is_internal", Boolean),
        ("partitions", _arr(
            ("error_code", Int16),
            ("index", Int32),
            ("leader", Int32),
            ("replicas", Array(Int32)),
            ("isr", Array(Int32)))))),
))

API_VERSIONS = Api(API_API_VERSIONS, 0, request=Struct(), response=Struct(
    ("error_code", Int16),
    ("api_keys", _arr(
        ("api_key", Int16),
        ("min_version", Int16),
        ("max_version", Int16))),
))

CREATE_TOPICS = Api(API_CREATE_TOPICS, 0, request=Struct(
    ("topics", _arr(
        ("name", String),
        ("num_partitions", Int32),
        ("replication_factor", Int16),
        ("assignments", _arr(
            ("partition_index", Int32),
            ("broker_ids", Array(Int32)))),
        ("configs", _arr(
            ("name", String),
            ("value", NullableString))))),
    ("timeout_ms", Int32),
), response=Struct(
    ("topics", _arr(
        ("name", String),
        ("error_code", Int16))),
))

DESCRIBE_CONFIGS = Api(API_DESCRIBE_CONFIGS, 0, request=Struct(
    ("resources", _arr(
        ("resource_type", Int8),
        ("resource_name", String),
        ("configuration_keys", Array(String)))),  # null = all keys
), response=Struct(
    ("throttle_time_ms", Int32),
    ("results", _arr(
        ("error_code", Int16),
        ("error_message", NullableString),
        ("resource_type", Int8),
        ("resource_name", String),
        ("configs", _arr(
            ("name", String),
            ("value", NullableString),
            ("read_only", Boolean),
            ("is_default", Boolean),
            ("is_sensitive", Boolean))))),
))

ALTER_CONFIGS = Api(API_ALTER_CONFIGS, 0, request=Struct(
    ("resources", _arr(
        ("resource_type", Int8),
        ("resource_name", String),
        ("configs", _arr(
            ("name", String),
            ("value", NullableString))))),
    ("validate_only", Boolean),
), response=Struct(
    ("throttle_time_ms", Int32),
    ("responses", _arr(
        ("error_code", Int16),
        ("error_message", NullableString),
        ("resource_type", Int8),
        ("resource_name", String))),
))

# Incremental ops (KIP-339).
OP_SET = 0
OP_DELETE = 1
OP_APPEND = 2
OP_SUBTRACT = 3

INCREMENTAL_ALTER_CONFIGS = Api(API_INCREMENTAL_ALTER_CONFIGS, 0,
                                request=Struct(
    ("resources", _arr(
        ("resource_type", Int8),
        ("resource_name", String),
        ("configs", _arr(
            ("name", String),
            ("config_operation", Int8),
            ("value", NullableString))))),
    ("validate_only", Boolean),
), response=Struct(
    ("throttle_time_ms", Int32),
    ("responses", _arr(
        ("error_code", Int16),
        ("error_message", NullableString),
        ("resource_type", Int8),
        ("resource_name", String))),
))

ALTER_REPLICA_LOG_DIRS = Api(API_ALTER_REPLICA_LOG_DIRS, 0, request=Struct(
    ("dirs", _arr(
        ("path", String),
        ("topics", _arr(
            ("name", String),
            ("partitions", Array(Int32)))))),
), response=Struct(
    ("throttle_time_ms", Int32),
    ("results", _arr(
        ("topic_name", String),
        ("partitions", _arr(
            ("partition_index", Int32),
            ("error_code", Int16))))),
))

DESCRIBE_LOG_DIRS = Api(API_DESCRIBE_LOG_DIRS, 0, request=Struct(
    ("topics", _arr(
        ("topic", String),
        ("partitions", Array(Int32)))),  # null = every partition hosted
), response=Struct(
    ("throttle_time_ms", Int32),
    ("results", _arr(
        ("error_code", Int16),
        ("log_dir", String),
        ("topics", _arr(
            ("name", String),
            ("partitions", _arr(
                ("partition_index", Int32),
                ("partition_size", Int64),
                ("offset_lag", Int64),
                ("is_future_key", Boolean))))))),
))

ELECT_LEADERS = Api(API_ELECT_LEADERS, 1, request=Struct(
    ("election_type", Int8),
    ("topic_partitions", _arr(
        ("topic", String),
        ("partitions", Array(Int32)))),  # null = all eligible
    ("timeout_ms", Int32),
), response=Struct(
    ("throttle_time_ms", Int32),
    ("error_code", Int16),
    ("replica_election_results", _arr(
        ("topic", String),
        ("partition_results", _arr(
            ("partition_id", Int32),
            ("error_code", Int16),
            ("error_message", NullableString))))),
))

ALTER_PARTITION_REASSIGNMENTS = Api(
    API_ALTER_PARTITION_REASSIGNMENTS, 0, flexible=True, request=Struct(
        ("timeout_ms", Int32),
        ("topics", _carr(
            ("name", CompactString),
            ("partitions", _carr(
                ("partition_index", Int32),
                ("replicas", CompactArray(Int32)))))),  # null = cancel
        flexible=True,
    ), response=Struct(
        ("throttle_time_ms", Int32),
        ("error_code", Int16),
        ("error_message", CompactNullableString),
        ("responses", _carr(
            ("name", CompactString),
            ("partitions", _carr(
                ("partition_index", Int32),
                ("error_code", Int16),
                ("error_message", CompactNullableString))))),
        flexible=True,
    ))

LIST_PARTITION_REASSIGNMENTS = Api(
    API_LIST_PARTITION_REASSIGNMENTS, 0, flexible=True, request=Struct(
        ("timeout_ms", Int32),
        ("topics", _carr(
            ("name", CompactString),
            ("partition_indexes", CompactArray(Int32)))),  # null = all
        flexible=True,
    ), response=Struct(
        ("throttle_time_ms", Int32),
        ("error_code", Int16),
        ("error_message", CompactNullableString),
        ("topics", _carr(
            ("name", CompactString),
            ("partitions", _carr(
                ("partition_index", Int32),
                ("replicas", CompactArray(Int32)),
                ("adding_replicas", CompactArray(Int32)),
                ("removing_replicas", CompactArray(Int32)))))),
        flexible=True,
    ))

ALL_APIS: tuple[Api, ...] = (
    PRODUCE, FETCH, LIST_OFFSETS, METADATA, API_VERSIONS, CREATE_TOPICS,
    DESCRIBE_CONFIGS, ALTER_CONFIGS, ALTER_REPLICA_LOG_DIRS,
    DESCRIBE_LOG_DIRS, ELECT_LEADERS, INCREMENTAL_ALTER_CONFIGS,
    ALTER_PARTITION_REASSIGNMENTS, LIST_PARTITION_REASSIGNMENTS,
)

BY_KEY: dict[int, Api] = {api.key: api for api in ALL_APIS}

# ---- error codes (the subset the framework produces/interprets) ----------
NONE = 0
UNKNOWN_SERVER_ERROR = -1
OFFSET_OUT_OF_RANGE = 1
CORRUPT_MESSAGE = 2
UNKNOWN_TOPIC_OR_PARTITION = 3
MESSAGE_TOO_LARGE = 10
RECORD_LIST_TOO_LARGE = 18
UNSUPPORTED_VERSION = 35
NOT_LEADER_OR_FOLLOWER = 6
TOPIC_ALREADY_EXISTS = 36
INVALID_REQUEST = 42
LOG_DIR_NOT_FOUND = 57
KAFKA_STORAGE_ERROR = 56
NOT_CONTROLLER = 41
NO_REASSIGNMENT_IN_PROGRESS = 85
ELECTION_NOT_NEEDED = 84
PREFERRED_LEADER_NOT_AVAILABLE = 80
REPLICA_NOT_AVAILABLE = 9

ERROR_NAMES = {
    NONE: "NONE", UNKNOWN_SERVER_ERROR: "UNKNOWN_SERVER_ERROR",
    OFFSET_OUT_OF_RANGE: "OFFSET_OUT_OF_RANGE",
    CORRUPT_MESSAGE: "CORRUPT_MESSAGE",
    MESSAGE_TOO_LARGE: "MESSAGE_TOO_LARGE",
    RECORD_LIST_TOO_LARGE: "RECORD_LIST_TOO_LARGE",
    UNSUPPORTED_VERSION: "UNSUPPORTED_VERSION",
    UNKNOWN_TOPIC_OR_PARTITION: "UNKNOWN_TOPIC_OR_PARTITION",
    NOT_LEADER_OR_FOLLOWER: "NOT_LEADER_OR_FOLLOWER",
    TOPIC_ALREADY_EXISTS: "TOPIC_ALREADY_EXISTS",
    INVALID_REQUEST: "INVALID_REQUEST",
    LOG_DIR_NOT_FOUND: "LOG_DIR_NOT_FOUND",
    KAFKA_STORAGE_ERROR: "KAFKA_STORAGE_ERROR",
    NOT_CONTROLLER: "NOT_CONTROLLER",
    NO_REASSIGNMENT_IN_PROGRESS: "NO_REASSIGNMENT_IN_PROGRESS",
    ELECTION_NOT_NEEDED: "ELECTION_NOT_NEEDED",
    PREFERRED_LEADER_NOT_AVAILABLE: "PREFERRED_LEADER_NOT_AVAILABLE",
    REPLICA_NOT_AVAILABLE: "REPLICA_NOT_AVAILABLE",
}


# Codes where re-sending the SAME request can never succeed — callers that
# buffer-and-retry must drop on these instead of re-queueing.
# CORRUPT_MESSAGE is deliberately NOT here: in-transit corruption succeeds
# on re-send (the Java client treats CorruptRecordException as retriable).
PERMANENT_ERRORS = frozenset({
    MESSAGE_TOO_LARGE, RECORD_LIST_TOO_LARGE, UNSUPPORTED_VERSION,
    INVALID_REQUEST,
})

# Codes whose condition is expected to clear on its own (leadership or
# controller movement, in-transit corruption) — the Java client's
# RetriableException analogue. utils.resilience.default_retryable reads
# the ``transient`` property below, so these retry under a RetryPolicy.
RETRIABLE_ERRORS = frozenset({
    CORRUPT_MESSAGE, NOT_LEADER_OR_FOLLOWER, NOT_CONTROLLER,
    REPLICA_NOT_AVAILABLE, PREFERRED_LEADER_NOT_AVAILABLE,
})


class KafkaProtocolError(RuntimeError):
    def __init__(self, code: int, context: str = ""):
        self.code = code
        name = ERROR_NAMES.get(code, str(code))
        super().__init__(f"{name}{f' ({context})' if context else ''}")

    @property
    def is_permanent(self) -> bool:
        return self.code in PERMANENT_ERRORS

    @property
    def transient(self) -> bool:
        return self.code in RETRIABLE_ERRORS
