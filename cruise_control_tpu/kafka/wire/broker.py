"""Embedded in-process Kafka cluster speaking the real wire protocol.

The integration-test tier: the reference boots actual broker JVMs
(CCKafkaIntegrationTestHarness, CruiseControlIntegrationTestHarness.java:17);
this environment has no Kafka distribution, so the harness implements the
broker side of the same wire format the client speaks — every integration
test round-trips real bytes over real sockets through both codec stacks.

One ``EmbeddedKafkaCluster`` runs N TCP listeners (one per broker id)
sharing one cluster state, so per-broker APIs (DescribeLogDirs,
AlterReplicaLogDirs, broker DescribeConfigs) behave like the real thing:
the answer depends on which broker you ask.

Failure injection for detector/executor tests:
- ``kill_broker(id)``        — listener stops accepting (dead broker)
- ``set_logdir_health(...)`` — storage errors on DescribeLogDirs
- ``auto_complete_reassignments=False`` + ``complete_reassignments()``
  — hold reassignments in flight so poll loops are observable.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from dataclasses import dataclass, field

from . import messages as m
from .records import Record, decode_batches, encode_batch
from .types import NullableString, TaggedFields, decode, encode

LOG = logging.getLogger(__name__)

DEFAULT_LOGDIRS = ("/data/d0", "/data/d1")


@dataclass
class PartitionLog:
    replicas: list[int]
    leader: int
    isr: list[int]
    records: list[Record] = field(default_factory=list)
    next_offset: int = 0
    adding: list[int] = field(default_factory=list)
    removing: list[int] = field(default_factory=list)
    target: list[int] | None = None          # in-flight reassignment target
    logdir: dict[int, str] = field(default_factory=dict)  # broker -> dir


@dataclass
class TopicState:
    partitions: dict[int, PartitionLog]
    configs: dict[str, str] = field(default_factory=dict)
    is_internal: bool = False


class EmbeddedKafkaCluster:
    def __init__(self, num_brokers: int = 1,
                 racks: dict[int, str] | None = None,
                 logdirs: tuple[str, ...] = DEFAULT_LOGDIRS,
                 auto_complete_reassignments: bool = True,
                 host: str = "127.0.0.1"):
        self._host = host
        self._lock = threading.RLock()
        self.topics: dict[str, TopicState] = {}
        self.broker_ids = list(range(num_brokers))
        self.racks = racks or {}
        self.logdir_names = logdirs
        self.logdir_health: dict[int, dict[str, bool]] = {
            b: {d: True for d in logdirs} for b in self.broker_ids}
        self.broker_configs: dict[int, dict[str, str]] = {
            b: {} for b in self.broker_ids}
        self.auto_complete = auto_complete_reassignments
        self._servers: dict[int, socket.socket] = {}
        self._ports: dict[int, int] = {}
        self._threads: list[threading.Thread] = []
        self._conns: dict[int, set[socket.socket]] = {}
        self._dead: set[int] = set()
        self._running = False

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "EmbeddedKafkaCluster":
        self._running = True
        for broker_id in self.broker_ids:
            self._start_listener(broker_id)
        return self

    def _start_listener(self, broker_id: int) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._host, self._ports.get(broker_id, 0)))
        srv.listen(16)
        # Timed accept: a thread blocked in accept() pins the listener's
        # open file description, so close() from kill_broker()/stop() would
        # leave the port LISTENING forever. The timeout bounds how long the
        # accept loop can hold it after shutdown.
        srv.settimeout(0.1)
        self._ports[broker_id] = srv.getsockname()[1]
        self._servers[broker_id] = srv
        t = threading.Thread(target=self._accept_loop,
                             args=(broker_id, srv),
                             name=f"embedded-kafka-{broker_id}", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        for srv in self._servers.values():
            try:
                srv.close()
            except OSError:
                pass
        self._servers.clear()

    @property
    def bootstrap_servers(self) -> str:
        return ",".join(f"{self._host}:{self._ports[b]}"
                        for b in self.broker_ids if b not in self._dead)

    def port_of(self, broker_id: int) -> int:
        return self._ports[broker_id]

    # ---- failure injection ----------------------------------------------
    def kill_broker(self, broker_id: int) -> None:
        self._dead.add(broker_id)
        srv = self._servers.pop(broker_id, None)
        if srv is not None:
            srv.close()
        # A dead broker resets its established connections too — in-flight
        # clients must see a connection error, not one last answer.
        for conn in list(self._conns.get(broker_id, ())):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()

    def revive_broker(self, broker_id: int) -> None:
        self._dead.discard(broker_id)
        # The port may linger in CLOSE_WAIT until per-connection server
        # threads notice the peer hung up; retry the bind briefly.
        deadline = time.time() + 5.0
        while True:
            try:
                self._start_listener(broker_id)
                return
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)

    def set_logdir_health(self, broker_id: int, logdir: str,
                          healthy: bool) -> None:
        self.logdir_health[broker_id][logdir] = healthy

    def complete_reassignments(self) -> int:
        """Finish every in-flight reassignment (manual mode)."""
        with self._lock:
            n = 0
            for topic in self.topics.values():
                for p in topic.partitions.values():
                    if p.target is not None:
                        self._finish_reassignment(p)
                        n += 1
            return n

    def _finish_reassignment(self, p: PartitionLog) -> None:
        assert p.target is not None
        default_dir = self.logdir_names[0]
        for b in p.target:
            p.logdir.setdefault(b, default_dir)
        for b in p.removing:
            p.logdir.pop(b, None)
        p.replicas = list(p.target)
        p.isr = [b for b in p.replicas if b not in self._dead]
        if p.leader not in p.replicas:
            p.leader = next((b for b in p.replicas if b in p.isr), -1)
        p.adding, p.removing, p.target = [], [], None

    def trim_log(self, topic: str, partition: int, new_start: int) -> None:
        """Advance the log start offset (retention simulation): records
        below ``new_start`` disappear, fetches below it become
        OFFSET_OUT_OF_RANGE — the real cleanup.policy=delete behavior."""
        with self._lock:
            p = self.topics[topic].partitions[partition]
            p.records = [r for r in p.records if r.offset >= new_start]

    # ---- topic helpers (test setup) -------------------------------------
    def create_topic(self, name: str, num_partitions: int = 1, rf: int = 1,
                     configs: dict[str, str] | None = None,
                     assignment: dict[int, list[int]] | None = None) -> None:
        with self._lock:
            alive = [b for b in self.broker_ids if b not in self._dead]
            parts: dict[int, PartitionLog] = {}
            for i in range(num_partitions):
                replicas = (assignment[i] if assignment
                            else [alive[(i + j) % len(alive)]
                                  for j in range(min(rf, len(alive)))])
                parts[i] = PartitionLog(
                    replicas=list(replicas), leader=replicas[0],
                    isr=list(replicas),
                    logdir={b: self.logdir_names[0] for b in replicas})
            self.topics[name] = TopicState(
                partitions=parts, configs=dict(configs or {}),
                is_internal=name.startswith("__"))

    # ---- server loop -----------------------------------------------------
    def _accept_loop(self, broker_id: int, srv: socket.socket) -> None:
        with srv:
            while self._running and broker_id not in self._dead \
                    and srv is self._servers.get(broker_id):
                try:
                    conn, _addr = srv.accept()
                except TimeoutError:
                    continue
                except OSError:
                    return
                t = threading.Thread(target=self._serve,
                                     args=(broker_id, conn), daemon=True)
                t.start()

    def _read_exact(self, conn: socket.socket, n: int) -> bytes | None:
        chunks = []
        while n:
            try:
                chunk = conn.recv(n)
            except OSError:
                return None
            if not chunk:
                return None
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _serve(self, broker_id: int, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conns.setdefault(broker_id, set()).add(conn)
        try:
            self._serve_loop(broker_id, conn)
        finally:
            self._conns.get(broker_id, set()).discard(conn)

    def _serve_loop(self, broker_id: int, conn: socket.socket) -> None:
        with conn:
            while self._running and broker_id not in self._dead:
                head = self._read_exact(conn, 4)
                if head is None:
                    return
                (size,) = struct.unpack(">i", head)
                frame = self._read_exact(conn, size)
                if frame is None:
                    return
                try:
                    reply = self._handle(broker_id, memoryview(frame))
                except Exception:
                    LOG.exception("embedded broker %d: request failed",
                                  broker_id)
                    return
                try:
                    conn.sendall(struct.pack(">i", len(reply)) + reply)
                except OSError:
                    return

    def _handle(self, broker_id: int, frame: memoryview) -> bytes:
        api_key, version, correlation = struct.unpack_from(">hhi", frame, 0)
        pos = 8
        _client_id, pos = NullableString.read(frame, pos)
        api = m.BY_KEY.get(api_key)
        if api is None or api.version != version:
            raise ValueError(f"unsupported api {api_key} v{version}")
        if api.flexible:
            _tags, pos = TaggedFields.read(frame, pos)
        request = decode(api.request, frame[pos:])
        with self._lock:
            response = self._dispatch(broker_id, api_key, request)
        head = bytearray(struct.pack(">i", correlation))
        if api.flexible:  # response header v1
            TaggedFields.write(head, None)
        return bytes(head) + encode(api.response, response)

    def _dispatch(self, broker_id: int, api_key: int, req: dict) -> dict:
        handler = {
            m.API_API_VERSIONS: self._h_api_versions,
            m.API_METADATA: self._h_metadata,
            m.API_CREATE_TOPICS: self._h_create_topics,
            m.API_PRODUCE: self._h_produce,
            m.API_FETCH: self._h_fetch,
            m.API_LIST_OFFSETS: self._h_list_offsets,
            m.API_DESCRIBE_CONFIGS: self._h_describe_configs,
            m.API_ALTER_CONFIGS: self._h_alter_configs,
            m.API_INCREMENTAL_ALTER_CONFIGS: self._h_incremental_alter,
            m.API_ALTER_PARTITION_REASSIGNMENTS: self._h_alter_reassign,
            m.API_LIST_PARTITION_REASSIGNMENTS: self._h_list_reassign,
            m.API_ELECT_LEADERS: self._h_elect_leaders,
            m.API_DESCRIBE_LOG_DIRS: self._h_describe_log_dirs,
            m.API_ALTER_REPLICA_LOG_DIRS: self._h_alter_replica_log_dirs,
        }[api_key]
        return handler(broker_id, req)

    # ---- handlers --------------------------------------------------------
    def _h_api_versions(self, broker_id: int, req: dict) -> dict:
        return {"error_code": m.NONE,
                "api_keys": [{"api_key": a.key, "min_version": a.version,
                              "max_version": a.version} for a in m.ALL_APIS]}

    def _alive(self) -> list[int]:
        return [b for b in self.broker_ids if b not in self._dead]

    def _h_metadata(self, broker_id: int, req: dict) -> dict:
        names = req["topics"]
        if names is None:
            names = list(self.topics)
        topics = []
        for name in names:
            t = self.topics.get(name)
            if t is None:
                topics.append({"error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                               "name": name, "is_internal": False,
                               "partitions": []})
                continue
            topics.append({
                "error_code": m.NONE, "name": name,
                "is_internal": t.is_internal,
                "partitions": [
                    {"error_code": m.NONE, "index": i,
                     "leader": p.leader, "replicas": list(p.replicas),
                     "isr": list(p.isr)}
                    for i, p in sorted(t.partitions.items())]})
        alive = self._alive()
        return {
            "brokers": [{"node_id": b, "host": self._host,
                         "port": self._ports[b],
                         "rack": self.racks.get(b)} for b in alive],
            "controller_id": alive[0] if alive else -1,
            "topics": topics}

    def _h_create_topics(self, broker_id: int, req: dict) -> dict:
        out = []
        for t in req["topics"]:
            if t["name"] in self.topics:
                out.append({"name": t["name"],
                            "error_code": m.TOPIC_ALREADY_EXISTS})
                continue
            self.create_topic(
                t["name"], max(t["num_partitions"], 1),
                max(t["replication_factor"], 1),
                configs={c["name"]: c["value"] for c in t["configs"]
                         if c["value"] is not None},
                assignment={a["partition_index"]: a["broker_ids"]
                            for a in t["assignments"]} or None)
            out.append({"name": t["name"], "error_code": m.NONE})
        return {"topics": out}

    def _partition(self, topic: str, index: int) -> PartitionLog | None:
        t = self.topics.get(topic)
        return t.partitions.get(index) if t else None

    def _h_produce(self, broker_id: int, req: dict) -> dict:
        topics_out = []
        for t in req["topics"]:
            parts_out = []
            for pr in t["partitions"]:
                p = self._partition(t["name"], pr["index"])
                if p is None:
                    parts_out.append(
                        {"index": pr["index"],
                         "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                         "base_offset": -1, "log_append_time_ms": -1})
                    continue
                if p.leader != broker_id:
                    parts_out.append(
                        {"index": pr["index"],
                         "error_code": m.NOT_LEADER_OR_FOLLOWER,
                         "base_offset": -1, "log_append_time_ms": -1})
                    continue
                base = p.next_offset
                for rec in decode_batches(pr["records"] or b""):
                    p.records.append(Record(
                        offset=p.next_offset,
                        timestamp_ms=rec.timestamp_ms,
                        key=rec.key, value=rec.value, headers=rec.headers))
                    p.next_offset += 1
                parts_out.append({"index": pr["index"], "error_code": m.NONE,
                                  "base_offset": base,
                                  "log_append_time_ms": -1})
            topics_out.append({"name": t["name"], "partitions": parts_out})
        return {"topics": topics_out, "throttle_time_ms": 0}

    def _h_fetch(self, broker_id: int, req: dict) -> dict:
        topics_out = []
        for t in req["topics"]:
            parts_out = []
            for pr in t["partitions"]:
                p = self._partition(t["name"], pr["index"])
                if p is None:
                    parts_out.append({
                        "index": pr["index"],
                        "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                        "high_watermark": -1, "last_stable_offset": -1,
                        "aborted_transactions": None, "records": None})
                    continue
                offset = pr["fetch_offset"]
                log_start = p.records[0].offset if p.records else 0
                if offset > p.next_offset or offset < log_start:
                    parts_out.append({
                        "index": pr["index"],
                        "error_code": m.OFFSET_OUT_OF_RANGE,
                        "high_watermark": p.next_offset,
                        "last_stable_offset": p.next_offset,
                        "aborted_transactions": None, "records": None})
                    continue
                window = [r for r in p.records if r.offset >= offset]
                budget = pr["max_bytes"]
                batch = b""
                if window:
                    # Paginate by WHOLE batches: grow the record count until
                    # the encoding would exceed the byte budget, always
                    # returning at least one record (the real broker's
                    # at-least-one-complete-batch contract). A truncated
                    # partial batch would decode to [] and read as
                    # end-of-data — silent data loss past the budget point.
                    n = len(window)
                    batch = encode_batch(window)
                    while len(batch) > budget and n > 1:
                        n = max(1, n // 2)
                        batch = encode_batch(window[:n])
                parts_out.append({
                    "index": pr["index"], "error_code": m.NONE,
                    "high_watermark": p.next_offset,
                    "last_stable_offset": p.next_offset,
                    "aborted_transactions": None,
                    "records": batch})
            topics_out.append({"name": t["name"], "partitions": parts_out})
        return {"throttle_time_ms": 0, "topics": topics_out}

    def _h_list_offsets(self, broker_id: int, req: dict) -> dict:
        topics_out = []
        for t in req["topics"]:
            parts_out = []
            for pr in t["partitions"]:
                p = self._partition(t["name"], pr["index"])
                if p is None:
                    parts_out.append({
                        "index": pr["index"],
                        "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                        "timestamp_ms": -1, "offset": -1})
                    continue
                ts = pr["timestamp_ms"]
                if ts == m.LATEST_TIMESTAMP:
                    offset, rts = p.next_offset, -1
                elif ts == m.EARLIEST_TIMESTAMP:
                    offset = p.records[0].offset if p.records else 0
                    rts = -1
                else:
                    hit = next((r for r in p.records
                                if r.timestamp_ms >= ts), None)
                    offset = hit.offset if hit else -1
                    rts = hit.timestamp_ms if hit else -1
                parts_out.append({"index": pr["index"], "error_code": m.NONE,
                                  "timestamp_ms": rts, "offset": offset})
            topics_out.append({"name": t["name"], "partitions": parts_out})
        return {"topics": topics_out}

    def _h_describe_configs(self, broker_id: int, req: dict) -> dict:
        results = []
        for r in req["resources"]:
            if r["resource_type"] == m.RESOURCE_TOPIC:
                t = self.topics.get(r["resource_name"])
                if t is None:
                    results.append({
                        "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                        "error_message": "unknown topic",
                        "resource_type": r["resource_type"],
                        "resource_name": r["resource_name"], "configs": []})
                    continue
                configs = t.configs
            else:
                configs = self.broker_configs.get(
                    int(r["resource_name"]), {})
            keys = r["configuration_keys"]
            results.append({
                "error_code": m.NONE, "error_message": None,
                "resource_type": r["resource_type"],
                "resource_name": r["resource_name"],
                "configs": [
                    {"name": k, "value": v, "read_only": False,
                     "is_default": False, "is_sensitive": False}
                    for k, v in configs.items()
                    if keys is None or k in keys]})
        return {"throttle_time_ms": 0, "results": results}

    def _config_store(self, resource_type: int, name: str) -> dict | None:
        if resource_type == m.RESOURCE_TOPIC:
            t = self.topics.get(name)
            return t.configs if t else None
        return self.broker_configs.setdefault(int(name), {})

    def _h_alter_configs(self, broker_id: int, req: dict) -> dict:
        responses = []
        for r in req["resources"]:
            store = self._config_store(r["resource_type"],
                                       r["resource_name"])
            if store is None:
                responses.append({
                    "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                    "error_message": "unknown topic",
                    "resource_type": r["resource_type"],
                    "resource_name": r["resource_name"]})
                continue
            if not req["validate_only"]:
                store.clear()  # legacy AlterConfigs = full replace
                for c in r["configs"]:
                    if c["value"] is not None:
                        store[c["name"]] = c["value"]
            responses.append({"error_code": m.NONE, "error_message": None,
                              "resource_type": r["resource_type"],
                              "resource_name": r["resource_name"]})
        return {"throttle_time_ms": 0, "responses": responses}

    def _h_incremental_alter(self, broker_id: int, req: dict) -> dict:
        responses = []
        for r in req["resources"]:
            store = self._config_store(r["resource_type"],
                                       r["resource_name"])
            if store is None:
                responses.append({
                    "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                    "error_message": "unknown topic",
                    "resource_type": r["resource_type"],
                    "resource_name": r["resource_name"]})
                continue
            if not req["validate_only"]:
                for c in r["configs"]:
                    if c["config_operation"] == m.OP_DELETE:
                        store.pop(c["name"], None)
                    elif c["config_operation"] == m.OP_SET:
                        store[c["name"]] = c["value"] or ""
            responses.append({"error_code": m.NONE, "error_message": None,
                              "resource_type": r["resource_type"],
                              "resource_name": r["resource_name"]})
        return {"throttle_time_ms": 0, "responses": responses}

    def _h_alter_reassign(self, broker_id: int, req: dict) -> dict:
        responses = []
        for t in req["topics"] or []:
            parts_out = []
            for pr in t["partitions"] or []:
                p = self._partition(t["name"], pr["partition_index"])
                if p is None:
                    parts_out.append({
                        "partition_index": pr["partition_index"],
                        "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                        "error_message": "unknown partition"})
                    continue
                target = pr["replicas"]
                if target is None:  # cancel
                    if p.target is None:
                        parts_out.append({
                            "partition_index": pr["partition_index"],
                            "error_code": m.NO_REASSIGNMENT_IN_PROGRESS,
                            "error_message": None})
                        continue
                    p.replicas = [b for b in p.replicas
                                  if b not in p.adding]
                    p.isr = [b for b in p.isr if b in p.replicas]
                    p.adding, p.removing, p.target = [], [], None
                    if p.leader not in p.replicas:
                        p.leader = p.replicas[0] if p.replicas else -1
                else:
                    original = [b for b in p.replicas if b not in p.adding]
                    p.target = list(target)
                    p.adding = [b for b in target if b not in original]
                    p.removing = [b for b in original if b not in target]
                    # Full replica set during the move (URP view).
                    p.replicas = original + [b for b in p.adding]
                    if self.auto_complete:
                        self._finish_reassignment(p)
                parts_out.append({
                    "partition_index": pr["partition_index"],
                    "error_code": m.NONE, "error_message": None})
            responses.append({"name": t["name"], "partitions": parts_out})
        return {"throttle_time_ms": 0, "error_code": m.NONE,
                "error_message": None, "responses": responses}

    def _h_list_reassign(self, broker_id: int, req: dict) -> dict:
        topics_out = []
        for name, t in self.topics.items():
            parts = [{"partition_index": i, "replicas": list(p.replicas),
                      "adding_replicas": list(p.adding),
                      "removing_replicas": list(p.removing)}
                     for i, p in t.partitions.items() if p.target is not None]
            if parts:
                topics_out.append({"name": name, "partitions": parts})
        return {"throttle_time_ms": 0, "error_code": m.NONE,
                "error_message": None, "topics": topics_out}

    def _h_elect_leaders(self, broker_id: int, req: dict) -> dict:
        results = []
        targets: list[tuple[str, list[int]]]
        if req["topic_partitions"] is None:
            targets = [(name, list(t.partitions))
                       for name, t in self.topics.items()]
        else:
            targets = [(e["topic"], e["partitions"])
                       for e in req["topic_partitions"]]
        for name, parts in targets:
            parts_out = []
            for i in parts:
                p = self._partition(name, i)
                if p is None:
                    parts_out.append({
                        "partition_id": i,
                        "error_code": m.UNKNOWN_TOPIC_OR_PARTITION,
                        "error_message": None})
                    continue
                preferred = p.replicas[0] if p.replicas else -1
                if p.leader == preferred:
                    parts_out.append({"partition_id": i,
                                      "error_code": m.ELECTION_NOT_NEEDED,
                                      "error_message": None})
                elif preferred in p.isr and preferred not in self._dead:
                    p.leader = preferred
                    parts_out.append({"partition_id": i,
                                      "error_code": m.NONE,
                                      "error_message": None})
                else:
                    parts_out.append({
                        "partition_id": i,
                        "error_code": m.PREFERRED_LEADER_NOT_AVAILABLE,
                        "error_message": "preferred replica not in ISR"})
            results.append({"topic": name, "partition_results": parts_out})
        return {"throttle_time_ms": 0, "error_code": m.NONE,
                "replica_election_results": results}

    def _h_describe_log_dirs(self, broker_id: int, req: dict) -> dict:
        wanted = None
        if req["topics"] is not None:
            wanted = {(t["topic"], i)
                      for t in req["topics"] for i in t["partitions"]}
        results = []
        for d in self.logdir_names:
            healthy = self.logdir_health[broker_id].get(d, True)
            topics_out: dict[str, list[dict]] = {}
            for name, t in self.topics.items():
                for i, p in t.partitions.items():
                    if wanted is not None and (name, i) not in wanted:
                        continue
                    if p.logdir.get(broker_id) == d:
                        topics_out.setdefault(name, []).append({
                            "partition_index": i,
                            "partition_size": sum(
                                len(r.value or b"") for r in p.records),
                            "offset_lag": 0, "is_future_key": False})
            results.append({
                "error_code": m.NONE if healthy else m.KAFKA_STORAGE_ERROR,
                "log_dir": d,
                "topics": [{"name": n, "partitions": ps}
                           for n, ps in topics_out.items()]})
        return {"throttle_time_ms": 0, "results": results}

    def _h_alter_replica_log_dirs(self, broker_id: int, req: dict) -> dict:
        by_topic: dict[str, list[dict]] = {}
        for d in req["dirs"]:
            path = d["path"]
            for t in d["topics"]:
                for i in t["partitions"]:
                    p = self._partition(t["name"], i)
                    if p is None or broker_id not in p.replicas:
                        code = m.REPLICA_NOT_AVAILABLE
                    elif path not in self.logdir_names:
                        code = m.LOG_DIR_NOT_FOUND
                    elif not self.logdir_health[broker_id].get(path, True):
                        code = m.KAFKA_STORAGE_ERROR
                    else:
                        p.logdir[broker_id] = path
                        code = m.NONE
                    by_topic.setdefault(t["name"], []).append(
                        {"partition_index": i, "error_code": code})
        return {"throttle_time_ms": 0,
                "results": [{"topic_name": n, "partitions": ps}
                            for n, ps in by_topic.items()]}


def wait_port_open(host: str, port: int, timeout_s: float = 5.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with socket.create_connection((host, port), timeout=0.5):
                return
        except OSError:
            time.sleep(0.02)
    raise TimeoutError(f"{host}:{port} never opened")
