"""MetricsTransport over the ``__CruiseControlMetrics`` topic.

Reference parity: monitor/sampling/CruiseControlMetricsReporterSampler.java
(consume the reporter topic between two timestamps — its offsetsForTimes
strategy maps to ListOffsets with a timestamp) and the reporter's producer
side (CruiseControlMetricsReporter.java:241-270, topic auto-creation
included — exposed as ``ensure_topic`` so the broker-side agent can call it
through the same transport).
"""

from __future__ import annotations

import logging
import time

from .wire import messages as m
from .wire.client import WireClient
from .wire.records import Record, decode_batches

LOG = logging.getLogger(__name__)

METRICS_TOPIC = "__CruiseControlMetrics"


class KafkaMetricsTransport:
    """Implements ``monitor.sampling.MetricsTransport``: ``produce`` from
    the broker-side agent, ``poll(start_ms, end_ms)`` from the sampler."""

    def __init__(self, bootstrap_servers: str, topic: str = METRICS_TOPIC,
                 num_partitions: int = 32, replication_factor: int = 1,
                 max_pending_records: int = 100_000,
                 client: WireClient | None = None, **_compat):
        self._client = client or WireClient(
            bootstrap_servers, client_id="cruise-control-tpu-metrics")
        self._topic = topic
        self._num_partitions = num_partitions
        self._rf = replication_factor
        self._pending: list[Record] = []
        self._max_pending = max_pending_records
        self._rr = 0  # round-robin partition cursor

    # ---- topic auto-creation (reporter side) -----------------------------
    def ensure_topic(self) -> None:
        """Create the metrics topic if absent
        (CruiseControlMetricsReporter.maybeCreateTopic)."""
        self._client.create_topic(
            self._topic, self._num_partitions, self._rf,
            configs={"retention.ms": str(60 * 60 * 1000),
                     "cleanup.policy": "delete"})

    # ---- MetricsTransport protocol ---------------------------------------
    def produce(self, payload: bytes) -> None:
        self._pending.append(Record(
            offset=0, timestamp_ms=int(time.time() * 1000),
            key=None, value=payload))

    def flush(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        try:
            try:
                parts = sorted(self._client.partitions_for(self._topic))
            except m.KafkaProtocolError:
                parts = []
            if not parts:
                self.ensure_topic()
                try:
                    parts = sorted(self._client.partitions_for(self._topic))
                except m.KafkaProtocolError:
                    parts = []
            if not parts:
                # Metadata for a just-created topic can lag on a real
                # cluster (transient LEADER_NOT_AVAILABLE window).
                raise ConnectionError(
                    f"metrics topic {self._topic!r} has no partitions yet")
            self._rr = (self._rr + 1) % len(parts)
            for i, rec in enumerate(batch):
                rec.offset = i
            self._client.produce(self._topic, parts[self._rr], batch)
        except (ConnectionError, m.KafkaProtocolError) as e:
            # A PERMANENTLY-rejected batch (e.g. MESSAGE_TOO_LARGE) is NOT
            # re-queued: the identical batch would fail identically every
            # interval and poison the head of the buffer.
            if isinstance(e, m.KafkaProtocolError) and e.is_permanent:
                LOG.warning("broker rejected metrics batch (%d records): "
                            "dropping it", len(batch), exc_info=True)
                raise
            # Transient failures (connection errors, leader elections in
            # progress) re-queue so a broker blip does not punch a hole in
            # the metric windows the load model trains on (the Java
            # producer's in-flight buffer gives the reference the same
            # durability, CruiseControlMetricsReporter.java:241) — bounded
            # like buffer.memory: during a LONG outage the OLDEST records
            # are dropped first (they age out of the aggregation windows
            # anyway; unbounded growth would OOM the broker agent).
            requeued = batch + self._pending
            if len(requeued) > self._max_pending:
                dropped = len(requeued) - self._max_pending
                requeued = requeued[dropped:]
                LOG.warning("metrics buffer full: dropped %d oldest records",
                            dropped)
            self._pending = requeued
            raise

    def _consume_raw(self, start_ms: int, handle) -> None:
        """The shared per-partition consume loop: seek each partition to
        the start offset by time (ListOffsets), fetch raw record sets to
        the high watermark, and feed each to ``handle(raw, fetch_offset)``
        which returns the next offset to fetch (None = partition
        exhausted). Both the record-object and columnar polls ride this
        one loop so their offset/window semantics can never diverge."""
        try:
            parts = self._client.partitions_for(self._topic)
        except m.KafkaProtocolError:
            return
        for partition in sorted(parts):
            try:
                start, _ts = self._client.list_offsets(self._topic, partition,
                                                       start_ms)
                if start < 0:  # no record at/after start_ms
                    continue
                offset = start
                while True:
                    raw, hw = self._client.fetch_raw(self._topic, partition,
                                                     offset)
                    nxt = handle(raw, offset)
                    if nxt is None or nxt <= offset:
                        break
                    offset = nxt
                    if offset >= hw:
                        break
            except (ConnectionError, m.KafkaProtocolError):
                LOG.warning("metrics poll failed for %s-%d", self._topic,
                            partition, exc_info=True)

    def poll(self, start_ms: int, end_ms: int) -> list[bytes]:
        """All payloads with record timestamp in [start_ms, end_ms):
        filter BOTH bounds so adjacent windows never double-count under
        producer clock skew."""
        out: list[bytes] = []

        def handle(raw: bytes, offset: int):
            records = decode_batches(raw)
            if not records:
                return None
            for r in records:
                if r.offset >= offset and r.value is not None \
                        and start_ms <= r.timestamp_ms < end_ms:
                    out.append(r.value)
            return records[-1].offset + 1

        self._consume_raw(start_ms, handle)
        return out

    def poll_columns(self, start_ms: int, end_ms: int):
        """Columnar ``poll``: (concatenated buffer, value spans [N, 2])
        with the same timestamp-bound semantics, but no per-record Python
        objects — the native record-batch index supplies offsets,
        timestamps, and value spans in one C pass per fetch. Returns None
        when the native library is unavailable (caller falls back to
        ``poll``)."""
        from ..native import index_records, lib
        if lib() is None:
            return None
        import numpy as np

        chunks: list[bytes] = []
        span_parts: list[np.ndarray] = []
        state = {"base": 0}

        def handle(raw: bytes, offset: int):
            idx = index_records(raw)
            if idx is None or not len(idx):
                return None
            keep = (idx[:, 0] >= offset) \
                & (idx[:, 1] >= start_ms) & (idx[:, 1] < end_ms) \
                & (idx[:, 4] >= 0)
            if keep.any():
                chunks.append(raw)
                span = idx[keep][:, 4:6].copy()
                span[:, 0] += state["base"]
                span_parts.append(span)
                state["base"] += len(raw)
            return int(idx[-1, 0]) + 1

        self._consume_raw(start_ms, handle)
        data = b"".join(chunks)
        spans = (np.concatenate(span_parts) if span_parts
                 else np.zeros((0, 2), dtype=np.int64))
        return data, spans

    def close(self) -> None:
        self._client.close()
