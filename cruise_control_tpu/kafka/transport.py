"""MetricsTransport over the ``__CruiseControlMetrics`` topic.

Reference parity: monitor/sampling/CruiseControlMetricsReporterSampler.java
(consume the reporter topic between two timestamps) and the reporter's
producer side (CruiseControlMetricsReporter.java:241-270, topic
auto-creation included — here exposed as ``ensure_topic`` so the
broker-side agent can call it through the same transport).
"""

from __future__ import annotations

import logging

from . import require_kafka

LOG = logging.getLogger(__name__)

METRICS_TOPIC = "__CruiseControlMetrics"


class KafkaMetricsTransport:
    """Implements ``monitor.sampling.MetricsTransport``: ``produce`` from
    the broker-side agent, ``poll(start_ms, end_ms)`` from the sampler."""

    def __init__(self, bootstrap_servers: str, topic: str = METRICS_TOPIC,
                 group_id: str = "cruise-control-tpu-sampler",
                 num_partitions: int = 32, replication_factor: int = 1,
                 **kwargs):
        require_kafka("KafkaMetricsTransport")
        self._bootstrap = bootstrap_servers
        self._topic = topic
        self._group = group_id
        self._num_partitions = num_partitions
        self._rf = replication_factor
        self._kwargs = kwargs
        self._producer = None
        self._consumer = None

    # ---- topic auto-creation (reporter side) -----------------------------
    def ensure_topic(self) -> None:
        """Create the metrics topic if absent
        (CruiseControlMetricsReporter.maybeCreateTopic)."""
        from kafka.admin import KafkaAdminClient, NewTopic
        from kafka.errors import TopicAlreadyExistsError

        admin = KafkaAdminClient(bootstrap_servers=self._bootstrap,
                                 **self._kwargs)
        try:
            admin.create_topics([NewTopic(
                name=self._topic, num_partitions=self._num_partitions,
                replication_factor=self._rf,
                topic_configs={"retention.ms": str(60 * 60 * 1000),
                               "cleanup.policy": "delete"})])
        except TopicAlreadyExistsError:
            pass
        finally:
            admin.close()

    # ---- MetricsTransport protocol ---------------------------------------
    def produce(self, payload: bytes) -> None:
        if self._producer is None:
            from kafka import KafkaProducer

            self._producer = KafkaProducer(
                bootstrap_servers=self._bootstrap, acks=1,
                linger_ms=100, **self._kwargs)
        self._producer.send(self._topic, payload)

    def flush(self) -> None:
        if self._producer is not None:
            self._producer.flush()

    def poll(self, start_ms: int, end_ms: int) -> list[bytes]:
        """All payloads with record timestamp in [start_ms, end_ms): seek
        each partition to the start offset by time, read to the end
        offset (the reference sampler's offsetsForTimes strategy)."""
        from kafka import KafkaConsumer, TopicPartition

        if self._consumer is None:
            self._consumer = KafkaConsumer(
                bootstrap_servers=self._bootstrap, group_id=self._group,
                enable_auto_commit=False, consumer_timeout_ms=2_000,
                **self._kwargs)
        consumer = self._consumer
        parts = consumer.partitions_for_topic(self._topic) or set()
        tps = [TopicPartition(self._topic, p) for p in sorted(parts)]
        if not tps:
            return []
        consumer.assign(tps)
        start_offsets = consumer.offsets_for_times({tp: start_ms for tp in tps})
        end_offsets = consumer.end_offsets(tps)
        out: list[bytes] = []
        remaining: dict = {}
        for tp in tps:
            start = start_offsets.get(tp)
            end = end_offsets.get(tp, 0)
            # Partitions with no record at/after start_ms (None) or nothing
            # between the seek point and the end offset will never deliver:
            # keeping them in `remaining` would make every poll stall out
            # the full consumer timeout.
            if start is None or end <= start.offset:
                continue
            consumer.seek(tp, start.offset)
            remaining[tp] = end
        if not remaining:
            return []
        consumer.assign(list(remaining))
        for record in consumer:
            # offsets_for_times seeks by timestamp index, but later offsets
            # can carry earlier CreateTime stamps (producer clock skew):
            # filter BOTH bounds so adjacent windows never double-count.
            if start_ms <= record.timestamp < end_ms:
                out.append(record.value)
            tp = type(tps[0])(record.topic, record.partition)
            if record.offset + 1 >= remaining.get(tp, 0):
                remaining.pop(tp, None)
                if not remaining:
                    break
        return out

    def close(self) -> None:
        if self._producer is not None:
            self._producer.close()
        if self._consumer is not None:
            self._consumer.close()
