"""SampleStore over two Kafka topics (partition samples + training
samples).

Reference parity: monitor/sampling/KafkaSampleStore.java:94-106 (two
durable topics ``__KafkaCruiseControlPartitionMetricSamples`` /
``__KafkaCruiseControlModelTrainingSamples``), :179 (storeSamples
producer), :204 (loadSamples replay at startup for warm windows).

Serialization reuses the JSONL row format of
``monitor.sampling.sample_store.FileSampleStore`` — one sample per record
— so a cluster can migrate between file and Kafka persistence.
"""

from __future__ import annotations

import json
import logging

from ..monitor.sampling.sampler import SamplerResult
from . import require_kafka

LOG = logging.getLogger(__name__)

PARTITION_SAMPLES_TOPIC = "__KafkaCruiseControlPartitionMetricSamples"
TRAINING_SAMPLES_TOPIC = "__KafkaCruiseControlModelTrainingSamples"


class KafkaSampleStore:
    """Implements ``monitor.sampling.SampleStore`` against Kafka topics."""

    def __init__(self, bootstrap_servers: str,
                 partition_topic: str = PARTITION_SAMPLES_TOPIC,
                 training_topic: str = TRAINING_SAMPLES_TOPIC,
                 group_id: str = "cruise-control-tpu-sample-store",
                 **kwargs):
        require_kafka("KafkaSampleStore")
        self._bootstrap = bootstrap_servers
        self._topics = {"partition": partition_topic,
                        "training": training_topic}
        self._group = group_id
        self._kwargs = kwargs
        self._producer = None

    def store_samples(self, result: SamplerResult) -> None:
        from ..monitor.sampling.samples import (
            broker_samples_record, partition_samples_record,
        )

        if self._producer is None:
            from kafka import KafkaProducer

            self._producer = KafkaProducer(
                bootstrap_servers=self._bootstrap, acks=1, **self._kwargs)
        for row in partition_samples_record(result.partition_samples):
            self._producer.send(self._topics["partition"],
                                json.dumps(row).encode())
        # Broker samples feed the linear CPU model — the reference's
        # "model training samples" topic.
        for row in broker_samples_record(result.broker_samples):
            self._producer.send(self._topics["training"],
                                json.dumps(row).encode())
        self._producer.flush()

    def load_samples(self) -> SamplerResult:
        """Replay both topics from the beginning (warm-start windows after a
        restart — KafkaSampleStore.loadSamples:204)."""
        from kafka import KafkaConsumer

        from ..monitor.sampling.samples import (
            broker_samples_from_record, partition_samples_from_record,
        )

        rows = {"partition": [], "training": []}
        for kind, topic in self._topics.items():
            consumer = KafkaConsumer(
                topic, bootstrap_servers=self._bootstrap,
                group_id=None, auto_offset_reset="earliest",
                enable_auto_commit=False, consumer_timeout_ms=3_000,
                **self._kwargs)
            for record in consumer:
                try:
                    rows[kind].append(json.loads(record.value))
                except (ValueError, TypeError):
                    LOG.warning("skipping undecodable sample record at %s:%d",
                                topic, record.offset)
            consumer.close()
        return SamplerResult(
            partition_samples_from_record(rows["partition"]),
            broker_samples_from_record(rows["training"]), 0)

    def close(self) -> None:
        if self._producer is not None:
            self._producer.close()
