"""SampleStore over two Kafka topics (partition samples + training
samples).

Reference parity: monitor/sampling/KafkaSampleStore.java:94-106 (two
durable topics ``__KafkaCruiseControlPartitionMetricSamples`` /
``__KafkaCruiseControlModelTrainingSamples``), :179 (storeSamples
producer), :204 (loadSamples replay at startup for warm windows).

Serialization reuses the JSONL row format of
``monitor.sampling.sample_store.FileSampleStore`` — one sample per record
— so a cluster can migrate between file and Kafka persistence.
"""

from __future__ import annotations

import json
import logging
import time

from ..monitor.sampling.sampler import SamplerResult
from .wire import messages as m
from .wire.client import WireClient
from .wire.records import Record

LOG = logging.getLogger(__name__)

PARTITION_SAMPLES_TOPIC = "__KafkaCruiseControlPartitionMetricSamples"
TRAINING_SAMPLES_TOPIC = "__KafkaCruiseControlModelTrainingSamples"


class KafkaSampleStore:
    """Implements ``monitor.sampling.SampleStore`` against Kafka topics."""

    def __init__(self, bootstrap_servers: str,
                 partition_topic: str = PARTITION_SAMPLES_TOPIC,
                 training_topic: str = TRAINING_SAMPLES_TOPIC,
                 num_partitions: int = 8, replication_factor: int = 1,
                 client: WireClient | None = None, **_compat):
        self._client = client or WireClient(
            bootstrap_servers, client_id="cruise-control-tpu-samples")
        self._topics = {"partition": partition_topic,
                        "training": training_topic}
        self._num_partitions = num_partitions
        self._rf = replication_factor
        self._rr = 0

    def _ensure_topics(self) -> None:
        for topic in self._topics.values():
            self._client.create_topic(
                topic, self._num_partitions, self._rf,
                configs={"cleanup.policy": "delete"})

    def _produce_rows(self, topic: str, rows: list[dict]) -> None:
        if not rows:
            return
        now = int(time.time() * 1000)
        records = [Record(offset=i, timestamp_ms=now, key=None,
                          value=json.dumps(row).encode())
                   for i, row in enumerate(rows)]
        try:
            parts = sorted(self._client.partitions_for(topic))
        except m.KafkaProtocolError:
            parts = []
        if not parts:
            self._ensure_topics()
            try:
                parts = sorted(self._client.partitions_for(topic))
            except m.KafkaProtocolError:
                parts = []
        if not parts:
            # Metadata for a just-created topic can lag on a real cluster.
            raise ConnectionError(
                f"sample topic {topic!r} has no partitions yet")
        self._rr = (self._rr + 1) % len(parts)
        self._client.produce(topic, parts[self._rr], records)

    def store_samples(self, result: SamplerResult) -> None:
        from ..monitor.sampling.samples import (
            broker_samples_record, partition_samples_record,
        )

        self._produce_rows(self._topics["partition"],
                           list(partition_samples_record(
                               result.partition_samples)))
        # Broker samples feed the linear CPU model — the reference's
        # "model training samples" topic.
        self._produce_rows(self._topics["training"],
                           list(broker_samples_record(result.broker_samples)))

    def load_samples(self) -> SamplerResult:
        """Replay both topics from the beginning (warm-start windows after a
        restart — KafkaSampleStore.loadSamples:204)."""
        from ..monitor.sampling.samples import (
            broker_samples_from_record, partition_samples_from_record,
        )

        rows: dict[str, list] = {"partition": [], "training": []}
        for kind, topic in self._topics.items():
            try:
                parts = self._client.partitions_for(topic)
            except m.KafkaProtocolError:
                continue  # topic absent: cold start
            for partition in sorted(parts):
                try:
                    # Log-start, not 0: retention (cleanup.policy=delete)
                    # advances the start offset, and fetch(0) would return
                    # OFFSET_OUT_OF_RANGE — skipping records that still
                    # exist at higher offsets.
                    offset, _ts = self._client.list_offsets(
                        topic, partition, m.EARLIEST_TIMESTAMP)
                except (ConnectionError, m.KafkaProtocolError):
                    LOG.warning("sample replay failed for %s-%d", topic,
                                partition, exc_info=True)
                    continue
                while True:
                    try:
                        records, hw = self._client.fetch(topic, partition,
                                                         offset)
                    except (ConnectionError, m.KafkaProtocolError):
                        LOG.warning("sample replay failed for %s-%d", topic,
                                    partition, exc_info=True)
                        break
                    if not records:
                        break
                    for r in records:
                        if r.value is None:
                            continue
                        try:
                            rows[kind].append(json.loads(r.value))
                        except (ValueError, TypeError):
                            LOG.warning(
                                "skipping undecodable sample record at %s:%d",
                                topic, r.offset)
                    offset = records[-1].offset + 1
                    if offset >= hw:
                        break
        return SamplerResult(
            partition_samples_from_record(rows["partition"]),
            broker_samples_from_record(rows["training"]), 0)

    def close(self) -> None:
        self._client.close()
