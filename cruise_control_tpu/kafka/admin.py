"""AdminBackend over kafka-python's KafkaAdminClient.

Reference parity: executor/ExecutionUtils.java:483
(alterPartitionReassignments), :433 (electLeaders),
listPartitionsBeingReassigned (Executor.java:1238), incremental
alter-configs for throttles (ReplicationThrottleHelper.java) and
describeLogDirs (DiskFailureDetector.java).

kafka-python notes (>=2.1 — the KIP-455 reassignment and leader-election
APIs arrived with the 2.1+ revival):
- ``alter_partition_reassignments`` / ``list_partition_reassignments``
  implement KIP-455 (cancel = target ``None``).
- ``perform_leader_election`` with PREFERRED election type maps
  electLeaders.
- Config alteration is the legacy (non-incremental) AlterConfigs: this
  binding emulates incremental semantics by describing first and merging
  (value ``None`` deletes a key) — same observable behavior as the
  reference's IncrementalAlterConfigs path.
"""

from __future__ import annotations

from typing import Iterable, Mapping

from ..executor.admin import PartitionState
from . import require_kafka


class KafkaAdminBackend:
    """Implements ``executor.admin.AdminBackend`` against a live cluster."""

    def __init__(self, bootstrap_servers: str, client_id: str = "cruise-control-tpu",
                 request_timeout_ms: int = 30_000, **kwargs):
        require_kafka("KafkaAdminBackend")
        from kafka import KafkaAdminClient

        self._admin = KafkaAdminClient(
            bootstrap_servers=bootstrap_servers, client_id=client_id,
            request_timeout_ms=request_timeout_ms, **kwargs)

    # ---- reassignment / leadership ---------------------------------------
    def alter_partition_reassignments(
            self, targets: Mapping[tuple[str, int], tuple[int, ...]]) -> None:
        from kafka.structs import TopicPartition

        self._admin.alter_partition_reassignments({
            TopicPartition(t, p): list(replicas)
            for (t, p), replicas in targets.items()})

    def cancel_partition_reassignments(
            self, partitions: Iterable[tuple[str, int]]) -> None:
        from kafka.structs import TopicPartition

        # KIP-455: a None target cancels the in-flight reassignment.
        self._admin.alter_partition_reassignments({
            TopicPartition(t, p): None for (t, p) in partitions})

    def elect_leaders(self, partitions: Iterable[tuple[str, int]]) -> None:
        from kafka.admin import ElectionType
        from kafka.structs import TopicPartition

        self._admin.perform_leader_election(
            ElectionType.PREFERRED,
            [TopicPartition(t, p) for (t, p) in partitions])

    def list_reassigning_partitions(self) -> list[tuple[str, int]]:
        listing = self._admin.list_partition_reassignments()
        return [(tp.topic, tp.partition) for tp in listing]

    # ---- metadata --------------------------------------------------------
    def describe_partitions(self) -> dict[tuple[str, int], PartitionState]:
        listing = self._admin.list_partition_reassignments()
        items = listing.items() if isinstance(listing, dict) else []
        reassigning = {(tp.topic, tp.partition): st for tp, st in items}
        out: dict[tuple[str, int], PartitionState] = {}
        for topic_meta in self._admin.describe_topics():
            topic = topic_meta["topic"]
            for pm in topic_meta["partitions"]:
                key = (topic, pm["partition"])
                ra = reassigning.get(key)
                out[key] = PartitionState(
                    topic=topic, partition=pm["partition"],
                    replicas=tuple(pm["replicas"]), leader=pm["leader"],
                    isr=tuple(pm["isr"]),
                    adding=tuple(getattr(ra, "adding_replicas", ()) or ()),
                    removing=tuple(getattr(ra, "removing_replicas", ()) or ()))
        return out

    def alive_brokers(self) -> set[int]:
        return {b["node_id"] if isinstance(b, dict) else b.nodeId
                for b in self._admin.describe_cluster()["brokers"]}

    # ---- configs (emulated incremental semantics) ------------------------
    def _merge_alter(self, resource_type, name_to_kv, describe):
        from kafka.admin import ConfigResource

        current = describe([k for k in name_to_kv])
        resources = []
        for name, kv in name_to_kv.items():
            merged = dict(current.get(name, {}))
            for k, v in kv.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = str(v)
            resources.append(ConfigResource(resource_type, str(name),
                                            configs=merged))
        self._admin.alter_configs(resources)

    def alter_broker_configs(self, configs: Mapping[int, Mapping[str, str]]) -> None:
        from kafka.admin import ConfigResourceType

        self._merge_alter(ConfigResourceType.BROKER, dict(configs),
                          self.describe_broker_configs)

    def alter_topic_configs(self, configs: Mapping[str, Mapping[str, str]]) -> None:
        from kafka.admin import ConfigResourceType

        self._merge_alter(ConfigResourceType.TOPIC, dict(configs),
                          self.describe_topic_configs)

    def _describe(self, resource_type, names):
        from kafka.admin import ConfigResource

        resp = self._admin.describe_configs(
            [ConfigResource(resource_type, str(n)) for n in names])
        out = {}
        for r in resp:
            resources = getattr(r, "resources", None)
            if resources is None:
                raise RuntimeError(
                    f"unexpected DescribeConfigs response shape: {type(r)!r} "
                    "has no 'resources' field (kafka-python version drift?)")
            for res in resources:
                # DescribeConfigsResponse resource tuple:
                # (error_code, error_message, resource_type, resource_name,
                #  config_entries). Named access when available, positional
                #  fallback with an explicit arity check.
                if hasattr(res, "resource_name"):
                    rname, entries = res.resource_name, res.config_entries
                else:
                    if len(res) < 5:
                        raise RuntimeError(
                            f"unexpected DescribeConfigs resource arity "
                            f"{len(res)}: {res!r}")
                    _err, _msg, _rtype, rname, entries = res[:5]
                out[rname] = {e[0]: e[1] for e in entries}
        return out

    def describe_broker_configs(self, brokers: Iterable[int]
                                ) -> dict[int, dict[str, str]]:
        from kafka.admin import ConfigResourceType

        raw = self._describe(ConfigResourceType.BROKER, list(brokers))
        return {int(k): v for k, v in raw.items()}

    def describe_topic_configs(self, topics: Iterable[str]
                               ) -> dict[str, dict[str, str]]:
        from kafka.admin import ConfigResourceType

        return self._describe(ConfigResourceType.TOPIC, list(topics))

    # ---- log dirs (JBOD) -------------------------------------------------
    def _await_each(self, futures: dict[int, object]) -> dict[int, object]:
        """Wait for every future individually; failed/timed-out brokers are
        skipped instead of aborting the batch (KafkaAdminClient's
        _wait_for_futures raises on the FIRST failure, which would kill the
        executor's poll thread because one broker was unreachable)."""
        out: dict[int, object] = {}
        for broker, f in futures.items():
            try:
                self._admin._wait_for_futures([f])
            except Exception:  # noqa: BLE001 — per-broker degradation
                import logging

                logging.getLogger(__name__).warning(
                    "logdir request to broker %s failed", broker,
                    exc_info=True)
                continue
            if f.succeeded():
                out[broker] = f.value
        return out

    def _logdir_responses(self, brokers: Iterable[int] | None = None,
                          ) -> dict[int, object]:
        """One DescribeLogDirs response PER BROKER (KafkaAdminClient's
        describe_log_dirs() only asks the least-loaded node; logdir state is
        broker-local). ``brokers`` restricts the fan-out — the executor
        passes only the brokers with in-flight moves, matching
        ExecutorAdminUtils.getLogdirInfoForExecutingReplicaMove."""
        targets = set(brokers) if brokers is not None else self.alive_brokers()
        from kafka.protocol.admin import DescribeLogDirsRequest_v0

        futures = {b: self._admin._send_request_to_node(
            b, DescribeLogDirsRequest_v0()) for b in targets}
        return self._await_each(futures)

    def describe_logdirs(self) -> dict[int, dict[str, bool]]:
        """broker -> {log_dir: healthy} (DiskFailureDetector's view)."""
        out: dict[int, dict[str, bool]] = {}
        for broker, resp in self._logdir_responses().items():
            dirs: dict[str, bool] = {}
            for entry in resp.log_dirs:
                error_code, log_dir = entry[0], entry[1]
                dirs[log_dir] = error_code == 0
            out[broker] = dirs
        return out

    def replica_logdirs(self, brokers: Iterable[int] | None = None,
                        ) -> dict[tuple[str, int, int], str]:
        """(topic, partition, broker) -> current log dir. Future (in-flight
        move) entries are skipped so completion polling sees the move only
        once the broker promoted the future replica."""
        out: dict[tuple[str, int, int], str] = {}
        for broker, resp in self._logdir_responses(brokers).items():
            for entry in resp.log_dirs:
                log_dir, topics = entry[1], entry[2]
                for name, partitions in topics:
                    for p in partitions:
                        idx, is_future = p[0], bool(p[3]) if len(p) > 3 else False
                        if not is_future:
                            out[(name, idx, broker)] = log_dir
        return out

    def alter_replica_logdirs(
            self, moves) -> list[tuple[str, int, int]]:
        """((topic, partition), broker, destination_dir) batch →
        AlterReplicaLogDirs (API key 34) sent to each affected broker
        (ExecutorAdminUtils.executeIntraBrokerReplicaMovements). Returns the
        (topic, partition, broker) keys the brokers REJECTED (per-partition
        error codes, e.g. LOG_DIR_NOT_FOUND/KAFKA_STORAGE_ERROR) so the
        executor can DEAD-mark them immediately instead of polling a move
        that will never happen."""
        by_broker: dict[int, dict[str, dict[str, list[int]]]] = {}
        for (topic, part), broker, dst in moves:
            by_broker.setdefault(broker, {}).setdefault(dst, {}) \
                .setdefault(topic, []).append(part)
        req_cls = _alter_replica_logdirs_request()
        futures = {}
        for broker, by_dir in by_broker.items():
            dirs = [(path, [(topic, parts) for topic, parts in topics.items()])
                    for path, topics in by_dir.items()]
            futures[broker] = self._admin._send_request_to_node(
                broker, req_cls(dirs=dirs))
        responses = self._await_each(futures)
        failed: list[tuple[str, int, int]] = []
        for broker in by_broker:
            resp = responses.get(broker)
            if resp is None:
                # Entire broker request failed: every move on it is failed.
                failed.extend((t, p, broker)
                              for by_dir in [by_broker[broker]]
                              for topics in by_dir.values()
                              for t, parts in topics.items() for p in parts)
                continue
            for name, partitions in resp.responses:
                for idx, error_code in partitions:
                    if error_code != 0:
                        failed.append((name, idx, broker))
        return failed

    def close(self) -> None:
        self._admin.close()


def _alter_replica_logdirs_request():
    """kafka-python ships DescribeLogDirs but (in some versions) not
    AlterReplicaLogDirs — define the v0 wire schema locally when absent."""
    try:
        from kafka.protocol.admin import AlterReplicaLogDirsRequest_v0
        return AlterReplicaLogDirsRequest_v0
    except ImportError:
        from kafka.protocol.api import Request, Response
        from kafka.protocol.types import Array, Int16, Int32, Schema, String

        class AlterReplicaLogDirsResponse_v0(Response):
            API_KEY = 34
            API_VERSION = 0
            SCHEMA = Schema(
                ("throttle_time_ms", Int32),
                ("responses", Array(
                    ("name", String("utf-8")),
                    ("partitions", Array(
                        ("partition_index", Int32),
                        ("error_code", Int16))))))

        class AlterReplicaLogDirsRequest_v0(Request):
            API_KEY = 34
            API_VERSION = 0
            RESPONSE_TYPE = AlterReplicaLogDirsResponse_v0
            SCHEMA = Schema(
                ("dirs", Array(
                    ("path", String("utf-8")),
                    ("topics", Array(
                        ("name", String("utf-8")),
                        ("partitions", Array(Int32)))))))

        return AlterReplicaLogDirsRequest_v0
