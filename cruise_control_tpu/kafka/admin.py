"""AdminBackend over kafka-python's KafkaAdminClient.

Reference parity: executor/ExecutionUtils.java:483
(alterPartitionReassignments), :433 (electLeaders),
listPartitionsBeingReassigned (Executor.java:1238), incremental
alter-configs for throttles (ReplicationThrottleHelper.java) and
describeLogDirs (DiskFailureDetector.java).

kafka-python notes (>=2.1 — the KIP-455 reassignment and leader-election
APIs arrived with the 2.1+ revival):
- ``alter_partition_reassignments`` / ``list_partition_reassignments``
  implement KIP-455 (cancel = target ``None``).
- ``perform_leader_election`` with PREFERRED election type maps
  electLeaders.
- Config alteration is the legacy (non-incremental) AlterConfigs: this
  binding emulates incremental semantics by describing first and merging
  (value ``None`` deletes a key) — same observable behavior as the
  reference's IncrementalAlterConfigs path.
"""

from __future__ import annotations

from typing import Iterable, Mapping

from ..executor.admin import PartitionState
from . import require_kafka


class KafkaAdminBackend:
    """Implements ``executor.admin.AdminBackend`` against a live cluster."""

    def __init__(self, bootstrap_servers: str, client_id: str = "cruise-control-tpu",
                 request_timeout_ms: int = 30_000, **kwargs):
        require_kafka("KafkaAdminBackend")
        from kafka import KafkaAdminClient

        self._admin = KafkaAdminClient(
            bootstrap_servers=bootstrap_servers, client_id=client_id,
            request_timeout_ms=request_timeout_ms, **kwargs)

    # ---- reassignment / leadership ---------------------------------------
    def alter_partition_reassignments(
            self, targets: Mapping[tuple[str, int], tuple[int, ...]]) -> None:
        from kafka.structs import TopicPartition

        self._admin.alter_partition_reassignments({
            TopicPartition(t, p): list(replicas)
            for (t, p), replicas in targets.items()})

    def cancel_partition_reassignments(
            self, partitions: Iterable[tuple[str, int]]) -> None:
        from kafka.structs import TopicPartition

        # KIP-455: a None target cancels the in-flight reassignment.
        self._admin.alter_partition_reassignments({
            TopicPartition(t, p): None for (t, p) in partitions})

    def elect_leaders(self, partitions: Iterable[tuple[str, int]]) -> None:
        from kafka.admin import ElectionType
        from kafka.structs import TopicPartition

        self._admin.perform_leader_election(
            ElectionType.PREFERRED,
            [TopicPartition(t, p) for (t, p) in partitions])

    def list_reassigning_partitions(self) -> list[tuple[str, int]]:
        listing = self._admin.list_partition_reassignments()
        return [(tp.topic, tp.partition) for tp in listing]

    # ---- metadata --------------------------------------------------------
    def describe_partitions(self) -> dict[tuple[str, int], PartitionState]:
        listing = self._admin.list_partition_reassignments()
        items = listing.items() if isinstance(listing, dict) else []
        reassigning = {(tp.topic, tp.partition): st for tp, st in items}
        out: dict[tuple[str, int], PartitionState] = {}
        for topic_meta in self._admin.describe_topics():
            topic = topic_meta["topic"]
            for pm in topic_meta["partitions"]:
                key = (topic, pm["partition"])
                ra = reassigning.get(key)
                out[key] = PartitionState(
                    topic=topic, partition=pm["partition"],
                    replicas=tuple(pm["replicas"]), leader=pm["leader"],
                    isr=tuple(pm["isr"]),
                    adding=tuple(getattr(ra, "adding_replicas", ()) or ()),
                    removing=tuple(getattr(ra, "removing_replicas", ()) or ()))
        return out

    def alive_brokers(self) -> set[int]:
        return {b["node_id"] if isinstance(b, dict) else b.nodeId
                for b in self._admin.describe_cluster()["brokers"]}

    # ---- configs (emulated incremental semantics) ------------------------
    def _merge_alter(self, resource_type, name_to_kv, describe):
        from kafka.admin import ConfigResource

        current = describe([k for k in name_to_kv])
        resources = []
        for name, kv in name_to_kv.items():
            merged = dict(current.get(name, {}))
            for k, v in kv.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = str(v)
            resources.append(ConfigResource(resource_type, str(name),
                                            configs=merged))
        self._admin.alter_configs(resources)

    def alter_broker_configs(self, configs: Mapping[int, Mapping[str, str]]) -> None:
        from kafka.admin import ConfigResourceType

        self._merge_alter(ConfigResourceType.BROKER, dict(configs),
                          self.describe_broker_configs)

    def alter_topic_configs(self, configs: Mapping[str, Mapping[str, str]]) -> None:
        from kafka.admin import ConfigResourceType

        self._merge_alter(ConfigResourceType.TOPIC, dict(configs),
                          self.describe_topic_configs)

    def _describe(self, resource_type, names):
        from kafka.admin import ConfigResource

        resp = self._admin.describe_configs(
            [ConfigResource(resource_type, str(n)) for n in names])
        out = {}
        for r in resp:
            for res in r.resources:
                _err, _msg, _rtype, rname, entries = res[:5]
                out[rname] = {e[0]: e[1] for e in entries}
        return out

    def describe_broker_configs(self, brokers: Iterable[int]
                                ) -> dict[int, dict[str, str]]:
        from kafka.admin import ConfigResourceType

        raw = self._describe(ConfigResourceType.BROKER, list(brokers))
        return {int(k): v for k, v in raw.items()}

    def describe_topic_configs(self, topics: Iterable[str]
                               ) -> dict[str, dict[str, str]]:
        from kafka.admin import ConfigResourceType

        return self._describe(ConfigResourceType.TOPIC, list(topics))

    # ---- log dirs (JBOD) -------------------------------------------------
    def describe_logdirs(self) -> dict[int, dict[str, bool]]:
        resp = self._admin.describe_log_dirs()
        out: dict[int, dict[str, bool]] = {}
        for broker_id, dirs in getattr(resp, "items", lambda: [])():
            out[broker_id] = {d.log_dir: d.error_code == 0 for d in dirs}
        return out

    def close(self) -> None:
        self._admin.close()
