"""AdminBackend over the framework's own wire client.

Reference parity: executor/ExecutionUtils.java:483
(alterPartitionReassignments), :433 (electLeaders),
listPartitionsBeingReassigned (Executor.java:1238), incremental
alter-configs for throttles (ReplicationThrottleHelper.java),
describeLogDirs (DiskFailureDetector.java) and alterReplicaLogDirs
(ExecutorAdminUtils.executeIntraBrokerReplicaMovements).

No external Kafka client: every call goes through
``kafka.wire.WireClient`` — the same codec stack the embedded
integration broker speaks, so this binding is integration-tested against
real wire bytes in every environment (``tests/test_wire_integration.py``),
not just where a client library happens to be installed.
"""

from __future__ import annotations

import logging
from typing import Iterable, Mapping, Sequence

from ..executor.admin import PartitionState
from ..utils.resilience import RetryPolicy
from .wire import messages as m
from .wire.client import WireClient

LOG = logging.getLogger(__name__)


class KafkaAdminBackend:
    """Implements ``executor.admin.AdminBackend`` against a live cluster."""

    def __init__(self, bootstrap_servers: str,
                 client_id: str = "cruise-control-tpu",
                 request_timeout_ms: int = 30_000,
                 client: WireClient | None = None,
                 view_snapshot_ttl_s: float = 5.0,
                 retry_policy: RetryPolicy | None = None):
        self._client = client or WireClient(
            bootstrap_servers, client_id=client_id,
            timeout_s=request_timeout_ms / 1000.0)
        # Per-broker request resilience (round 9): broker-local calls
        # (DescribeLogDirs) retry under the policy before the broker is
        # written off for the sweep.
        self._retry_policy = retry_policy
        # Movement-strategy views (partition_size etc.) are called once per
        # TASK while sorting a plan; a short-TTL snapshot turns N-task sorts
        # into one metadata + one logdir sweep instead of N full sweeps.
        self._view_ttl_s = view_snapshot_ttl_s
        self._view_cache: dict[str, tuple[float, object]] = {}

    # ---- reassignment / leadership ---------------------------------------
    def alter_partition_reassignments(
            self, targets: Mapping[tuple[str, int], tuple[int, ...]]) -> None:
        self._client.alter_partition_reassignments(
            {tp: list(replicas) for tp, replicas in targets.items()})

    def cancel_partition_reassignments(
            self, partitions: Iterable[tuple[str, int]]) -> None:
        # KIP-455: a null target cancels the in-flight reassignment.
        self._client.alter_partition_reassignments(
            {tp: None for tp in partitions})

    def elect_leaders(self, partitions: Iterable[tuple[str, int]]) -> None:
        failed = self._client.elect_leaders(partitions, m.ELECTION_PREFERRED)
        for topic, part, code in failed:
            # Per-partition election failures (e.g. preferred replica out of
            # ISR) degrade to the poll loop: the executor observes leadership
            # via metadata and times the task out if it never lands.
            LOG.warning("leader election failed for %s-%d: %s", topic, part,
                        m.ERROR_NAMES.get(code, code))

    def list_reassigning_partitions(self) -> list[tuple[str, int]]:
        return list(self._client.list_partition_reassignments())

    # ---- metadata --------------------------------------------------------
    def describe_partitions(self) -> dict[tuple[str, int], PartitionState]:
        reassigning = self._client.list_partition_reassignments()
        meta = self._client.metadata(topics=None)
        out: dict[tuple[str, int], PartitionState] = {}
        for t in meta["topics"]:
            if t["error_code"] != m.NONE:
                continue
            for pm in t["partitions"]:
                key = (t["name"], pm["index"])
                ra = reassigning.get(key, {})
                out[key] = PartitionState(
                    topic=t["name"], partition=pm["index"],
                    replicas=tuple(pm["replicas"]), leader=pm["leader"],
                    isr=tuple(pm["isr"]),
                    adding=tuple(ra.get("adding", ())),
                    removing=tuple(ra.get("removing", ())))
        return out

    def alive_brokers(self) -> set[int]:
        return self._client.alive_broker_ids()

    def broker_racks(self) -> dict[int, str]:
        """broker id -> rack from cluster metadata (brokers without a
        configured broker.rack are omitted). LoadMonitor refreshes this
        per model build so late-joining brokers get their racks."""
        meta = self._client.metadata(topics=[])
        return {b["node_id"]: b["rack"] for b in meta["brokers"]
                if b.get("rack")}

    def broker_hosts(self) -> dict[int, str]:
        """broker id -> advertised host from cluster metadata (the
        Host.java topology level: rackless brokers fall back to their host
        as the fault domain, and co-hosted brokers share it)."""
        meta = self._client.metadata(topics=[])
        return {b["node_id"]: b["host"] for b in meta["brokers"]
                if b.get("host")}

    # ---- configs (real KIP-339 incremental semantics) --------------------
    def alter_broker_configs(self,
                             configs: Mapping[int, Mapping[str, str]]) -> None:
        self._client.incremental_alter_configs(m.RESOURCE_BROKER,
                                               dict(configs))

    def alter_topic_configs(self,
                            configs: Mapping[str, Mapping[str, str]]) -> None:
        self._client.incremental_alter_configs(m.RESOURCE_TOPIC,
                                               dict(configs))

    def describe_broker_configs(self, brokers: Iterable[int]
                                ) -> dict[int, dict[str, str]]:
        raw = self._client.describe_configs(m.RESOURCE_BROKER, list(brokers))
        return {int(k): v for k, v in raw.items()}

    def describe_topic_configs(self, topics: Iterable[str]
                               ) -> dict[str, dict[str, str]]:
        return self._client.describe_configs(m.RESOURCE_TOPIC, list(topics))

    # ---- log dirs (JBOD) -------------------------------------------------
    def _each_broker(self, brokers: Iterable[int] | None):
        """DescribeLogDirs is broker-local state: fan out per broker, and
        degrade per broker — one unreachable broker must not kill the
        executor's poll thread (ExecutorAdminUtils semantics). Each
        broker's request runs under the retry policy first; a broker
        that STILL fails is dropped from the sweep with a
        ``logdir_describe_failures_total{broker=}`` sensor, so a
        persistently unreachable broker shrinking the
        DiskFailureDetector's view is visible, not invisible."""
        from ..utils.resilience import call_with_resilience
        from ..utils.sensors import SENSORS
        targets = (set(brokers) if brokers is not None
                   else self._client.alive_broker_ids())
        for b in sorted(targets):
            try:
                yield b, call_with_resilience(
                    "admin.describe_log_dirs",
                    lambda b=b: self._client.describe_log_dirs(b),
                    policy=self._retry_policy)
            except (ConnectionError, TimeoutError, OSError,
                    m.KafkaProtocolError):
                LOG.warning("logdir request to broker %s failed", b,
                            exc_info=True)
                SENSORS.count("logdir_describe_failures",
                              labels={"broker": str(b)})

    def describe_logdirs(self) -> dict[int, dict[str, bool]]:
        """broker -> {log_dir: healthy} (DiskFailureDetector's view)."""
        return {b: {r["log_dir"]: r["error_code"] == m.NONE for r in results}
                for b, results in self._each_broker(None)}

    def replica_logdirs(self, brokers: Iterable[int] | None = None,
                        ) -> dict[tuple[str, int, int], str]:
        """(topic, partition, broker) -> current log dir. Future (in-flight
        move) entries are skipped so completion polling sees the move only
        once the broker promoted the future replica."""
        out: dict[tuple[str, int, int], str] = {}
        for b, results in self._each_broker(brokers):
            for r in results:
                for t in r["topics"]:
                    for p in t["partitions"]:
                        if not p["is_future_key"]:
                            out[(t["name"], p["partition_index"], b)] = \
                                r["log_dir"]
        return out

    def alter_replica_logdirs(
            self, moves: Sequence[tuple[tuple[str, int], int, str]],
            ) -> list[tuple[str, int, int]]:
        """((topic, partition), broker, destination_dir) batch. Returns the
        (topic, partition, broker) keys the brokers REJECTED (per-partition
        error codes, e.g. LOG_DIR_NOT_FOUND/KAFKA_STORAGE_ERROR) so the
        executor can DEAD-mark them immediately instead of polling a move
        that will never happen."""
        by_broker: dict[int, dict[str, dict[str, list[int]]]] = {}
        for (topic, part), broker, dst in moves:
            by_broker.setdefault(broker, {}).setdefault(dst, {}) \
                .setdefault(topic, []).append(part)
        failed: list[tuple[str, int, int]] = []
        for broker, by_dir in by_broker.items():
            try:
                rejected = self._client.alter_replica_log_dirs(broker, by_dir)
            except (ConnectionError, m.KafkaProtocolError):
                LOG.warning("alter_replica_log_dirs to broker %s failed",
                            broker, exc_info=True)
                failed.extend(
                    (t, p, broker)
                    for topics in by_dir.values()
                    for t, parts in topics.items() for p in parts)
                continue
            failed.extend((t, p, broker) for t, p, _code in rejected)
        return failed

    # ---- movement-strategy views (strategy.ClusterView) ------------------
    # Called once per task while a plan is sorted; every view reads from a
    # TTL'd whole-cluster snapshot (one sweep per sort, not per task).
    def _view(self, key: str, compute):
        import time

        now = time.time()
        hit = self._view_cache.get(key)
        if hit is not None and now - hit[0] <= self._view_ttl_s:
            return hit[1]
        value = compute()
        self._view_cache[key] = (now, value)
        return value

    def _partitions_view(self) -> dict[tuple[str, int], PartitionState]:
        return self._view("partitions", self.describe_partitions)

    def _alive_view(self) -> set[int]:
        return self._view("alive", self.alive_brokers)

    def _sizes_view(self) -> dict[tuple[str, int, int], int]:
        def sweep():
            sizes: dict[tuple[str, int, int], int] = {}
            for b, results in self._each_broker(None):
                for r in results:
                    for t in r["topics"]:
                        for p in t["partitions"]:
                            # Skip future (in-flight JBOD move) entries:
                            # the partially-copied future replica shares
                            # the key and would under-report the size.
                            if not p["is_future_key"]:
                                sizes[(t["name"], p["partition_index"], b)] \
                                    = p["partition_size"]
            return sizes
        return self._view("sizes", sweep)

    def _min_isr_view(self) -> dict[str, int]:
        def sweep():
            topics = {t for t, _p in self._partitions_view()}
            out = {}
            for t, cfg in self.describe_topic_configs(topics).items():
                # ccsa: ok[CCSA005] KAFKA topic-config key space
                raw = cfg.get("min.insync.replicas")
                try:
                    out[t] = int(raw) if raw is not None else 1
                except (TypeError, ValueError):
                    out[t] = 1
            return out
        return self._view("min_isr", sweep)

    def partition_size(self, topic: str, partition: int) -> float:
        """Max on-disk size across replicas (DescribeLogDirs partition_size
        — PrioritizeLargeReplicaMovementStrategy's sort key)."""
        state = self._partitions_view().get((topic, partition))
        if state is None:
            return 0.0
        sizes = self._sizes_view()
        return float(max((sizes.get((topic, partition, b), 0)
                          for b in state.replicas), default=0))

    def is_under_replicated(self, topic: str, partition: int) -> bool:
        """ISR smaller than the replica set
        (PostponeUrpReplicaMovementStrategy's predicate)."""
        state = self._partitions_view().get((topic, partition))
        return state is not None and len(state.isr) < len(state.replicas)

    def is_under_min_isr_with_offline(self, topic: str,
                                      partition: int) -> bool:
        """Live ISR below topic min.insync.replicas AND an offline replica
        present (PrioritizeMinIsrWithOfflineReplicasStrategy's predicate)."""
        state = self._partitions_view().get((topic, partition))
        if state is None:
            return False
        alive = self._alive_view()
        has_offline = any(b not in alive for b in state.replicas)
        min_isr = self._min_isr_view().get(topic, 1)
        live_isr = sum(1 for b in state.isr if b in alive)
        return has_offline and live_isr < min_isr

    def close(self) -> None:
        self._client.close()
