"""Monitor layer: metric ingestion, windowed aggregation, model generation.

Reference parity: monitor/ (LoadMonitor, LoadMonitorTaskRunner, sampling/,
metricdefinition/ lives in ..metricdef).
"""

from .capacity import (
    BrokerCapacityConfigResolver, FileCapacityResolver, StaticCapacityResolver,
)
from .load_monitor import (
    LoadMonitor, LoadMonitorState, ModelCompletenessRequirements,
)
from .task_runner import LoadMonitorTaskRunner, RunnerState, SamplingMode

__all__ = [
    "BrokerCapacityConfigResolver", "FileCapacityResolver", "LoadMonitor",
    "LoadMonitorState", "LoadMonitorTaskRunner",
    "ModelCompletenessRequirements", "RunnerState", "SamplingMode",
    "StaticCapacityResolver",
]
