"""Sampling task scheduler: the monitor's background loop.

Reference parity: monitor/task/LoadMonitorTaskRunner.java:33,245 (state
machine NOT_STARTED → RUNNING/SAMPLING ↔ PAUSED, with BOOTSTRAPPING,
TRAINING and LOADING excursions), SamplingTask / BootstrapTask /
SampleLoadingTask. The executor pauses sampling around proposal execution
(Executor.java:1408-1424) via set_mode(ONGOING_EXECUTION).
"""

from __future__ import annotations

import enum
import logging
import threading


from ..executor.admin import AdminBackend
from .sampling.fetcher import MetricFetcherManager
from .sampling.sampler import now_ms
from .sampling.sample_store import SampleStore

LOG = logging.getLogger(__name__)


class RunnerState(enum.Enum):
    NOT_STARTED = "NOT_STARTED"
    LOADING = "LOADING"
    RUNNING = "RUNNING"
    SAMPLING = "SAMPLING"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"
    PAUSED = "PAUSED"


class SamplingMode(enum.Enum):
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    ONGOING_EXECUTION = "ONGOING_EXECUTION"  # reduced-scope sampling during moves


class LoadMonitorTaskRunner:
    def __init__(self, fetcher: MetricFetcherManager, metadata: AdminBackend,
                 sample_store: SampleStore, sampling_interval_ms: int):
        self._fetcher = fetcher
        self._metadata = metadata
        self._store = sample_store
        self._interval_ms = int(sampling_interval_ms)
        self._state = RunnerState.NOT_STARTED
        self._mode = SamplingMode.RUNNING
        self._mode_reason = ""
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_sample_ms = 0
        self._samples_loaded = 0

    # -- lifecycle --------------------------------------------------------
    def start(self, block_on_load: bool = True) -> None:
        with self._lock:
            if self._state is not RunnerState.NOT_STARTED:
                return
            self._state = RunnerState.LOADING
        if block_on_load:
            self._load_samples()
            self._start_sampling_thread()
        else:
            def boot():
                self._load_samples()
                self._start_sampling_thread()
            threading.Thread(target=boot, name="sample-loading", daemon=True).start()

    def _load_samples(self) -> None:
        try:
            loaded = self._store.load_samples()
            self._samples_loaded = self._fetcher.replay(loaded)
            if self._samples_loaded:
                LOG.info("replayed %d samples from sample store", self._samples_loaded)
        except Exception:
            LOG.exception("sample store replay failed; starting cold")
        with self._lock:
            self._state = RunnerState.RUNNING

    def _start_sampling_thread(self) -> None:
        self._thread = threading.Thread(target=self._run, name="sampling-task",
                                        daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- mode / state ------------------------------------------------------
    def set_mode(self, mode: SamplingMode, reason: str = "") -> None:
        with self._lock:
            self._mode = mode
            self._mode_reason = reason
            if self._state in (RunnerState.RUNNING, RunnerState.PAUSED):
                self._state = (RunnerState.PAUSED if mode is SamplingMode.PAUSED
                               else RunnerState.RUNNING)

    @property
    def sampling_mode(self) -> SamplingMode:
        return self._mode

    @property
    def state_name(self) -> str:
        return self._state.value

    @property
    def samples_loaded(self) -> int:
        return self._samples_loaded

    # -- the loop ----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self._interval_ms / 1000.0):
            if self._mode is SamplingMode.PAUSED:
                continue
            self.run_sampling_once()

    def run_sampling_once(self, end_ms: int | None = None) -> None:
        """One sampling interval (SamplingTask.run); callable directly for
        deterministic tests and simulations."""
        end = end_ms if end_ms is not None else now_ms()
        start = self._last_sample_ms or (end - self._interval_ms)
        with self._lock:
            if self._state is RunnerState.RUNNING:
                self._state = RunnerState.SAMPLING
        from .sampling.fetcher import PartialWindowError
        try:
            partitions = self._metadata.describe_partitions()
            self._fetcher.fetch_metric_samples(partitions, start, end)
            self._last_sample_ms = end
        except PartialWindowError as e:
            # The window is below the completeness floor and LOST either
            # way — advance the clock so the next interval fetches only
            # ITS span. Leaving start pinned would re-fetch the whole
            # outage range every interval (O(outage²) sampler work).
            LOG.warning("sampling interval [%s, %s) rejected: %s",
                        start, end, e)
            self._last_sample_ms = end
        except Exception:
            LOG.exception("sampling interval [%s, %s) failed", start, end)
        finally:
            with self._lock:
                if self._state is RunnerState.SAMPLING:
                    self._state = RunnerState.RUNNING

    def bootstrap(self, start_ms: int, end_ms: int, clear_metrics: bool = True,
                  ) -> None:
        """BootstrapTask.run: replay a historic range through the samplers
        window by window to warm the aggregators."""
        with self._lock:
            prev = self._state
            self._state = RunnerState.BOOTSTRAPPING
        try:
            if clear_metrics:
                self._fetcher.clear()
            partitions = self._metadata.describe_partitions()
            t = start_ms
            while t < end_ms and not self._stop.is_set():
                nxt = min(t + self._interval_ms, end_ms)
                try:
                    self._fetcher.fetch_metric_samples(partitions, t, nxt,
                                                       store=False)
                except Exception:  # noqa: BLE001 — one bad window (e.g.
                    # below the partial-completeness floor, or a range
                    # predating available metrics) must not abort the
                    # whole historic replay; later windows still warm.
                    LOG.warning("bootstrap window [%s, %s) failed; "
                                "continuing", t, nxt, exc_info=True)
                t = nxt
            self._last_sample_ms = end_ms
        finally:
            with self._lock:
                self._state = prev
