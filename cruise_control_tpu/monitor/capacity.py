"""Broker capacity resolution.

Reference parity: config/BrokerCapacityConfigFileResolver (reads
capacity.json / capacityJBOD.json / capacityCores.json) behind the
BrokerCapacityConfigResolver SPI. Capacity units match the reference: DISK
in MB, CPU in percent (0-100, cores×100 in the cores format), NW_IN/NW_OUT
in KB/s. Broker id -1 is the default capacity applied to brokers without an
explicit entry.
"""

from __future__ import annotations

import json
from typing import Mapping, Protocol

from ..common.resources import Resource

DEFAULT_BROKER_ID = -1
DEFAULT_CAPACITY = {Resource.CPU: 100.0, Resource.NW_IN: 10_000.0,
                    Resource.NW_OUT: 10_000.0, Resource.DISK: 500_000.0}


class BrokerCapacityConfigResolver(Protocol):
    def capacity_for(self, broker_id: int) -> dict[Resource, float]: ...

    def disk_capacity_by_logdir(self, broker_id: int) -> dict[str, float] | None: ...

    def is_estimated(self, broker_id: int) -> bool:
        """True when the broker's capacity is an estimate rather than an
        explicit config entry (BrokerCapacityInfo.estimationInfo). Gated by
        the allow_capacity_estimation request parameter."""
        ...


class CapacityEstimationError(ValueError):
    """allow_capacity_estimation=false but a broker capacity is estimated
    (BrokerCapacityResolutionException)."""


class StaticCapacityResolver:
    """Fixed capacities from a mapping (tests / synthetic clusters): the
    operator supplied every value programmatically, so nothing is an
    estimate."""

    def __init__(self, by_broker: Mapping[int, Mapping[Resource, float]],
                 default: Mapping[Resource, float] | None = None):
        self._by_broker = {b: dict(c) for b, c in by_broker.items()}
        self._default = dict(default or DEFAULT_CAPACITY)

    def capacity_for(self, broker_id: int) -> dict[Resource, float]:
        return dict(self._by_broker.get(broker_id, self._default))

    def disk_capacity_by_logdir(self, broker_id: int):
        return None

    def is_estimated(self, broker_id: int) -> bool:
        return False


class FileCapacityResolver:
    """capacity.json formats:

    {"brokerCapacities": [{"brokerId": "-1"|"0"...,
       "capacity": {"DISK": "100000"            # flat MB, or
                    "DISK": {"/dir1": "50000", "/dir2": "50000"},  # JBOD
                    "CPU": "100" | {"num.cores": "8"},
                    "NW_IN": "10000", "NW_OUT": "10000"}}]}
    """

    def __init__(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        self._caps: dict[int, dict[Resource, float]] = {}
        self._logdirs: dict[int, dict[str, float]] = {}
        for entry in doc.get("brokerCapacities", []):
            bid = int(entry["brokerId"])
            cap = entry.get("capacity", {})
            out: dict[Resource, float] = {}
            disk = cap.get("DISK", DEFAULT_CAPACITY[Resource.DISK])
            if isinstance(disk, dict):
                dirs = {d: float(v) for d, v in disk.items()}
                self._logdirs[bid] = dirs
                out[Resource.DISK] = sum(dirs.values())
            else:
                out[Resource.DISK] = float(disk)
            cpu = cap.get("CPU", DEFAULT_CAPACITY[Resource.CPU])
            if isinstance(cpu, dict):  # capacityCores.json format
                # ccsa: ok[CCSA005] capacityCores.json field (reference
                # BrokerCapacityConfigFileResolver format), not a config key
                out[Resource.CPU] = float(cpu.get("num.cores", 1)) * 100.0
            else:
                out[Resource.CPU] = float(cpu)
            out[Resource.NW_IN] = float(cap.get("NW_IN", DEFAULT_CAPACITY[Resource.NW_IN]))
            out[Resource.NW_OUT] = float(cap.get("NW_OUT", DEFAULT_CAPACITY[Resource.NW_OUT]))
            self._caps[bid] = out

    def capacity_for(self, broker_id: int) -> dict[Resource, float]:
        if broker_id in self._caps:
            return dict(self._caps[broker_id])
        if DEFAULT_BROKER_ID in self._caps:
            return dict(self._caps[DEFAULT_BROKER_ID])
        return dict(DEFAULT_CAPACITY)

    def disk_capacity_by_logdir(self, broker_id: int):
        dirs = self._logdirs.get(broker_id, self._logdirs.get(DEFAULT_BROKER_ID))
        return dict(dirs) if dirs else None

    def is_estimated(self, broker_id: int) -> bool:
        """A broker served by the broker-id -1 default entry (or the
        builtin default) got an ESTIMATE, exactly the case
        BrokerCapacityConfigFileResolver marks with estimation info."""
        return broker_id not in self._caps
