"""Sampling subsystem: samplers, processor, sample store, fetch fan-out.

Reference parity: monitor/sampling/ (MetricSampler SPI, SampleStore SPI,
MetricFetcherManager, CruiseControlMetricsProcessor + holder/).
"""

from .fetcher import MetricFetcherManager, default_partition_assignor
from .holder import BrokerLoad, group_by_broker
from .processor import CruiseControlMetricsProcessor, ProcessorResult
from .sample_store import FileSampleStore, NoopSampleStore, SampleStore
from .sampler import (
    CruiseControlMetricsReporterSampler, InMemoryMetricsTransport,
    MetricSampler, NoopSampler, PrometheusMetricSampler, SamplerResult,
    SyntheticSampler,
)
from .samples import (
    BrokerEntity, BrokerMetricSample, PartitionEntity, PartitionMetricSample,
)

__all__ = [
    "BrokerEntity", "BrokerLoad", "BrokerMetricSample",
    "CruiseControlMetricsProcessor", "CruiseControlMetricsReporterSampler",
    "FileSampleStore", "InMemoryMetricsTransport", "MetricFetcherManager",
    "MetricSampler", "NoopSampleStore", "NoopSampler", "PartitionEntity",
    "PartitionMetricSample", "PrometheusMetricSampler", "ProcessorResult",
    "SampleStore", "SamplerResult", "SyntheticSampler",
    "default_partition_assignor", "group_by_broker",
]
