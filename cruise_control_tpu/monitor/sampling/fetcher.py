"""Metric fetch fan-out.

Reference parity: monitor/sampling/MetricFetcherManager.java:37-174 (N
fetcher threads over a pluggable MetricSamplerPartitionAssignor) and
SamplingFetcher.java (feeds aggregators + sample store).
"""

from __future__ import annotations

import logging
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Mapping

import numpy as np

from ...executor.admin import PartitionState
from ...utils.resilience import RetryPolicy, call_with_resilience
from .sampler import MetricSampler, SamplerResult
from .sample_store import SampleStore
from .samples import samples_to_matrix

LOG = logging.getLogger(__name__)


class PartialWindowError(RuntimeError):
    """The sampling interval fetched less than the configured
    completeness floor — the window is rejected rather than ingested
    (the task runner logs and the next interval retries)."""


def default_partition_assignor(partitions: Mapping[tuple[str, int], PartitionState],
                               num_fetchers: int) -> list[dict]:
    """DefaultMetricSamplerPartitionAssignor: deterministic spread of the
    partition universe across fetchers at TOPIC granularity. Keeping a
    topic's partitions in one bucket is load-bearing: the processor derives
    per-partition rates from topic-level rates using share weights over the
    partitions it sees, so splitting a topic across fetchers would make each
    fetcher attribute the full topic rate to its subset.

    The topic hash is ``crc32`` (NOT builtin ``hash``, which varies per
    process under PYTHONHASHSEED): topic→fetcher placement must survive
    restarts so per-fetcher sample stores and caches stay warm."""
    buckets: list[dict] = [{} for _ in range(num_fetchers)]
    for (topic, part), st in partitions.items():
        idx = zlib.crc32(topic.encode("utf-8")) % num_fetchers
        buckets[idx][(topic, part)] = st
    return buckets


class MetricFetcherManager:
    """Fans a sampling interval out over samplers and routes the returned
    samples into the two aggregators + the sample store."""

    def __init__(self, samplers: list[MetricSampler],
                 partition_aggregator, broker_aggregator,
                 sample_store: SampleStore,
                 assignor: Callable = default_partition_assignor,
                 num_fetchers: int | None = None,
                 retry_policy: RetryPolicy | None = None,
                 min_completeness: float = 0.0):
        if not samplers:
            raise ValueError("at least one sampler required")
        # Resilience (round 9): each fetcher retries its sampler under
        # the policy; a fetcher that still fails costs only ITS bucket.
        # The merged interval is accepted as a PARTIAL window while the
        # fetched fraction stays at or above ``min_completeness``
        # (reference parity: sampling completeness) and rejected with
        # PartialWindowError below it — degraded data beats no data,
        # but a mostly-empty window would poison the aggregates.
        self._retry_policy = retry_policy
        self._min_completeness = min_completeness
        # num.metric.fetchers fan-out (MetricFetcherManager.java:37-110):
        # the reference runs N fetcher threads each with its own sampler
        # instance. With one configured sampler and N > 1, clone it per
        # fetcher when it supports clone(); a sampler without clone() is
        # shared across threads (must then be thread-safe, like the
        # synthetic and noop samplers).
        n = num_fetchers or len(samplers)
        if len(samplers) == 1 and n > 1:
            base = samplers[0]
            clone = getattr(base, "clone", None)
            samplers = [base] + [clone() if clone else base
                                 for _ in range(n - 1)]
        self._samplers = samplers
        self._partition_agg = partition_aggregator
        self._broker_agg = broker_aggregator
        self._store = sample_store
        self._assignor = assignor
        self._pool = ThreadPoolExecutor(max_workers=len(samplers),
                                        thread_name_prefix="metric-fetcher")
        self._lock = threading.Lock()

    def fetch_metric_samples(self, partitions: Mapping[tuple[str, int], PartitionState],
                             start_ms: int, end_ms: int,
                             store: bool = True) -> SamplerResult:
        from ...utils.tracing import TRACER
        with TRACER.span("monitor.sample_fetch", operation="sampling",
                         num_partitions=len(partitions),
                         num_fetchers=len(self._samplers)) as sp:
            buckets = self._assignor(partitions, len(self._samplers))
            futures = [self._pool.submit(self._fetch_one, s, b,
                                         start_ms, end_ms)
                       for s, b in zip(self._samplers, buckets)]
            merged = SamplerResult([], [], 0)
            for f in futures:
                r = f.result()
                merged.partition_samples.extend(r.partition_samples)
                merged.broker_samples.extend(r.broker_samples)
                merged.skipped_partitions += r.skipped_partitions
            total = len(partitions)
            completeness = 1.0 if total == 0 \
                else 1.0 - merged.skipped_partitions / total
            if total and completeness < self._min_completeness:
                from ...utils.sensors import SENSORS
                SENSORS.count("monitor_windows_rejected")
                sp.set(completeness=round(completeness, 4), rejected=True)
                raise PartialWindowError(
                    f"sampling interval [{start_ms}, {end_ms}) fetched "
                    f"{completeness:.1%} of {total} partitions, below the "
                    f"{self._min_completeness:.1%} completeness floor")
            if merged.skipped_partitions:
                # Degraded but above the floor: accept the partial window
                # (the reference's sampling-completeness semantics) and
                # make the degradation visible.
                from ...utils.sensors import SENSORS
                SENSORS.count("monitor_partial_windows")
                sp.set(partial=True)
            self._ingest(merged, end_ms, store)
            sp.set(partition_samples=len(merged.partition_samples),
                   broker_samples=len(merged.broker_samples),
                   skipped_partitions=merged.skipped_partitions,
                   completeness=round(completeness, 4))
            return merged

    def _fetch_one(self, sampler: MetricSampler, bucket, start_ms, end_ms):
        try:
            return call_with_resilience(
                "sampler.get_samples",
                lambda: sampler.get_samples(bucket, start_ms, end_ms),
                policy=self._retry_policy)
        except Exception:
            LOG.exception("metric sampler failed for interval [%s, %s)",
                          start_ms, end_ms)
            # sampling-fetch failure rate (LoadMonitorTaskRunner sensors).
            # Per-fetcher degradation: this bucket's partitions count as
            # skipped; the other fetchers' samples still land.
            from ...utils.sensors import SENSORS
            SENSORS.count("monitor_sampling_fetch_failures")
            return SamplerResult([], [], len(bucket))

    def _ingest(self, result: SamplerResult, time_ms: int, store: bool) -> None:
        with self._lock:
            ents, vals = samples_to_matrix(result.partition_samples)
            if ents:
                self._partition_agg.add_samples_batch(ents, time_ms, vals)
            ents, vals = samples_to_matrix(result.broker_samples)
            if ents:
                self._broker_agg.add_samples_batch(ents, time_ms, vals)
        if store:
            self._store.store_samples(result)

    def clear(self) -> None:
        """Drop all aggregated windows (bootstrap with clear-metrics)."""
        with self._lock:
            self._partition_agg.clear()
            self._broker_agg.clear()

    def replay(self, result: SamplerResult) -> int:
        """Load store-replayed samples into the aggregators at their original
        timestamps (KafkaSampleStore.loadSamples warm-start path)."""
        count = 0
        with self._lock:
            for s in result.partition_samples:
                self._partition_agg.add_sample(s.entity, s.time_ms,
                                               np.asarray(s.values, dtype=np.float32))
                count += 1
            for s in result.broker_samples:
                self._broker_agg.add_sample(s.entity, s.time_ms,
                                            np.asarray(s.values, dtype=np.float32))
                count += 1
        return count

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
        for s in self._samplers:
            s.close()
