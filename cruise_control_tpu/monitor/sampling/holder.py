"""Per-broker raw-metric accumulation for one sampling interval.

Reference parity: monitor/sampling/holder/BrokerLoad.java (328) — collects
the broker/topic/partition raw metrics reported by each broker between two
sampling points and answers the derived questions the processor asks
(leader bytes in/out, replication bytes in, CPU util, per-topic rates).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

from ...metricdef.raw_metric_type import MetricScope, RawMetricType
from ...reporter.metrics import CruiseControlMetric

R = RawMetricType


@dataclasses.dataclass
class BrokerLoad:
    broker_id: int
    broker_metrics: dict[RawMetricType, list[float]] = dataclasses.field(
        default_factory=lambda: defaultdict(list))
    topic_metrics: dict[tuple[str, RawMetricType], list[float]] = dataclasses.field(
        default_factory=lambda: defaultdict(list))
    partition_sizes: dict[tuple[str, int], float] = dataclasses.field(
        default_factory=dict)

    def record(self, m: CruiseControlMetric) -> None:
        if m.scope is MetricScope.BROKER:
            self.broker_metrics[m.raw_type].append(m.value)
        elif m.scope is MetricScope.TOPIC:
            self.topic_metrics[(m.topic, m.raw_type)].append(m.value)
        else:  # PARTITION_SIZE is the only partition-scope metric
            self.partition_sizes[(m.topic, m.partition)] = m.value

    # -- derived views ----------------------------------------------------
    def broker_metric(self, raw: RawMetricType, default: float = 0.0) -> float:
        vals = self.broker_metrics.get(raw)
        return sum(vals) / len(vals) if vals else default

    def has_broker_metric(self, raw: RawMetricType) -> bool:
        return bool(self.broker_metrics.get(raw))

    def topic_metric(self, topic: str, raw: RawMetricType,
                     default: float = 0.0) -> float:
        vals = self.topic_metrics.get((topic, raw))
        return sum(vals) / len(vals) if vals else default

    @property
    def cpu_util(self) -> float:
        return self.broker_metric(R.BROKER_CPU_UTIL)

    @property
    def leader_bytes_in(self) -> float:
        return self.broker_metric(R.ALL_TOPIC_BYTES_IN)

    @property
    def leader_bytes_out(self) -> float:
        return self.broker_metric(R.ALL_TOPIC_BYTES_OUT)

    @property
    def follower_bytes_in(self) -> float:
        return self.broker_metric(R.ALL_TOPIC_REPLICATION_BYTES_IN)

    def topics(self) -> set[str]:
        return ({t for (t, _raw) in self.topic_metrics}
                | {t for (t, _p) in self.partition_sizes})

    def partition_size(self, topic: str, partition: int) -> float:
        return self.partition_sizes.get((topic, partition), 0.0)


def group_by_broker(metrics) -> dict[int, BrokerLoad]:
    loads: dict[int, BrokerLoad] = {}
    for m in metrics:
        loads.setdefault(m.broker_id, BrokerLoad(m.broker_id)).record(m)
    return loads


def broker_loads_from_columns(cols) -> dict[int, BrokerLoad]:
    """Columnar ``group_by_broker``: one numpy grouping pass instead of a
    ``record()`` call per metric. Per-(key) means are stored as one-element
    lists so every ``BrokerLoad`` view behaves identically to the scalar
    path (the views average their lists); partition sizes keep
    LAST-observation-wins semantics like ``record``."""
    import numpy as np

    loads: dict[int, BrokerLoad] = {}
    if not len(cols):
        return loads
    scope = cols.scope

    def mean_by(keys_2d, values):
        """(unique key rows, mean value per key) via lexicographic sort."""
        uniq, inv = np.unique(keys_2d, axis=0, return_inverse=True)
        sums = np.zeros(len(uniq))
        counts = np.zeros(len(uniq))
        np.add.at(sums, inv, values)
        np.add.at(counts, inv, 1.0)
        return uniq, sums / counts

    b_rows = np.nonzero(scope == 0)[0]
    if b_rows.size:
        uniq, means = mean_by(
            np.stack([cols.broker[b_rows], cols.raw_id[b_rows]], axis=1),
            cols.value[b_rows])
        for (bid, rid), v in zip(uniq.tolist(), means.tolist()):
            loads.setdefault(bid, BrokerLoad(bid)) \
                .broker_metrics[RawMetricType(rid)].append(v)
    t_rows = np.nonzero(scope == 1)[0]
    if t_rows.size:
        uniq, means = mean_by(
            np.stack([cols.broker[t_rows], cols.topic_id[t_rows],
                      cols.raw_id[t_rows]], axis=1), cols.value[t_rows])
        for (bid, tid, rid), v in zip(uniq.tolist(), means.tolist()):
            loads.setdefault(bid, BrokerLoad(bid)) \
                .topic_metrics[(cols.topics[tid], RawMetricType(rid))].append(v)
    p_rows = np.nonzero(scope == 2)[0]
    if p_rows.size:
        # Last observation wins: iterate brokers, bulk-build each dict
        # from the LAST occurrence per (topic, partition).
        for bid in np.unique(cols.broker[p_rows]).tolist():
            rows = p_rows[cols.broker[p_rows] == bid]
            key = (cols.topic_id[rows].astype(np.int64) << 32) \
                | cols.partition[rows].astype(np.int64)
            # np.unique keeps the FIRST occurrence of each key in the order
            # given; reversing makes that the last observation.
            rev = rows[::-1]
            rkey = key[::-1]
            _u, first = np.unique(rkey, return_index=True)
            keep = rev[first]
            load = loads.setdefault(bid, BrokerLoad(bid))
            load.partition_sizes.update(zip(
                ((cols.topics[t], int(p)) for t, p in
                 zip(cols.topic_id[keep].tolist(),
                     cols.partition[keep].tolist())),
                cols.value[keep].tolist()))
    return loads
