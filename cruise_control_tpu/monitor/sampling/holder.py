"""Per-broker raw-metric accumulation for one sampling interval.

Reference parity: monitor/sampling/holder/BrokerLoad.java (328) — collects
the broker/topic/partition raw metrics reported by each broker between two
sampling points and answers the derived questions the processor asks
(leader bytes in/out, replication bytes in, CPU util, per-topic rates).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

from ...metricdef.raw_metric_type import MetricScope, RawMetricType
from ...reporter.metrics import CruiseControlMetric

R = RawMetricType


@dataclasses.dataclass
class BrokerLoad:
    broker_id: int
    broker_metrics: dict[RawMetricType, list[float]] = dataclasses.field(
        default_factory=lambda: defaultdict(list))
    topic_metrics: dict[tuple[str, RawMetricType], list[float]] = dataclasses.field(
        default_factory=lambda: defaultdict(list))
    partition_sizes: dict[tuple[str, int], float] = dataclasses.field(
        default_factory=dict)

    def record(self, m: CruiseControlMetric) -> None:
        if m.scope is MetricScope.BROKER:
            self.broker_metrics[m.raw_type].append(m.value)
        elif m.scope is MetricScope.TOPIC:
            self.topic_metrics[(m.topic, m.raw_type)].append(m.value)
        else:  # PARTITION_SIZE is the only partition-scope metric
            self.partition_sizes[(m.topic, m.partition)] = m.value

    # -- derived views ----------------------------------------------------
    def broker_metric(self, raw: RawMetricType, default: float = 0.0) -> float:
        vals = self.broker_metrics.get(raw)
        return sum(vals) / len(vals) if vals else default

    def has_broker_metric(self, raw: RawMetricType) -> bool:
        return bool(self.broker_metrics.get(raw))

    def topic_metric(self, topic: str, raw: RawMetricType,
                     default: float = 0.0) -> float:
        vals = self.topic_metrics.get((topic, raw))
        return sum(vals) / len(vals) if vals else default

    @property
    def cpu_util(self) -> float:
        return self.broker_metric(R.BROKER_CPU_UTIL)

    @property
    def leader_bytes_in(self) -> float:
        return self.broker_metric(R.ALL_TOPIC_BYTES_IN)

    @property
    def leader_bytes_out(self) -> float:
        return self.broker_metric(R.ALL_TOPIC_BYTES_OUT)

    @property
    def follower_bytes_in(self) -> float:
        return self.broker_metric(R.ALL_TOPIC_REPLICATION_BYTES_IN)

    def topics(self) -> set[str]:
        return ({t for (t, _raw) in self.topic_metrics}
                | {t for (t, _p) in self.partition_sizes})

    def partition_size(self, topic: str, partition: int) -> float:
        return self.partition_sizes.get((topic, partition), 0.0)


def group_by_broker(metrics) -> dict[int, BrokerLoad]:
    loads: dict[int, BrokerLoad] = {}
    for m in metrics:
        loads.setdefault(m.broker_id, BrokerLoad(m.broker_id)).record(m)
    return loads
