"""Raw reporter metrics → partition/broker metric samples.

Reference parity: monitor/sampling/CruiseControlMetricsProcessor.java (241)
+ holder/ package: groups raw metrics by reporting broker, derives
per-partition byte rates from topic-level rates, estimates per-partition
leader CPU from broker CPU × traffic shares
(ModelUtils.estimateLeaderCpuUtilPerCore), and emits one
PartitionMetricSample per leader partition plus one BrokerMetricSample per
broker.

Redesign: the per-broker work is batched — all partitions led by a broker
are processed as numpy columns in one shot (CPU estimation is a single
vectorized call per broker, not a call per partition). Topic-level byte
rates are distributed over the broker's leader partitions of that topic
proportionally to partition size, falling back to an even split when sizes
are all zero (the reference distributes evenly; size-weighting is a strictly
better prior and keeps the same topic-level totals).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Iterable, Mapping

import numpy as np

from ...executor.admin import PartitionState
from ...metricdef.kafka_metric_def import (
    CommonMetric as CM, KafkaMetricDef, _BROKER_ONLY_NAMES,
)
from ...metricdef.raw_metric_type import RawMetricType as R
from ...model.cpu_estimation import CpuEstimator
from ...reporter.metrics import CruiseControlMetric
from .holder import BrokerLoad, group_by_broker
from .samples import BrokerMetricSample, PartitionMetricSample

# raw broker metric → broker-only model metric name (identical names except
# the idle-percent rename; KafkaMetricDef.java raw→model bridge).
_RAW_TO_BROKER_ONLY: dict[R, str] = {}
for _name in _BROKER_ONLY_NAMES:
    _raw_name = ("BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT"
                 if _name == "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT" else _name)
    _RAW_TO_BROKER_ONLY[R[_raw_name]] = _name


@dataclasses.dataclass
class ProcessorResult:
    partition_samples: list[PartitionMetricSample]
    broker_samples: list[BrokerMetricSample]
    skipped_partitions: int  # leader load unknown / inconsistent rates


class CruiseControlMetricsProcessor:
    def __init__(self, cpu_estimator: CpuEstimator | None = None):
        self._cpu = cpu_estimator or CpuEstimator()

    def process(self, metrics: Iterable[CruiseControlMetric],
                partitions: Mapping[tuple[str, int], PartitionState],
                time_ms: int,
                loads: Mapping[int, BrokerLoad] | None = None,
                ) -> ProcessorResult:
        """``loads`` short-circuits the per-metric grouping when the caller
        already built BrokerLoads columnar (broker_loads_from_columns)."""
        if loads is None:
            loads = group_by_broker(metrics)
        # leader broker → [(topic, partition)]
        by_leader: dict[int, list[tuple[str, int]]] = defaultdict(list)
        for (topic, part), st in partitions.items():
            if st.leader >= 0:
                by_leader[st.leader].append((topic, part))

        psamples: list[PartitionMetricSample] = []
        bsamples: list[BrokerMetricSample] = []
        skipped = 0
        for broker_id, led in by_leader.items():
            load = loads.get(broker_id)
            if load is None:
                skipped += len(led)
                continue
            samples, bad = self._partition_samples(load, led, time_ms)
            psamples.extend(samples)
            skipped += bad
        for broker_id, load in loads.items():
            bsamples.append(self._broker_sample(load, time_ms))
        return ProcessorResult(psamples, bsamples, skipped)

    # -- per-broker batch --------------------------------------------------
    def _partition_samples(self, load: BrokerLoad,
                           led: list[tuple[str, int]], time_ms: int,
                           ) -> tuple[list[PartitionMetricSample], int]:
        n = len(led)
        sizes = np.array([load.partition_size(t, p) for t, p in led])
        # Per-topic share weights over this broker's leader partitions.
        by_topic: dict[str, list[int]] = defaultdict(list)
        for i, (t, _p) in enumerate(led):
            by_topic[t].append(i)
        weights = np.zeros(n)
        for t, idxs in by_topic.items():
            s = sizes[idxs]
            tot = s.sum()
            weights[idxs] = (s / tot) if tot > 0 else (1.0 / len(idxs))

        def topic_col(raw: R) -> np.ndarray:
            per_topic = {t: load.topic_metric(t, raw) for t in by_topic}
            return np.array([per_topic[t] for t, _p in led]) * weights

        bytes_in = topic_col(R.TOPIC_BYTES_IN)
        bytes_out = topic_col(R.TOPIC_BYTES_OUT)
        repl_in = topic_col(R.TOPIC_REPLICATION_BYTES_IN)
        repl_out = topic_col(R.TOPIC_REPLICATION_BYTES_OUT)
        produce = topic_col(R.TOPIC_PRODUCE_REQUEST_RATE)
        fetch = topic_col(R.TOPIC_FETCH_REQUEST_RATE)
        messages = topic_col(R.TOPIC_MESSAGES_IN_PER_SEC)

        cpu = self._cpu.leader_cpu(
            np.full(n, load.cpu_util), np.full(n, load.leader_bytes_in),
            np.full(n, load.leader_bytes_out),
            np.full(n, load.follower_bytes_in), bytes_in, bytes_out)

        out: list[PartitionMetricSample] = []
        bad = 0
        for i, (t, p) in enumerate(led):
            if np.isnan(cpu[i]):
                bad += 1
                continue
            out.append(PartitionMetricSample.make(t, p, time_ms, {
                CM.CPU_USAGE: float(cpu[i]),
                CM.DISK_USAGE: float(sizes[i]),
                CM.LEADER_BYTES_IN: float(bytes_in[i]),
                CM.LEADER_BYTES_OUT: float(bytes_out[i]),
                CM.PRODUCE_RATE: float(produce[i]),
                CM.FETCH_RATE: float(fetch[i]),
                CM.MESSAGE_IN_RATE: float(messages[i]),
                CM.REPLICATION_BYTES_IN_RATE: float(repl_in[i]),
                CM.REPLICATION_BYTES_OUT_RATE: float(repl_out[i]),
            }))
        return out, bad

    def _broker_sample(self, load: BrokerLoad, time_ms: int) -> BrokerMetricSample:
        values: dict[str, float] = {
            CM.CPU_USAGE.name: load.cpu_util,
            CM.DISK_USAGE.name: float(sum(load.partition_sizes.values())),
            CM.LEADER_BYTES_IN.name: load.leader_bytes_in,
            CM.LEADER_BYTES_OUT.name: load.leader_bytes_out,
            CM.PRODUCE_RATE.name: load.broker_metric(R.ALL_TOPIC_PRODUCE_REQUEST_RATE),
            CM.FETCH_RATE.name: load.broker_metric(R.ALL_TOPIC_FETCH_REQUEST_RATE),
            CM.MESSAGE_IN_RATE.name: load.broker_metric(R.ALL_TOPIC_MESSAGES_IN_PER_SEC),
            CM.REPLICATION_BYTES_IN_RATE.name: load.follower_bytes_in,
            CM.REPLICATION_BYTES_OUT_RATE.name:
                load.broker_metric(R.ALL_TOPIC_REPLICATION_BYTES_OUT),
        }
        for raw, name in _RAW_TO_BROKER_ONLY.items():
            if load.has_broker_metric(raw):
                values[name] = load.broker_metric(raw)
        return BrokerMetricSample.make(load.broker_id, time_ms, values)
