"""Metric sample records flowing Monitor-ward.

Reference parity: monitor/sampling/holder/PartitionMetricSample.java (156)
and BrokerMetricSample.java (359) — one record per entity per sampling
interval, carrying the model-metric values keyed by KafkaMetricDef ids.

Redesign: samples are lightweight frozen records; batch ingestion converts
a list of samples into one numpy matrix per entity class so the windowed
aggregator does a single vectorized add per interval instead of per-entity
calls (the reference loops addSample per sample).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from ...metricdef.kafka_metric_def import CommonMetric, KafkaMetricDef


@dataclasses.dataclass(frozen=True, order=True)
class PartitionEntity:
    """Aggregation entity for a partition; group = topic
    (KafkaPartitionMetricSampleAggregator: group-by-topic granularity)."""

    topic: str
    partition: int

    @property
    def group(self) -> str:
        return self.topic


@dataclasses.dataclass(frozen=True, order=True)
class BrokerEntity:
    broker_id: int

    @property
    def group(self) -> str:
        return str(self.broker_id)


@dataclasses.dataclass(frozen=True)
class PartitionMetricSample:
    """Per-partition sample over COMMON metrics (CPU_USAGE..REPLICATION_*)."""

    entity: PartitionEntity
    time_ms: int
    values: tuple[float, ...]  # indexed by common metric id

    @staticmethod
    def make(topic: str, partition: int, time_ms: int,
             by_metric: dict[CommonMetric, float]) -> "PartitionMetricSample":
        n = KafkaMetricDef.common_metric_def().num_metrics
        vals = [0.0] * n
        for m, v in by_metric.items():
            vals[KafkaMetricDef.common_metric_id(m)] = float(v)
        return PartitionMetricSample(PartitionEntity(topic, partition),
                                     time_ms, tuple(vals))

    def metric_value(self, metric: CommonMetric) -> float:
        return self.values[KafkaMetricDef.common_metric_id(metric)]


@dataclasses.dataclass(frozen=True)
class BrokerMetricSample:
    """Per-broker sample over COMMON + BROKER_ONLY metrics."""

    entity: BrokerEntity
    time_ms: int
    values: tuple[float, ...]  # indexed by broker metric id

    @staticmethod
    def make(broker_id: int, time_ms: int,
             by_name: dict[str, float]) -> "BrokerMetricSample":
        d = KafkaMetricDef.broker_metric_def()
        vals = [0.0] * d.num_metrics
        for name, v in by_name.items():
            vals[d.metric_info(name).id] = float(v)
        return BrokerMetricSample(BrokerEntity(broker_id), time_ms, tuple(vals))

    def metric_value(self, name: str) -> float:
        return self.values[KafkaMetricDef.broker_metric_def().metric_info(name).id]


def samples_to_matrix(samples: Sequence[PartitionMetricSample | BrokerMetricSample],
                      ) -> tuple[list, np.ndarray]:
    """(entities, values[n, num_metrics]) for aggregator batch add."""
    if not samples:
        return [], np.zeros((0, 0), dtype=np.float32)
    entities = [s.entity for s in samples]
    values = np.asarray([s.values for s in samples], dtype=np.float32)
    return entities, values


def partition_samples_record(samples: Iterable[PartitionMetricSample]) -> list[dict]:
    """JSON-able rows for the sample store."""
    return [{"t": s.entity.topic, "p": s.entity.partition, "ms": s.time_ms,
             "v": list(s.values)} for s in samples]


def partition_samples_from_record(rows: Iterable[dict]) -> list[PartitionMetricSample]:
    return [PartitionMetricSample(PartitionEntity(r["t"], r["p"]), r["ms"],
                                  tuple(r["v"])) for r in rows]


def broker_samples_record(samples: Iterable[BrokerMetricSample]) -> list[dict]:
    return [{"b": s.entity.broker_id, "ms": s.time_ms, "v": list(s.values)}
            for s in samples]


def broker_samples_from_record(rows: Iterable[dict]) -> list[BrokerMetricSample]:
    return [BrokerMetricSample(BrokerEntity(r["b"]), r["ms"], tuple(r["v"]))
            for r in rows]
