"""MetricSampler SPI + bundled implementations.

Reference parity: monitor/sampling/MetricSampler.java plugin SPI with
CruiseControlMetricsReporterSampler (consumes the reporter's metrics topic),
PrometheusMetricSampler (PromQL over HTTP), and NoopSampler.

Redesign: the Kafka consumer is abstracted behind ``MetricsTransport`` (an
in-memory queue in this image — the wire binding (kafka.transport.KafkaMetricsTransport) implements
the same two methods against the real ``__CruiseControlMetrics`` topic).
The Prometheus sampler maps PromQL queries onto raw metric types like the
reference's PrometheusAdapter but is gated on an injectable ``http_get``
so tests run without a server and the image needs no client library.
"""

from __future__ import annotations

import dataclasses
import math
import time
import zlib
from typing import Callable, Mapping, Protocol

from ...executor.admin import PartitionState
from ...metricdef.raw_metric_type import RawMetricType as R
from ...model.cpu_estimation import CpuEstimator
from ...reporter.metrics import CruiseControlMetric, deserialize
from .processor import CruiseControlMetricsProcessor, ProcessorResult
from .samples import BrokerMetricSample, PartitionMetricSample


@dataclasses.dataclass
class SamplerResult:
    partition_samples: list[PartitionMetricSample]
    broker_samples: list[BrokerMetricSample]
    skipped_partitions: int = 0


class MetricSampler(Protocol):
    """getSamples(cluster, assigned partitions, [start, end)) → samples."""

    def get_samples(self, partitions: Mapping[tuple[str, int], PartitionState],
                    start_ms: int, end_ms: int) -> SamplerResult: ...

    def close(self) -> None: ...


class NoopSampler:
    def get_samples(self, partitions, start_ms, end_ms) -> SamplerResult:
        return SamplerResult([], [], 0)

    def close(self) -> None:
        pass


class MetricsTransport(Protocol):
    """Minimal consumer view of the metrics topic."""

    def poll(self, start_ms: int, end_ms: int) -> list[bytes]: ...

    def produce(self, payload: bytes) -> None: ...


class InMemoryMetricsTransport:
    """Test/simulation transport holding serialized metric records."""

    def __init__(self):
        self._records: list[tuple[int, bytes]] = []

    def produce(self, payload: bytes) -> None:
        m = deserialize(payload)
        self._records.append((m.time_ms, payload))

    def produce_metric(self, metric: CruiseControlMetric) -> None:
        from ...reporter.metrics import serialize
        self._records.append((metric.time_ms, serialize(metric)))

    def poll(self, start_ms: int, end_ms: int) -> list[bytes]:
        return [b for ts, b in self._records if start_ms <= ts < end_ms]


class CruiseControlMetricsReporterSampler:
    """Consumes reporter records from the transport and runs the processor
    (CruiseControlMetricsReporterSampler.java + MetricsProcessor)."""

    def __init__(self, transport: MetricsTransport,
                 cpu_estimator: CpuEstimator | None = None):
        self._transport = transport
        self._processor = CruiseControlMetricsProcessor(cpu_estimator)

    def get_samples(self, partitions, start_ms: int, end_ms: int) -> SamplerResult:
        res = self._columnar_samples(partitions, start_ms, end_ms)
        if res is None:
            raw = [deserialize(b) for b in self._transport.poll(start_ms, end_ms)]
            if partitions:
                assigned = set(partitions)
                raw = [m for m in raw
                       if m.topic is None or m.partition < 0
                       or (m.topic, m.partition) in assigned]
            res = self._processor.process(raw, partitions, end_ms)
        return SamplerResult(res.partition_samples, res.broker_samples,
                             res.skipped_partitions)

    def _columnar_samples(self, partitions, start_ms: int,
                          end_ms: int) -> "ProcessorResult | None":
        """The vectorized ingest path: raw record-set bytes → native span
        index → one columnar serde parse → batched BrokerLoads. Falls back
        to the per-record path when the transport cannot serve spans (the
        in-memory test transport, or no C compiler)."""
        poll_columns = getattr(self._transport, "poll_columns", None)
        if poll_columns is None:
            return None
        got = poll_columns(start_ms, end_ms)
        if got is None:
            return None
        import numpy as np

        from ...monitor.sampling.holder import broker_loads_from_columns
        from ...reporter.metrics import deserialize_columns

        data, spans = got
        cols = deserialize_columns(data, spans)
        if partitions and len(cols):
            # Assigned-partition filter (scalar path parity): only
            # partition-scope rows are filtered; broker/topic scope passes.
            tid_of = {t: i for i, t in enumerate(cols.topics)}
            assigned = np.array(
                [(tid_of[t] << 32) | p for (t, p) in partitions
                 if t in tid_of], dtype=np.int64)
            keys = (cols.topic_id.astype(np.int64) << 32) \
                | (cols.partition.astype(np.int64) & 0xFFFFFFFF)
            ok = (cols.scope != 2) | np.isin(keys, assigned)
            if not ok.all():
                cols = cols.take(ok)
        loads = broker_loads_from_columns(cols)
        return self._processor.process((), partitions, end_ms, loads=loads)

    def close(self) -> None:
        pass


# -- Prometheus ------------------------------------------------------------

# PromQL per raw metric (PrometheusMetricSampler.java DEFAULT_QUERY_MAP).
DEFAULT_PROMETHEUS_QUERIES: dict[R, str] = {
    R.ALL_TOPIC_BYTES_IN: "sum(rate(kafka_server_BrokerTopicMetrics_BytesInPerSec[1m])) by (instance)",
    R.ALL_TOPIC_BYTES_OUT: "sum(rate(kafka_server_BrokerTopicMetrics_BytesOutPerSec[1m])) by (instance)",
    R.BROKER_CPU_UTIL: "1 - avg(rate(node_cpu_seconds_total{mode='idle'}[1m])) by (instance)",
    R.TOPIC_BYTES_IN: "sum(rate(kafka_server_BrokerTopicMetrics_BytesInPerSec[1m])) by (instance, topic)",
    R.TOPIC_BYTES_OUT: "sum(rate(kafka_server_BrokerTopicMetrics_BytesOutPerSec[1m])) by (instance, topic)",
    R.PARTITION_SIZE: "kafka_log_Log_Size",
}


def prometheus_http_get(endpoint: str, timeout_s: float = 10.0,
                        ) -> "Callable[[str, float], list[tuple[dict, float]]]":
    """Production ``http_get`` for ``PrometheusMetricSampler``: an instant
    query against ``{endpoint}/api/v1/query`` via stdlib urllib
    (prometheus/PrometheusAdapter.java:queryMetric). Returns
    [(labels, value)] rows; non-success statuses raise."""
    import json as _json
    import urllib.parse
    import urllib.request

    base = endpoint.rstrip("/")

    def http_get(query: str, time_s: float) -> list[tuple[dict, float]]:
        import urllib.error

        url = (f"{base}/api/v1/query?"
               + urllib.parse.urlencode({"query": query, "time": time_s}))
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                payload = _json.load(resp)
        except urllib.error.HTTPError as e:
            # Prometheus reports query errors (e.g. bad PromQL) as non-2xx
            # WITH a JSON body — surface its detail, not a bare 400.
            try:
                payload = _json.load(e)
            except Exception:  # noqa: BLE001 — body was not JSON
                raise RuntimeError(
                    f"prometheus query failed: HTTP {e.code}") from e
        if payload.get("status") != "success":
            raise RuntimeError(f"prometheus query failed: "
                               f"{payload.get('error', payload)}")
        out = []
        for row in payload.get("data", {}).get("result", []):
            value = row.get("value", [None, "nan"])[1]
            out.append((row.get("metric", {}), float(value)))
        return out

    return http_get


class PrometheusMetricSampler:
    """PromQL-backed sampler. ``http_get(query, time_s) -> [(labels, value)]``
    is injected for tests; production uses ``from_endpoint`` (the stdlib
    urllib client against ``/api/v1/query``, with the server URL from the
    ``prometheus.server.endpoint`` config key)."""

    @classmethod
    def from_endpoint(cls, endpoint: str,
                      broker_of_instance: Callable[[str], int | None],
                      queries: Mapping[R, str] | None = None,
                      cpu_estimator: CpuEstimator | None = None,
                      ) -> "PrometheusMetricSampler":
        return cls(prometheus_http_get(endpoint), broker_of_instance,
                   queries, cpu_estimator)

    def __init__(self, http_get: Callable[[str, float], list[tuple[dict, float]]],
                 broker_of_instance: Callable[[str], int | None],
                 queries: Mapping[R, str] | None = None,
                 cpu_estimator: CpuEstimator | None = None):
        self._http_get = http_get
        self._broker_of = broker_of_instance
        self._queries = dict(queries or DEFAULT_PROMETHEUS_QUERIES)
        self._processor = CruiseControlMetricsProcessor(cpu_estimator)

    def get_samples(self, partitions, start_ms: int, end_ms: int) -> SamplerResult:
        raw: list[CruiseControlMetric] = []
        t = end_ms / 1000.0
        for rtype, q in self._queries.items():
            for labels, value in self._http_get(q, t):
                broker = self._broker_of(labels.get("instance", ""))
                if broker is None or not math.isfinite(value):
                    continue
                topic = labels.get("topic")
                part = int(labels.get("partition", -1))
                raw.append(CruiseControlMetric(rtype, end_ms, broker, value,
                                               topic=topic, partition=part))
        res = self._processor.process(raw, partitions, end_ms)
        return SamplerResult(res.partition_samples, res.broker_samples,
                             res.skipped_partitions)

    def close(self) -> None:
        pass


class SyntheticSampler:
    """Deterministic load generator for demos and tests: stable per-partition
    rates derived from a crc32 of (seed, topic, partition) so windows are
    self-consistent across intervals AND across processes (builtin
    ``hash()`` is PYTHONHASHSEED-randomized for the topic string — the
    same trap PR 4 fixed in the partition assignor; CCSA004 now polices
    it)."""

    def __init__(self, seed: int = 0, cpu_per_kb: float = 2e-4):
        self._seed = seed
        self._cpu_per_kb = cpu_per_kb

    def get_samples(self, partitions, start_ms, end_ms) -> SamplerResult:
        from ...metricdef.kafka_metric_def import CommonMetric as CM
        psamples = []
        per_broker: dict[int, float] = {}
        for (topic, part), st in partitions.items():
            if st.leader < 0:
                continue
            h = (zlib.crc32(f"{self._seed}:{topic}:{part}".encode())
                 % 1000) / 1000.0
            bytes_in = 50.0 + 950.0 * h
            bytes_out = 2.0 * bytes_in
            psamples.append(PartitionMetricSample.make(topic, part, end_ms, {
                CM.CPU_USAGE: self._cpu_per_kb * bytes_in,
                CM.DISK_USAGE: 10_000.0 * h + 100.0,
                CM.LEADER_BYTES_IN: bytes_in,
                CM.LEADER_BYTES_OUT: bytes_out,
                CM.REPLICATION_BYTES_IN_RATE: bytes_in,
                CM.MESSAGE_IN_RATE: bytes_in / 2,
            }))
            per_broker[st.leader] = per_broker.get(st.leader, 0.0) + bytes_in
        bsamples = [BrokerMetricSample.make(b, end_ms, {
            CM.CPU_USAGE.name: min(1.0, self._cpu_per_kb * v),
            CM.LEADER_BYTES_IN.name: v, CM.LEADER_BYTES_OUT.name: 2 * v,
        }) for b, v in per_broker.items()]
        return SamplerResult(psamples, bsamples, 0)

    def close(self) -> None:
        pass


def now_ms() -> int:
    return int(time.time() * 1000)
