"""SampleStore SPI: durable metric-sample persistence for warm restarts.

Reference parity: monitor/sampling/KafkaSampleStore.java:94-204 (two sample
topics, produced on every fetch, replayed in parallel at startup) and
NoopSampleStore. Here the default durable store is an append-only JSONL
file pair under ``sample.store.path`` (fileStore/ scratch dir in the
reference deployment); a Kafka-topic store can implement the same protocol
when a Kafka client is available.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Protocol

from .sampler import SamplerResult
from .samples import (
    broker_samples_from_record, broker_samples_record,
    partition_samples_from_record, partition_samples_record,
)


class SampleStore(Protocol):
    def store_samples(self, result: SamplerResult) -> None: ...

    def load_samples(self) -> SamplerResult: ...

    def close(self) -> None: ...


class NoopSampleStore:
    def store_samples(self, result: SamplerResult) -> None:
        pass

    def load_samples(self) -> SamplerResult:
        return SamplerResult([], [], 0)

    def close(self) -> None:
        pass


class FileSampleStore:
    """Append-only JSONL pair (partition-samples, broker-samples) with a
    byte budget: when a file exceeds ``max_bytes`` it is compacted to the
    newest half (the Kafka store relies on topic retention for the same)."""

    def __init__(self, path: str, max_bytes: int = 64 * 1024 * 1024):
        self._dir = path
        self._max_bytes = max_bytes
        os.makedirs(path, exist_ok=True)
        self._ppath = os.path.join(path, "partition_samples.jsonl")
        self._bpath = os.path.join(path, "broker_samples.jsonl")
        self._lock = threading.Lock()

    def store_samples(self, result: SamplerResult) -> None:
        with self._lock:
            self._append(self._ppath, partition_samples_record(result.partition_samples))
            self._append(self._bpath, broker_samples_record(result.broker_samples))

    def _append(self, path: str, rows: list[dict]) -> None:
        if not rows:
            return
        with open(path, "a") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        if os.path.getsize(path) > self._max_bytes:
            with open(path) as f:
                lines = f.readlines()
            with open(path, "w") as f:
                f.writelines(lines[len(lines) // 2:])

    def load_samples(self) -> SamplerResult:
        with self._lock:
            return SamplerResult(
                partition_samples_from_record(self._read(self._ppath)),
                broker_samples_from_record(self._read(self._bpath)), 0)

    @staticmethod
    def _read(path: str) -> list[dict]:
        if not os.path.exists(path):
            return []
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        rows.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail write — skip
        return rows

    def close(self) -> None:
        pass
