"""LoadMonitor: aggregated windows + metadata → device-resident ClusterTensors.

Reference parity: monitor/LoadMonitor.java (startUp:211,
clusterModel:437-541, acquireForModelGeneration semaphore :93,169,
pause/resumeMetricSampling), MonitorUtils.populatePartitionLoad:415,
ModelCompletenessRequirements.java, LoadMonitorState.java.

Redesign: the cluster model is not a mutable object graph guarded by a
semaphore pool — it is a frozen pytree built in one vectorized pass from
the aggregation matrices ([E, M, W] → per-partition resource rows) and
shipped to device once per generation. The semaphore survives only as a
bound on concurrent *builds* (each build is CPU+HBM work).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Mapping

import numpy as np

from ..common.broker_state import BrokerState
from ..common.resources import Resource
from ..config.cruise_control_config import CruiseControlConfig
from ..executor.admin import AdminBackend, PartitionState
from ..metricdef.kafka_metric_def import CommonMetric as CM, KafkaMetricDef
from ..metricdef.metricdef import ValueComputingStrategy as S
from ..model.builder import BrokerSpec
from ..model.cpu_estimation import CpuEstimator
from ..model.refresh import IncrementalModelPipeline, TopologyCache
from ..model.tensors import ClusterMeta, ClusterTensors
from .aggregator.aggregator import (
    AggregationOptions, AggregationResult, Granularity, MetricSampleAggregator,
    NotEnoughValidWindowsError,
)
from .capacity import BrokerCapacityConfigResolver, StaticCapacityResolver
from .sampling.fetcher import MetricFetcherManager
from .sampling.sampler import MetricSampler
from .sampling.sample_store import NoopSampleStore, SampleStore
from .task_runner import LoadMonitorTaskRunner, SamplingMode

LOG = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ModelCompletenessRequirements:
    """ModelCompletenessRequirements.java: gates model generation."""

    min_valid_windows: int = 1
    min_monitored_partitions_percentage: float = 0.95
    include_all_topics: bool = False

    def weaker(self) -> "ModelCompletenessRequirements":
        return ModelCompletenessRequirements(1, 0.0, self.include_all_topics)


@dataclasses.dataclass
class LoadMonitorState:
    runner_state: str
    num_valid_windows: int
    monitored_partitions_percentage: float
    total_num_partitions: int
    num_partition_samples: int
    model_generation: int


class ModelGenerationSemaphore:
    """acquireForModelGeneration (LoadMonitor.java:93): bound concurrent
    cluster-model builds."""

    def __init__(self, permits: int = 2):
        self._sem = threading.Semaphore(permits)

    def __enter__(self):
        self._sem.acquire()
        return self

    def __exit__(self, *exc):
        self._sem.release()
        return False


class LoadMonitor:
    def __init__(self, config: CruiseControlConfig, metadata: AdminBackend,
                 samplers: list[MetricSampler] | None = None,
                 sample_store: SampleStore | None = None,
                 capacity_resolver: BrokerCapacityConfigResolver | None = None,
                 broker_racks: Mapping[int, str] | None = None,
                 cpu_estimator: CpuEstimator | None = None,
                 partition_bucket: int | None = None):
        self._config = config
        self._metadata = metadata
        self._capacity = capacity_resolver or StaticCapacityResolver({})
        self._broker_racks = dict(broker_racks or {})
        from ..analyzer.plugins import rack_id_mapper_from_config
        self._rack_mapper = rack_id_mapper_from_config(config)
        self._cpu = cpu_estimator or CpuEstimator()
        # Shape bucketing (VERDICT r3 #10): pad the model's partition and
        # broker axes up to bucket multiples so ordinary cluster changes
        # (partition add/drop, broker join) keep the SAME compiled solver
        # kernels — XLA recompiles per shape, and a 7k-broker chain compile
        # is minutes even warm-cached when the shape is novel.
        self._partition_bucket = (
            config.get_int("solver.partition.bucket.size")
            if partition_bucket is None else partition_bucket)
        self._broker_bucket = config.get_int("solver.broker.bucket.size")
        # Post-build model hook: (state, meta) -> (state, meta), applied to
        # every cluster_model result. The fleet registry installs the
        # shared BucketGrid's padding here so all of a process's clusters
        # land on the same compiled solver shapes (fleet.bucketing).
        self.model_transform = None

        self._partition_agg = MetricSampleAggregator(
            num_windows=config.get("num.partition.metrics.windows"),
            window_ms=config.get("partition.metrics.window.ms"),
            min_samples_per_window=config.get("min.samples.per.partition.metrics.window"),
            metric_def=KafkaMetricDef.common_metric_def(),
            group_fn=lambda e: e.group,
            completeness_cache_size=config.get_int(
                "partition.metric.sample.aggregator.completeness.cache.size"))
        self._broker_agg = MetricSampleAggregator(
            num_windows=config.get("num.broker.metrics.windows"),
            window_ms=config.get("broker.metrics.window.ms"),
            min_samples_per_window=config.get("min.samples.per.broker.metrics.window"),
            metric_def=KafkaMetricDef.broker_metric_def(),
            completeness_cache_size=config.get_int(
                "broker.metric.sample.aggregator.completeness.cache.size"))

        store = sample_store or NoopSampleStore()
        if samplers is None:
            from .sampling.sampler import NoopSampler
            samplers = [NoopSampler()]
        # Sampling resilience (round 9): per-fetcher retries under the
        # shared policy — with the attempt budget the reference spells
        # fetch.metric.samples.max.retry.count — and partial-window
        # acceptance above the configured completeness floor.
        from ..utils.resilience import RetryPolicy
        # Metadata reads (describe_partitions / alive_brokers) retry
        # under the shared policy: a transiently unreachable control
        # plane must not fail a model build that aggregation already
        # paid for.
        self._retry_policy = RetryPolicy.from_config(config)
        fetch_policy = None
        if self._retry_policy is not None:
            # Same policy, but the attempt budget the reference spells
            # fetch.metric.samples.max.retry.count (RETRIES; the policy
            # counts ATTEMPTS).
            fetch_policy = dataclasses.replace(
                self._retry_policy, max_attempts=1 + max(0, config.get_int(
                    "fetch.metric.samples.max.retry.count")))
        self._fetcher = MetricFetcherManager(
            samplers, self._partition_agg, self._broker_agg, store,
            num_fetchers=config.get_int("num.metric.fetchers"),
            retry_policy=fetch_policy,
            # The completeness floor is part of the resilience layer:
            # disabled means bare pre-round-9 behavior (ingest whatever
            # arrived), not stricter rejection with no retries.
            min_completeness=(config.get_double(
                "resilience.sampling.min.completeness")
                if config.get_boolean("resilience.enabled") else 0.0))
        self._task_runner = LoadMonitorTaskRunner(
            self._fetcher, self._metadata, store,
            sampling_interval_ms=config.get("metric.sampling.interval.ms"))
        self._model_semaphore = ModelGenerationSemaphore()
        # Incremental device-resident refresh: topology tables + device
        # tensors cached across cluster_model() calls, invalidated by the
        # backend's metadata generation (or a structural fingerprint);
        # steady-state calls only re-gather loads (model/refresh.py).
        self._pipeline = IncrementalModelPipeline(self._partition_bucket,
                                                  self._broker_bucket)
        # Background model prefetch (the fleet pacer's overlap hook):
        # (agg generation, metadata token, (state, meta)) built
        # off-thread, consumed by the next default-argument
        # cluster_model() call.
        self._prefetch_lock = threading.Lock()
        self._prefetched: tuple | None = None
        self._prefetch_thread: threading.Thread | None = None
        # Last full cluster_model() wall-clock: the in-flight progress
        # estimate for the GeneratingClusterModel step (progress.to_list
        # reports a live completionPercentage from it).
        self._last_model_s: float | None = None

    # -- lifecycle --------------------------------------------------------
    def start_up(self, block_on_load: bool = True) -> None:
        self._task_runner.start(block_on_load=block_on_load)

    def shutdown(self) -> None:
        self._task_runner.shutdown()
        self._fetcher.shutdown()

    def pause_metric_sampling(self, reason: str = "") -> None:
        self._task_runner.set_mode(SamplingMode.PAUSED, reason)

    def resume_metric_sampling(self, reason: str = "") -> None:
        self._task_runner.set_mode(SamplingMode.RUNNING, reason)

    def train(self, start_ms: int, end_ms: int) -> dict:
        """TRAIN endpoint flow (TrainingTask → LinearRegressionModelParameters
        .updateModelCoefficient:70): feed the broker aggregator's windowed
        (CPU, leader-in, leader-out, replication-in) rows into the linear
        CPU model; on a successful fit the estimator switches over."""
        from ..metricdef.kafka_metric_def import BrokerMetric, KafkaMetricDef
        from ..model.cpu_estimation import LinearRegressionCpuModel
        from .aggregator.aggregator import AggregationOptions, Granularity

        if self._cpu.linear_model is None:
            bucket_pct = self._config.get_int(
                "linear.regression.model.cpu.util.bucket.size")
            self._cpu.linear_model = LinearRegressionCpuModel(
                num_buckets=max(1, 100 // bucket_pct),
                required_samples_per_bucket=self._config.get_int(
                    "linear.regression.model.required.samples.per.bucket"),
                min_num_buckets=self._config.get_int(
                    "linear.regression.model.min.num.cpu.util.buckets"))
        bdef = KafkaMetricDef.broker_metric_def()
        opts = AggregationOptions(min_valid_entity_ratio=0.0, min_valid_windows=1,
                                  granularity=Granularity.ENTITY,
                                  include_invalid_entities=True)
        agg = self._broker_agg.aggregate(opts)
        window_ms = self._broker_agg.window_ms
        valid = [i for i, w in enumerate(agg.window_indices)
                 if start_ms <= w * window_ms <= end_ms]
        ids = [bdef.metric_info(n).id for n in
               (CM.CPU_USAGE.name, CM.LEADER_BYTES_IN.name,
                CM.LEADER_BYTES_OUT.name, CM.REPLICATION_BYTES_IN_RATE.name)]
        if valid and len(agg.entities):
            cols = agg.values[:, :, valid]                     # [E, M, W']
            self._cpu.linear_model.add_observations(
                cols[:, ids[0], :], cols[:, ids[1], :],
                cols[:, ids[2], :], cols[:, ids[3], :])
        trained = self._cpu.linear_model.train()
        if trained:
            self._cpu.use_linear_regression = True
        return {"trained": trained,
                "trainingCompleteness": self._cpu.linear_model.training_completeness,
                "coefficients": (None if not trained else
                                 [float(c) for c in
                                  self._cpu.linear_model.coefficients])}

    def bootstrap(self, start_ms: int, end_ms: int, clear_metrics: bool = True) -> None:
        self._task_runner.bootstrap(start_ms, end_ms, clear_metrics)

    @property
    def task_runner(self) -> LoadMonitorTaskRunner:
        return self._task_runner

    @property
    def partition_aggregator(self) -> MetricSampleAggregator:
        return self._partition_agg

    @property
    def broker_aggregator(self) -> MetricSampleAggregator:
        return self._broker_agg

    @property
    def model_generation(self) -> int:
        return self._partition_agg.generation

    def acquire_for_model_generation(self) -> ModelGenerationSemaphore:
        return self._model_semaphore

    def latest_broker_metrics(self, metric_names: "Sequence[str] | None" = None,
                              ) -> dict[int, dict[str, float]]:
        """{broker_id: {metric_name: latest value}} from the broker
        aggregator's in-fill window — the freshest per-broker view, feeding
        the executor's metric-limit concurrency adjuster
        (Executor.java:465-683 reads the same broker metrics).
        ``metric_names`` restricts the columns materialized (the adjuster
        needs 5 of ~60; building every dict entry per broker per 1 s tick
        would be pure allocation churn at large broker counts)."""
        entities, values = self._broker_agg.peek_current_window()
        if not entities:
            return {}
        bdef = KafkaMetricDef.broker_metric_def()
        if metric_names is None:
            cols = [(m.name, m.id) for m in bdef.all()]
        else:
            cols = [(n, bdef.metric_info(n).id) for n in metric_names
                    if bdef.has_metric(n)]
        return {e.broker_id: {n: float(row[i]) for n, i in cols}
                for e, row in zip(entities, values)}

    # -- state ------------------------------------------------------------
    @property
    def capacity_resolver(self):
        """The configured BrokerCapacityConfigResolver (capacity_only and
        populate_disk_info responses read it directly)."""
        return self._capacity

    def window_times(self) -> list[int]:
        """Stable window start timestamps (STATE super_verbose detail)."""
        return self._partition_agg.all_window_times()

    def state(self) -> LoadMonitorState:
        # Deliberately NOT retried: /state is the diagnostic surface an
        # operator hits DURING an outage — it must fail fast, not sleep
        # through the retry schedule (model builds keep the retries).
        partitions = self._metadata.describe_partitions()
        opts = self._aggregation_options(ModelCompletenessRequirements(1, 0.0))
        try:
            completeness = self._partition_agg.completeness(opts)
            valid_windows = len(completeness.valid_windows)
            ratio = completeness.valid_entity_ratio
        except Exception:
            valid_windows, ratio = 0, 0.0
        return LoadMonitorState(
            runner_state=self._task_runner.state_name,
            num_valid_windows=valid_windows,
            monitored_partitions_percentage=ratio,
            total_num_partitions=len(partitions),
            num_partition_samples=self._partition_agg.num_samples(),
            model_generation=self.model_generation)

    # -- model building ----------------------------------------------------
    def _aggregation_options(self, req: ModelCompletenessRequirements,
                             ) -> AggregationOptions:
        return AggregationOptions(
            min_valid_entity_ratio=req.min_monitored_partitions_percentage,
            min_valid_windows=req.min_valid_windows,
            max_allowed_extrapolations_per_entity=self._config.get(
                "max.allowed.extrapolations.per.partition"),
            granularity=(Granularity.ENTITY_GROUP if req.include_all_topics
                         else Granularity.ENTITY),
            include_invalid_entities=False)

    def cluster_model(self, requirements: ModelCompletenessRequirements | None = None,
                      allow_capacity_estimation: bool = True,
                      start_ms: int = -1, end_ms: int = -1,
                      min_valid_partition_ratio: float | None = None,
                      reduction: str = "default",
                      ) -> tuple[ClusterTensors, ClusterMeta]:
        """LoadMonitor.clusterModel:489 — aggregate valid windows, resolve
        capacities, populate per-partition loads, freeze to tensors.

        ``allow_capacity_estimation=False`` raises CapacityEstimationError
        when any alive broker's capacity is an estimate (the
        allow_capacity_estimation request param). ``start_ms``/``end_ms``
        restrict aggregation to windows overlapping the range (the LOAD
        endpoint's time/start/end params); -1 = unbounded.
        ``min_valid_partition_ratio`` overrides the configured completeness
        ratio (PARTITION_LOAD param). ``reduction`` overrides the
        per-metric window-reduction strategy: "max"/"avg" mirror
        Load.expectedUtilizationFor(wantMaxLoad/avgLoad)."""
        req = requirements or ModelCompletenessRequirements(
            min_valid_windows=1,
            min_monitored_partitions_percentage=(
                self._config.get("min.valid.partition.ratio")
                if min_valid_partition_ratio is None
                else min_valid_partition_ratio))
        defaults = (requirements is None and allow_capacity_estimation
                    and start_ms < 0 and end_ms < 0
                    and min_valid_partition_ratio is None
                    and reduction == "default")
        if defaults:
            # A background prefetch (fleet pacer overlap) that matches the
            # CURRENT aggregation generation AND metadata generation is
            # this call's answer — the assembly already happened while the
            # solver was busy elsewhere. Both stamps matter: a topology
            # change (broker death, completed reassignment) does not bump
            # the sample-aggregator generation, and a stale-topology model
            # must never shortcut the pipeline's own invalidation.
            with self._prefetch_lock:
                pre, self._prefetched = self._prefetched, None
            if pre is not None and pre[0] == self.model_generation \
                    and pre[1] == self._metadata_token():
                from ..utils.sensors import SENSORS
                from ..utils.tracing import TRACER
                SENSORS.count("model_prefetch_hits")
                TRACER.annotate(model_prefetch_hit=True)
                return pre[2]
        from ..utils.progress import step
        from ..utils.tracing import TRACER
        step("WaitingForClusterModel")
        with self._model_semaphore, \
                TRACER.span("monitor.cluster_model") as sp:
            # Timer starts INSIDE the semaphore: queue wait is the
            # WaitingForClusterModel step, not model-creation time.
            t0 = time.time()
            step("AggregatingMetrics")
            # Token BEFORE the partitions snapshot: if a concurrent
            # topology change lands between the two reads, the snapshot's
            # (possibly stale) tables get cached under the OLD token and
            # the next call rebuilds — the reverse order would cache
            # pre-change replica data under the post-change key and serve
            # it until the next unrelated topology bump.
            token = self._metadata_token()
            from ..utils.resilience import call_with_resilience
            partitions = call_with_resilience(
                "admin.describe_partitions",
                self._metadata.describe_partitions,
                policy=self._retry_policy)
            alive = call_with_resilience(
                "admin.alive_brokers", self._metadata.alive_brokers,
                policy=self._retry_policy)
            if not allow_capacity_estimation:
                from .capacity import CapacityEstimationError
                estimated = sorted(
                    b for b in alive
                    if getattr(self._capacity, "is_estimated",
                               lambda _b: False)(b))
                if estimated:
                    raise CapacityEstimationError(
                        f"allow_capacity_estimation=false but capacities of "
                        f"brokers {estimated} are estimated (no explicit "
                        "entry in the capacity config)")
            opts = self._aggregation_options(req)
            if start_ms >= 0 or end_ms >= 0:
                import dataclasses as _dc
                opts = _dc.replace(opts, start_ms=start_ms, end_ms=end_ms)
            agg = self._partition_agg.aggregate(opts)
            step("GeneratingClusterModel", estimate_s=self._last_model_s)
            built = self._build(partitions, alive, agg, reduction, token)
            if self.model_transform is not None:
                built = self.model_transform(*built)
            sp.set(generation=self.model_generation,
                   num_partitions=len(partitions), num_brokers=len(alive))
        # cluster-model-creation-timer (LoadMonitor.java:177).
        from ..utils.sensors import SENSORS
        self._last_model_s = time.time() - t0
        SENSORS.record_timer("monitor_cluster_model_creation",
                             self._last_model_s)
        # The request's model_build segment (NO_JOURNEY no-op outside a
        # journey scope — the ambient-stamp discipline of current_heal).
        from ..serving.journey import current_journey
        current_journey().add("model_build", self._last_model_s,
                              generation=self.model_generation,
                              brokers=len(alive))
        return built

    def _build(self, partitions: Mapping[tuple[str, int], PartitionState],
               alive: set[int], agg: AggregationResult,
               reduction: str = "default", token: object = None,
               ) -> tuple[ClusterTensors, ClusterMeta]:
        # Window reduction per metric strategy (Load.expectedUtilizationFor:
        # AVG over windows for rates, LATEST window for disk usage).
        # ``reduction`` "max"/"avg" force one reduction for every metric
        # (the PARTITION_LOAD max_load/avg_load request params).
        mdef = KafkaMetricDef.common_metric_def()
        vals = agg.values  # [E, M, W]
        if vals.shape[2] == 0:
            raise NotEnoughValidWindowsError("no valid windows for model generation")
        reduced = np.empty(vals.shape[:2], dtype=np.float64)  # [E, M]
        for info in mdef.all():
            col = vals[:, info.id, :]
            if reduction == "max":
                reduced[:, info.id] = col.max(axis=1)
                continue
            if reduction == "avg":
                reduced[:, info.id] = col.mean(axis=1)
                continue
            if info.strategy is S.LATEST:
                reduced[:, info.id] = col[:, -1]
            elif info.strategy is S.MAX:
                reduced[:, info.id] = col.max(axis=1)
            else:
                reduced[:, info.id] = col.mean(axis=1)

        brokers = self._broker_specs(partitions, alive)

        def fill_loads(cache: TopologyCache) -> None:
            self._fill_loads(cache, agg, reduced)

        return self._pipeline.assemble(brokers, partitions, fill_loads,
                                       topology_token=token)

    def _metadata_token(self):
        """The backend's O(1) metadata-generation stamp, or None when it
        has none (the pipeline then falls back to structural
        fingerprinting; prefetch consumption becomes best-effort on the
        topology axis — the aggregation-generation check still applies)."""
        gen_fn = getattr(self._metadata, "metadata_generation", None)
        return gen_fn() if callable(gen_fn) else None

    def _broker_specs(self, partitions: Mapping[tuple[str, int], PartitionState],
                      alive: set[int]) -> list[BrokerSpec]:
        all_brokers = sorted({b for st in partitions.values() for b in st.replicas}
                             | alive)
        # Brokers with no known rack refresh from the metadata backend
        # when it exposes racks (KafkaAdminBackend.broker_racks) — a
        # transient boot failure or a late-joining broker must not leave
        # rack-aware goals blind to real topology.
        if any(bid not in self._broker_racks for bid in all_brokers):
            racks_fn = getattr(self._metadata, "broker_racks", None)
            if racks_fn is not None:
                try:
                    self._broker_racks.update(racks_fn())
                except Exception:  # noqa: BLE001 — topology hint only
                    LOG.warning("broker rack refresh failed", exc_info=True)
        # Rack ids pass through the configured mapper before rack-aware
        # goals group by them (AbstractRackAwareGoal.java:51). A broker
        # with NO configured rack gets rack="" and the builder falls back
        # to its HOST as the fault domain (ClusterModel.createBroker:
        # rack == null ? host : rack) — co-hosted rackless brokers then
        # share one rack index, Host.java semantics.
        hosts_fn = getattr(self._metadata, "broker_hosts", None)
        hosts: dict[int, str] = {}
        if hosts_fn is not None:
            try:
                hosts = hosts_fn()
            except Exception:  # noqa: BLE001 — topology hint only
                LOG.warning("broker host refresh failed", exc_info=True)
        return [BrokerSpec(
            bid,
            rack=(self._rack_mapper.apply(self._broker_racks[bid])
                  if bid in self._broker_racks else ""),
            capacity=self._capacity.capacity_for(bid),
            state=(BrokerState.ALIVE if bid in alive else BrokerState.DEAD),
            host=hosts.get(bid, ""))
            for bid in all_brokers]

    def _fill_loads(self, cache: TopologyCache, agg: AggregationResult,
                    reduced: np.ndarray) -> None:
        """Vectorized load assembly into the pipeline's preallocated
        buffers: one gather from the reduced [E, M] matrix into [P, R]
        rows; entities with no valid aggregation contribute zero load
        (the reference drops them from the model; keeping them with zero
        load preserves placement for hard goals)."""
        n = len(cache.part_names)
        rows = self._entity_rows(cache, agg)
        valid = (rows >= 0)
        valid[valid] &= agg.entity_valid[rows[valid]]

        metric_cols = [KafkaMetricDef.common_metric_id(m) for m in
                       (CM.CPU_USAGE, CM.LEADER_BYTES_IN, CM.LEADER_BYTES_OUT,
                        CM.DISK_USAGE)]
        res_cols = [int(Resource.CPU), int(Resource.NW_IN),
                    int(Resource.NW_OUT), int(Resource.DISK)]
        ll, fl = cache.ll_buf, cache.fl_buf
        ll[np.ix_(valid, res_cols)] = reduced[rows[valid]][:, metric_cols]

        fl[:n] = ll[:n]
        fl[:n, int(Resource.NW_OUT)] = 0.0
        fl[:n, int(Resource.CPU)] = self._cpu.follower_cpu(
            ll[:n, int(Resource.NW_IN)],
            ll[:n, int(Resource.NW_OUT)],
            ll[:n, int(Resource.CPU)])

    @staticmethod
    def _entity_rows(cache: TopologyCache, agg: AggregationResult,
                     ) -> np.ndarray:
        """[P] row index into the aggregation matrix per partition (-1 =
        no entity). Cached in the topology cache's scratch area: rows only
        change when the aggregation ENTITY LIST changes (entity set or
        validity churn), so steady-state cycles skip the O(P) dict-lookup
        loop entirely."""
        from .sampling.samples import PartitionEntity
        ents = agg.entities
        cached = cache.scratch.get("entity_rows")
        if cached is not None:
            cid, cents, rows = cached
            if cid == id(ents) or cents == ents:
                cache.scratch["entity_rows"] = (id(ents), ents, rows)
                return rows
        row_of = {e: i for i, e in enumerate(ents)}
        n = len(cache.part_names)
        rows = np.fromiter(
            (row_of.get(PartitionEntity(t, p), -1)
             for t, p in cache.part_names), dtype=np.int64, count=n)
        cache.scratch["entity_rows"] = (id(ents), ents, rows)
        return rows

    @property
    def pipeline(self) -> IncrementalModelPipeline:
        """The incremental refresh pipeline (observability + tests)."""
        return self._pipeline

    # -- history export (forecast seam, round 19) --------------------------
    def load_history(self, num_windows: int,
                     ) -> "tuple[np.ndarray, int, ClusterTensors, ClusterMeta] | None":
        """The windowed per-partition resource history the forecaster
        fits: ``(history [W, P, R], window_ms, state, meta)`` where the
        partition axis is ALIGNED with the current cluster model's rows
        (``state``/``meta`` are this call's ``cluster_model()`` result,
        so the projected loads can be swapped straight into the model)
        and ``W == num_windows`` — exactly the LAST ``num_windows``
        stable windows, oldest first, so the forecaster compiles ONE
        program per (W, P, R) shape instead of one per history length.
        Returns None when fewer stable windows are available (forecast
        not ready) or the model cannot be built yet.

        Entities with no valid aggregation contribute zero rows (the
        same convention as ``_fill_loads``); the resource columns are
        the leader-load view (CPU, NW_IN, NW_OUT, DISK)."""
        from ..common.resources import NUM_RESOURCES
        try:
            state, meta = self.cluster_model()
        except Exception:  # noqa: BLE001 — monitor warming up
            return None
        opts = AggregationOptions(
            min_valid_entity_ratio=0.0, min_valid_windows=1,
            max_allowed_extrapolations_per_entity=self._config.get(
                "max.allowed.extrapolations.per.partition"),
            granularity=Granularity.ENTITY,
            include_invalid_entities=True)
        try:
            agg = self._partition_agg.aggregate(opts)
        except NotEnoughValidWindowsError:
            return None
        if len(agg.window_indices) < num_windows:
            return None
        vals = agg.values[:, :, -num_windows:]            # [E, M, W]
        row_of = {e: i for i, e in enumerate(agg.entities)}
        from .sampling.samples import PartitionEntity
        num_p = int(state.num_partitions)
        rows = np.full(num_p, -1, dtype=np.int64)
        for i, (t, p) in enumerate(meta.partition_index):
            rows[i] = row_of.get(PartitionEntity(t, p), -1)
        metric_cols = [KafkaMetricDef.common_metric_id(m) for m in
                       (CM.CPU_USAGE, CM.LEADER_BYTES_IN,
                        CM.LEADER_BYTES_OUT, CM.DISK_USAGE)]
        res_cols = [int(Resource.CPU), int(Resource.NW_IN),
                    int(Resource.NW_OUT), int(Resource.DISK)]
        history = np.zeros((num_windows, num_p, NUM_RESOURCES),
                           dtype=np.float32)
        known = rows >= 0
        # [Ek, Mk, W] -> [W, Pk, Rk]
        gathered = vals[rows[known]][:, metric_cols, :]
        history[:, known.nonzero()[0][:, None], res_cols] = \
            np.transpose(gathered, (2, 0, 1))
        return history, self._partition_agg.window_ms, state, meta

    def prefetch_model(self) -> bool:
        """Kick off a BACKGROUND assembly of the default cluster model for
        the current generation, overlapping host-side model work with
        whatever the solver is currently executing (the fleet precompute
        pacer calls this right before enqueueing a cluster's solve).
        Non-blocking; at most one prefetch runs at a time. Returns True
        when a build was started."""
        with self._prefetch_lock:
            if self._prefetch_thread is not None \
                    and self._prefetch_thread.is_alive():
                return False
            gen = self.model_generation
            token = self._metadata_token()
            pre = self._prefetched
            if pre is not None and pre[0] == gen and pre[1] == token:
                return False

            def build():
                try:
                    built = self.cluster_model()
                except Exception:  # noqa: BLE001 — model may not be ready
                    LOG.debug("model prefetch failed", exc_info=True)
                    return
                with self._prefetch_lock:
                    # Stamped with the generations at build START: if
                    # samples or topology changed mid-build, the entry is
                    # stale and the consumer's checks discard it.
                    self._prefetched = (gen, token, built)
                from ..utils.sensors import SENSORS
                SENSORS.count("model_prefetch_builds")

            t = threading.Thread(target=build, daemon=True,
                                 name="model-prefetch")
            self._prefetch_thread = t
            t.start()
            return True
