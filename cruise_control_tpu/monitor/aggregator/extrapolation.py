"""Extrapolation taxonomy for missing metric windows.

Reference parity: cruise-control-core .../aggregator/Extrapolation.java and
the category logic of RawMetricValues.aggregate (RawMetricValues.java:275-330):

- ``NONE``: window has >= min samples.
- ``AVG_AVAILABLE``: max(1, min//2) <= count < min — average of what's there.
- ``AVG_ADJACENT``: count < half-min but both stable neighbours have >= min
  samples — average across (prev, cur-if-any, next).
- ``FORCED_INSUFFICIENT``: 0 < count < half-min, no valid neighbours.
- ``NO_VALID_EXTRAPOLATION``: zero samples and no valid neighbours.

Encoded as int8 category codes so the whole [entities × windows] plane is
classified with vectorized comparisons instead of per-entity bookkeeping.
"""

from __future__ import annotations

import enum


class Extrapolation(enum.IntEnum):
    NONE = 0
    AVG_AVAILABLE = 1
    AVG_ADJACENT = 2
    FORCED_INSUFFICIENT = 3
    NO_VALID_EXTRAPOLATION = 4
