from .extrapolation import Extrapolation
from .raw_store import RawMetricStore
from .aggregator import (
    AggregationOptions, AggregationResult, Granularity, MetricSampleAggregator,
    MetricSampleCompleteness, NotEnoughValidWindowsError,
)
