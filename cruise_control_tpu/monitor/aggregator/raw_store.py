"""Dense windowed raw-metric storage for all entities of one kind.

Reference parity: cruise-control-core .../aggregator/RawMetricValues.java —
but where the reference keeps one cyclic float[] per (entity, metric), this
store keeps ONE dense ndarray ``values[E, M, W]`` plus ``counts[E, W]`` for
the whole entity population, so validity/extrapolation classification and
window reduction are single vectorized expressions over the population
instead of per-entity loops. This is the host-side ingest tensor that feeds
the JAX model builder.

Window indexing mirrors WindowIndexedArrays: a logical ``window_index``
(monotonic, time/window_ms) maps onto array slot ``window_index % W`` where
``W = num_stable_windows + 1`` (the +1 is the in-fill current window).
"""

from __future__ import annotations

import numpy as np

from ...metricdef.metricdef import MetricDef, ValueComputingStrategy
from .extrapolation import Extrapolation

_GROW_FACTOR = 2


class RawMetricStore:
    def __init__(self, num_stable_windows: int, min_samples_per_window: int,
                 metric_def: MetricDef, initial_capacity: int = 64):
        if num_stable_windows < 1:
            raise ValueError("need at least 1 stable window")
        self._num_stable = num_stable_windows
        self._buf_windows = num_stable_windows + 1
        self._min_samples = max(1, min_samples_per_window)
        # RawMetricValues.java:61 — half-min floor at 1.
        self._half_min = max(1, self._min_samples // 2)
        self._metric_def = metric_def
        num_metrics = metric_def.num_metrics
        strategies = metric_def.strategies_array()
        self._avg_mask = np.array([s is ValueComputingStrategy.AVG for s in strategies])
        self._max_mask = np.array([s is ValueComputingStrategy.MAX for s in strategies])
        self._latest_mask = np.array([s is ValueComputingStrategy.LATEST for s in strategies])

        cap = max(1, initial_capacity)
        self._values = np.zeros((cap, num_metrics, self._buf_windows), dtype=np.float32)
        self._counts = np.zeros((cap, self._buf_windows), dtype=np.int32)
        self._row_of: dict = {}
        self._entity_of: list = []
        self._first_window_index: int | None = None
        self._current_window_index: int | None = None
        # classify() memo, invalidated on any mutation (classification is
        # O(E×W) over the whole population; aggregate paths call it thrice).
        self._classify_cache: tuple[np.ndarray, np.ndarray, np.ndarray] | None = None

    # ---- entity registry -------------------------------------------------
    @property
    def num_entities(self) -> int:
        return len(self._entity_of)

    @property
    def entities(self) -> list:
        return list(self._entity_of)

    def row(self, entity) -> int | None:
        return self._row_of.get(entity)

    def _row_or_create(self, entity) -> int:
        r = self._row_of.get(entity)
        if r is not None:
            return r
        r = len(self._entity_of)
        if r >= self._values.shape[0]:
            new_cap = max(1, self._values.shape[0]) * _GROW_FACTOR
            self._values = np.concatenate(
                [self._values, np.zeros((new_cap - self._values.shape[0],) + self._values.shape[1:],
                                        dtype=np.float32)])
            self._counts = np.concatenate(
                [self._counts, np.zeros((new_cap - self._counts.shape[0], self._buf_windows),
                                        dtype=np.int32)])
        self._row_of[entity] = r
        self._entity_of.append(entity)
        self._classify_cache = None
        return r

    def remove_entities(self, entities) -> None:
        """Drop entities (MetricSampleAggregator.removeEntities). Rows are
        compacted lazily by rebuilding the arrays."""
        drop = {e for e in entities if e in self._row_of}
        if not drop:
            return
        keep_rows = [self._row_of[e] for e in self._entity_of if e not in drop]
        keep_entities = [e for e in self._entity_of if e not in drop]
        self._values = self._values[keep_rows].copy() if keep_rows else self._values[:0]
        self._counts = self._counts[keep_rows].copy() if keep_rows else self._counts[:0]
        self._entity_of = keep_entities
        self._row_of = {e: i for i, e in enumerate(keep_entities)}
        self._classify_cache = None

    def retain_entities(self, entities) -> None:
        keep = set(entities)
        self.remove_entities([e for e in self._entity_of if e not in keep])

    # ---- window bookkeeping ---------------------------------------------
    @property
    def current_window_index(self) -> int | None:
        return self._current_window_index

    @property
    def oldest_window_index(self) -> int | None:
        """Oldest retained window (stable range start). Stable windows are
        those already rolled past: [oldest, current)."""
        if self._current_window_index is None:
            return None
        return max(self._first_window_index, self._current_window_index - self._num_stable)

    def stable_window_indices(self) -> list[int]:
        if self._current_window_index is None:
            return []
        return list(range(self.oldest_window_index, self._current_window_index))

    def _slot(self, window_index: int) -> int:
        return window_index % self._buf_windows

    def roll_to(self, window_index: int) -> int:
        """Advance the current window to ``window_index``; newly-entered ring
        slots are reset (RawMetricValues.resetWindowIndices). Returns number
        of abandoned samples."""
        if self._current_window_index is None:
            self._first_window_index = window_index
            self._current_window_index = window_index
            return 0
        current = self._current_window_index
        if window_index <= current:
            return 0
        steps = window_index - current
        abandoned = 0
        n = min(steps, self._buf_windows)
        for i in range(n):
            slot = self._slot(window_index - n + 1 + i)
            abandoned += int(self._counts[:len(self._entity_of), slot].sum())
            self._counts[:, slot] = 0
            self._values[:, :, slot] = 0.0
        self._current_window_index = window_index
        self._classify_cache = None
        return abandoned

    # ---- ingest ----------------------------------------------------------
    def add_sample(self, entity, window_index: int, metric_values: np.ndarray) -> bool:
        """Add one sample vector (aligned with the MetricDef ids) to the
        entity's window. Late samples older than the retained range are
        dropped (RawMetricValues.addSample:121-127); future windows roll the
        buffer forward (MetricSampleAggregator.addSample window maintenance).
        """
        if self._current_window_index is None or window_index > self._current_window_index:
            self.roll_to(window_index)
        if window_index < self.oldest_window_index:
            return False
        row = self._row_or_create(entity)
        slot = self._slot(window_index)
        count = self._counts[row, slot]
        v = np.asarray(metric_values, dtype=np.float32)
        if count == 0:
            self._values[row, :, slot] = v
        else:
            cur = self._values[row, :, slot].copy()
            cur[self._avg_mask] += v[self._avg_mask]
            cur[self._max_mask] = np.maximum(cur[self._max_mask], v[self._max_mask])
            cur[self._latest_mask] = v[self._latest_mask]
            self._values[row, :, slot] = cur
        self._counts[row, slot] = count + 1
        self._classify_cache = None
        return True

    def add_samples_batch(self, rows: np.ndarray, window_index: int, values: np.ndarray) -> None:
        """Vectorized ingest of many single-sample entities in one window
        (the common case: one sample per partition per fetch). ``rows`` MUST
        be unique row indices — the aggregator deduplicates before calling."""
        slot = self._slot(window_index)
        fresh = self._counts[rows, slot] == 0
        fr = rows[fresh]
        self._values[fr, :, slot] = values[fresh]
        stale = rows[~fresh]
        if stale.size:
            sv = values[~fresh]
            cur = self._values[stale, :, slot]
            cur[:, self._avg_mask] += sv[:, self._avg_mask]
            cur[:, self._max_mask] = np.maximum(cur[:, self._max_mask], sv[:, self._max_mask])
            cur[:, self._latest_mask] = sv[:, self._latest_mask]
            self._values[stale, :, slot] = cur
        self._counts[rows, slot] += 1
        self._classify_cache = None

    def num_samples(self) -> int:
        return int(self._counts[:len(self._entity_of)].sum())

    # ---- classification & aggregation (vectorized) ----------------------
    def _stable_slots(self) -> np.ndarray:
        return np.array([self._slot(w) for w in self.stable_window_indices()], dtype=np.int64)

    def classify(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Classify every (entity, stable window) into an Extrapolation
        category; returns (categories[E, Ws], valid[E, Ws], extrapolated[E, Ws]).

        Mirrors RawMetricValues.aggregate's category decision
        (RawMetricValues.java:292-330) and the validity rules of
        updateEnoughSamples/updateForcedInsufficient/updateAvgAdjacent
        (RawMetricValues.java:425-465): a window is valid iff it has any
        sample or both stable neighbours have >= min samples; edge stable
        windows have no neighbours. Memoized until the next mutation.
        """
        if self._classify_cache is not None:
            return self._classify_cache
        e = len(self._entity_of)
        slots = self._stable_slots()
        counts = self._counts[:e][:, slots]  # [E, Ws]
        ws = len(slots)

        enough = counts >= self._min_samples
        avg_avail = (counts >= self._half_min) & ~enough
        # Neighbour sufficiency (stable-window neighbours only; edges excluded).
        prev_ok = np.zeros_like(enough)
        next_ok = np.zeros_like(enough)
        if ws >= 3:
            prev_ok[:, 1:] = counts[:, :-1] >= self._min_samples
            next_ok[:, :-1] = counts[:, 1:] >= self._min_samples
            prev_ok[:, 0] = False
            next_ok[:, -1] = False
        adjacent = ~enough & ~avg_avail & prev_ok & next_ok
        forced = ~enough & ~avg_avail & ~adjacent & (counts > 0)
        nothing = ~enough & ~avg_avail & ~adjacent & (counts == 0)

        cats = np.full((e, ws), int(Extrapolation.NONE), dtype=np.int8)
        cats[avg_avail] = int(Extrapolation.AVG_AVAILABLE)
        cats[adjacent] = int(Extrapolation.AVG_ADJACENT)
        cats[forced] = int(Extrapolation.FORCED_INSUFFICIENT)
        cats[nothing] = int(Extrapolation.NO_VALID_EXTRAPOLATION)

        valid = (counts > 0) | (prev_ok & next_ok)
        extrapolated = valid & ~enough
        self._classify_cache = (cats, valid, extrapolated)
        return self._classify_cache

    def aggregate_values(self) -> tuple[np.ndarray, np.ndarray]:
        """Reduce every stable window for every entity and metric; returns
        (agg[E, M, Ws] float32, cats[E, Ws] int8).

        AVG metrics divide the accumulated sum by the count; MAX/LATEST carry
        the stored value (RawMetricValues.getValue). AVG_ADJACENT windows
        blend (prev, cur, next) per RawMetricValues.java:303-318.
        """
        e = len(self._entity_of)
        slots = self._stable_slots()
        counts = self._counts[:e][:, slots].astype(np.float32)  # [E, Ws]
        vals = self._values[:e][:, :, slots]  # [E, M, Ws]
        cats, _valid, _extra = self.classify()

        safe_counts = np.maximum(counts, 1.0)[:, None, :]
        reduced = np.where(self._avg_mask[None, :, None], vals / safe_counts, vals)
        reduced = np.where((counts[:, None, :] > 0), reduced, 0.0)

        adjacent = cats == int(Extrapolation.AVG_ADJACENT)
        if adjacent.any() and len(slots) >= 3:
            prev_v = np.zeros_like(vals)
            next_v = np.zeros_like(vals)
            prev_v[:, :, 1:] = vals[:, :, :-1]
            next_v[:, :, :-1] = vals[:, :, 1:]
            prev_c = np.zeros_like(counts)
            next_c = np.zeros_like(counts)
            prev_c[:, 1:] = counts[:, :-1]
            next_c[:, :-1] = counts[:, 1:]
            has_cur = (counts > 0).astype(np.float32)
            total = prev_v + next_v + vals * (counts[:, None, :] > 0)
            denom_avg = prev_c + next_c + counts
            denom_other = 2.0 + has_cur
            blended = np.where(self._avg_mask[None, :, None],
                               total / np.maximum(denom_avg, 1.0)[:, None, :],
                               total / denom_other[:, None, :])
            reduced = np.where(adjacent[:, None, :], blended, reduced)
        return reduced.astype(np.float32), cats

    def entity_validity(self, max_allowed_extrapolations: int) -> np.ndarray:
        """Per-entity validity over all stable windows
        (RawMetricValues.isValid)."""
        _cats, valid, extrapolated = self.classify()
        return valid.all(axis=1) & (extrapolated.sum(axis=1) <= max_allowed_extrapolations)
