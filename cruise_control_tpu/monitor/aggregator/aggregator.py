"""Windowed metric sample aggregation with completeness accounting.

Reference parity: cruise-control-core .../aggregator/MetricSampleAggregator.java
(addSample:141, aggregate:193, completeness:277), AggregationOptions.java
(ENTITY vs ENTITY_GROUP granularity), MetricSampleCompleteness.java and
NotEnoughValidWindowsException.java.

Redesign: entities live as rows of one dense RawMetricStore, so completeness
ratios and validity are single vectorized reductions. ``aggregate`` returns
dense ndarrays ready to be fed to the JAX model builder — not per-entity
objects.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Any, Callable, Hashable, Sequence

import numpy as np

from ...metricdef.metricdef import MetricDef
from .extrapolation import Extrapolation
from .raw_store import RawMetricStore


class NotEnoughValidWindowsError(RuntimeError):
    """Too few windows satisfy the completeness requirements
    (NotEnoughValidWindowsException.java)."""


class Granularity(enum.Enum):
    """AggregationOptions.Granularity: ENTITY treats each entity separately;
    ENTITY_GROUP invalidates a whole group if any member entity is invalid."""

    ENTITY = "entity"
    ENTITY_GROUP = "entity_group"


@dataclasses.dataclass(frozen=True)
class AggregationOptions:
    min_valid_entity_ratio: float = 0.0
    min_valid_entity_group_ratio: float = 0.0
    min_valid_windows: int = 1
    max_allowed_extrapolations_per_entity: int = 8
    granularity: Granularity = Granularity.ENTITY
    interested_entities: tuple | None = None  # None = all known entities
    include_invalid_entities: bool = False
    # Wall-clock range restriction (LOAD/PARTITION_LOAD start/end/time
    # request params → MetricSampleAggregator.aggregate(from, to)); -1 =
    # unbounded. A window overlaps the range iff its [w*ms, (w+1)*ms) span
    # intersects [start_ms, end_ms].
    start_ms: int = -1
    end_ms: int = -1


@dataclasses.dataclass
class MetricSampleCompleteness:
    """Per-window coverage ratios over the interested entity universe
    (MetricSampleCompleteness.java)."""

    window_indices: list[int]
    valid_entity_ratio_by_window: np.ndarray  # [W]
    valid_entity_group_ratio_by_window: np.ndarray  # [W]
    valid_windows: list[int]
    valid_entity_ratio: float
    valid_entity_group_ratio: float
    generation: int


@dataclasses.dataclass
class AggregationResult:
    """Dense aggregation output: ``values[E, M, W]`` over the valid windows,
    aligned with ``entities`` and ``window_indices``."""

    entities: list
    window_indices: list[int]
    values: np.ndarray          # [E, M, W] float32
    extrapolations: np.ndarray  # [E, W] int8 Extrapolation codes
    entity_valid: np.ndarray    # [E] bool
    completeness: MetricSampleCompleteness


class MetricSampleAggregator:
    """Thread-safe windowed aggregator over one entity kind.

    ``group_fn`` maps an entity to its aggregation group (topic for
    partition entities; None for broker entities).
    """

    def __init__(self, num_windows: int, window_ms: int, min_samples_per_window: int,
                 metric_def: MetricDef, group_fn: Callable[[Any], Hashable] | None = None,
                 completeness_cache_size: int = 5):
        self._lock = threading.RLock()
        self._window_ms = int(window_ms)
        self._num_windows = int(num_windows)
        self._metric_def = metric_def
        self._group_fn = group_fn or (lambda e: e)
        self._store = RawMetricStore(num_windows, min_samples_per_window, metric_def)
        self._generation = 0
        # Bounded aggregation/completeness result cache
        # (MonitorConfig *.metric.sample.aggregator.completeness.cache.size;
        # distinct AggregationOptions keys evict oldest-first).
        self._cache: dict[tuple, AggregationResult] = {}
        self._cache_size = max(1, completeness_cache_size)

    @property
    def window_ms(self) -> int:
        return self._window_ms

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def store(self) -> RawMetricStore:
        """The raw store. READ-ONLY access: mutating it directly bypasses
        the aggregator's generation bump and can serve stale cached
        aggregates — use the aggregator's own ingest/roll methods."""
        return self._store

    def roll_to(self, window_index: int) -> int:
        """Advance the current window (MetricSampleAggregator's window
        maintenance on sample arrival, exposed for time-driven rollout);
        bumps the generation so cached aggregates invalidate."""
        with self._lock:
            abandoned = self._store.roll_to(window_index)
            self._generation += 1
        return abandoned

    def window_index_of(self, time_ms: int) -> int:
        return int(time_ms) // self._window_ms

    # ---- ingest ---------------------------------------------------------
    def add_sample(self, entity, time_ms: int, metric_values: np.ndarray) -> bool:
        """Add one sample (MetricSampleAggregator.addSample). Bumps the
        aggregator generation used for proposal-cache invalidation
        (LongGenerationed semantics)."""
        with self._lock:
            ok = self._store.add_sample(entity, self.window_index_of(time_ms), metric_values)
            if ok:
                self._generation += 1
                self._cache.clear()
            return ok

    def add_samples_batch(self, entities: Sequence, time_ms: int, values: np.ndarray) -> None:
        """Vectorized ingest: one sample per entity for one window."""
        with self._lock:
            w = self.window_index_of(time_ms)
            self._store.roll_to(w)
            if w < self._store.oldest_window_index:
                return
            rows = np.array([self._store._row_or_create(e) for e in entities], dtype=np.int64)
            values = np.asarray(values, dtype=np.float32)
            uniq, first_idx, counts = np.unique(rows, return_index=True, return_counts=True)
            if len(uniq) == len(rows):
                self._store.add_samples_batch(rows, w, values)
            else:
                # Duplicate entities in one batch: fast-path the unique first
                # occurrences, loop the rest (numpy fancy-index writes would
                # silently collapse repeated rows).
                self._store.add_samples_batch(rows[first_idx], w, values[first_idx])
                dup_mask = np.ones(len(rows), dtype=bool)
                dup_mask[first_idx] = False
                for i in np.nonzero(dup_mask)[0]:
                    self._store.add_sample(entities[i], w, values[i])
            self._generation += 1
            self._cache.clear()

    # ---- windows --------------------------------------------------------
    def available_windows(self) -> list[int]:
        with self._lock:
            return self._store.stable_window_indices()

    def num_available_windows(self) -> int:
        return len(self.available_windows())

    def all_window_times(self) -> list[int]:
        return [w * self._window_ms for w in self.available_windows()]

    def num_samples(self) -> int:
        with self._lock:
            return self._store.num_samples()

    def retain_entities(self, entities) -> None:
        with self._lock:
            self._store.retain_entities(entities)
            self._generation += 1
            self._cache.clear()

    def remove_entities(self, entities) -> None:
        with self._lock:
            self._store.remove_entities(entities)
            self._generation += 1
            self._cache.clear()

    def clear(self) -> None:
        with self._lock:
            self._store = RawMetricStore(
                self._num_windows, self._store._min_samples, self._metric_def)
            self._generation += 1
            self._cache.clear()

    # ---- aggregation ----------------------------------------------------
    def completeness(self, options: AggregationOptions) -> MetricSampleCompleteness:
        with self._lock:
            return self._completeness_locked(options)

    def _window_in_range(self, w: int, options: AggregationOptions) -> bool:
        """Window w spans [w*window_ms, (w+1)*window_ms); it participates
        when that span intersects the requested [start_ms, end_ms]."""
        if options.start_ms >= 0 and (w + 1) * self._window_ms <= options.start_ms:
            return False
        if options.end_ms >= 0 and w * self._window_ms > options.end_ms:
            return False
        return True

    def _group_indices(self, entities) -> tuple[np.ndarray, int]:
        """Dense group index per entity + group count."""
        group_of: dict = {}
        idx = np.array([group_of.setdefault(self._group_fn(e), len(group_of))
                        for e in entities], dtype=np.int64)
        return idx, max(1, len(group_of))

    def _entity_rows(self, options: AggregationOptions) -> tuple[list, np.ndarray]:
        known = self._store.entities
        if options.interested_entities is None:
            return known, np.arange(len(known), dtype=np.int64)
        rows, ents = [], []
        for e in options.interested_entities:
            r = self._store.row(e)
            ents.append(e)
            rows.append(-1 if r is None else r)
        return ents, np.array(rows, dtype=np.int64)

    def _completeness_locked(self, options: AggregationOptions) -> MetricSampleCompleteness:
        entities, rows = self._entity_rows(options)
        windows = self._store.stable_window_indices()
        if not windows or not entities:
            raise NotEnoughValidWindowsError(
                f"0 valid windows (required {options.min_valid_windows})")

        in_range = np.array([self._window_in_range(w, options)
                             for w in windows])
        if not in_range.any():
            raise NotEnoughValidWindowsError(
                f"0 stable windows overlap [{options.start_ms}, "
                f"{options.end_ms}] (required {options.min_valid_windows})")

        _cats, valid, extrapolated = self._store.classify()
        # Unknown interested entities contribute all-invalid rows.
        valid_sel = np.zeros((len(entities), valid.shape[1]), dtype=bool)
        known_mask = rows >= 0
        valid_sel[known_mask] = valid[rows[known_mask]]
        over_extra = np.zeros(len(entities), dtype=bool)
        over_extra[known_mask] = (
            extrapolated[rows[known_mask]].sum(axis=1)
            > options.max_allowed_extrapolations_per_entity)
        valid_sel[over_extra] = False

        group_index, n_g = self._group_indices(entities)

        # Per-window entity ratio; group valid in a window iff all members valid.
        entity_ratio = valid_sel.mean(axis=0)
        group_valid = np.ones((n_g, valid_sel.shape[1]), dtype=bool)
        np.logical_and.at(group_valid, group_index, valid_sel)
        group_ratio = group_valid.mean(axis=0)

        if options.granularity is Granularity.ENTITY_GROUP:
            # Entity coverage counts only entities in fully-valid groups
            # (AggregationOptions ENTITY_GROUP semantics).
            entity_ratio = (group_valid[group_index] & valid_sel).mean(axis=0)

        ok = (entity_ratio >= options.min_valid_entity_ratio) & \
             (group_ratio >= options.min_valid_entity_group_ratio) & in_range
        valid_windows = [w for w, keep in zip(windows, ok) if keep]
        if len(valid_windows) < options.min_valid_windows:
            raise NotEnoughValidWindowsError(
                f"{len(valid_windows)} valid windows out of {len(windows)} "
                f"(required {options.min_valid_windows}); "
                f"entity ratios {np.round(entity_ratio, 3).tolist()}")
        sel = ok
        return MetricSampleCompleteness(
            window_indices=list(windows),
            valid_entity_ratio_by_window=entity_ratio,
            valid_entity_group_ratio_by_window=group_ratio,
            valid_windows=valid_windows,
            valid_entity_ratio=float(entity_ratio[sel].mean()) if sel.any() else 0.0,
            valid_entity_group_ratio=float(group_ratio[sel].mean()) if sel.any() else 0.0,
            generation=self._generation,
        )

    def aggregate(self, options: AggregationOptions) -> AggregationResult:
        """Aggregate stable windows meeting the completeness requirements
        (MetricSampleAggregator.aggregate:193). Cached by generation."""
        from ...utils.tracing import TRACER
        with self._lock, TRACER.span("monitor.aggregate") as sp:
            cache_key = (self._generation, options.min_valid_entity_ratio,
                         options.min_valid_entity_group_ratio, options.min_valid_windows,
                         options.max_allowed_extrapolations_per_entity, options.granularity,
                         options.interested_entities, options.include_invalid_entities,
                         options.start_ms, options.end_ms)
            if cache_key in self._cache:
                sp.set(cache_hit=True, generation=self._generation)
                return self._cache[cache_key]
            sp.set(cache_hit=False, generation=self._generation)
            completeness = self._completeness_locked(options)
            entities, rows = self._entity_rows(options)
            values, cats = self._store.aggregate_values()
            windows = self._store.stable_window_indices()
            valid_set = set(completeness.valid_windows)
            keep_cols = np.array([w in valid_set for w in windows])

            known_mask = rows >= 0
            out_vals = np.zeros((len(entities), values.shape[1], int(keep_cols.sum())),
                                dtype=np.float32)
            out_cats = np.full((len(entities), int(keep_cols.sum())),
                               int(Extrapolation.NO_VALID_EXTRAPOLATION), dtype=np.int8)
            out_vals[known_mask] = values[rows[known_mask]][:, :, keep_cols]
            out_cats[known_mask] = cats[rows[known_mask]][:, keep_cols]

            entity_valid = np.zeros(len(entities), dtype=bool)
            ev = self._store.entity_validity(options.max_allowed_extrapolations_per_entity)
            entity_valid[known_mask] = ev[rows[known_mask]]

            if options.granularity is Granularity.ENTITY_GROUP:
                # One invalid member invalidates the whole group
                # (AggregationOptions ENTITY_GROUP semantics).
                group_index, n_g = self._group_indices(entities)
                group_valid = np.ones(n_g, dtype=bool)
                np.logical_and.at(group_valid, group_index, entity_valid)
                entity_valid = entity_valid & group_valid[group_index]

            if not options.include_invalid_entities:
                # Zero out metric rows of invalid entities rather than drop
                # them, keeping array alignment with `entities`.
                out_vals[~entity_valid] = 0.0

            # Freeze result arrays: the object is cached and shared between
            # callers; in-place mutation must fail loudly, not poison the cache.
            for arr in (out_vals, out_cats, entity_valid):
                arr.setflags(write=False)

            result = AggregationResult(
                entities=entities,
                window_indices=completeness.valid_windows,
                values=out_vals,
                extrapolations=out_cats,
                entity_valid=entity_valid,
                completeness=completeness,
            )
            self._cache[cache_key] = result
            while len(self._cache) > self._cache_size:
                self._cache.pop(next(iter(self._cache)))
            sp.set(num_entities=len(entities),
                   num_windows=len(completeness.valid_windows))
            return result

    def peek_current_window(self) -> tuple[list, np.ndarray]:
        """Reduce the in-fill current window only
        (MetricSampleAggregator.peekCurrentWindow)."""
        with self._lock:
            e = self._store.num_entities
            cur = self._store.current_window_index
            if cur is None or e == 0:
                return [], np.zeros((0, self._metric_def.num_metrics), dtype=np.float32)
            slot = self._store._slot(cur)
            counts = self._store._counts[:e, slot].astype(np.float32)
            vals = self._store._values[:e, :, slot]
            safe = np.maximum(counts, 1.0)[:, None]
            avg_mask = self._store._avg_mask
            reduced = np.where(avg_mask[None, :], vals / safe, vals)
            reduced = np.where(counts[:, None] > 0, reduced, 0.0)
            return self._store.entities, reduced.astype(np.float32)
