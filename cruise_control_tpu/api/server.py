"""The REST server: stdlib threaded HTTP front-end over the facade.

Reference parity: servlet/KafkaCruiseControlServletApp (Jetty) +
KafkaCruiseControlRequestHandler (dispatch, :~40) +
KafkaCruiseControlEndPoints — collapsed onto ThreadingHTTPServer. Request
flow mirrors the reference: resolve endpoint → authenticate/authorize →
two-step purgatory gate → parse parameters → sync handler or async
user-task submission (202 + ``User-Task-ID`` when still running).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.parse
from concurrent.futures import TimeoutError as FuturesTimeoutError
from functools import lru_cache
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from ..config.cruise_control_config import CruiseControlConfig
from ..facade import CruiseControl
from ..fleet.registry import ClusterPausedError, UnknownClusterError
from ..monitor.load_monitor import NotEnoughValidWindowsError
from ..serving import (
    AdmissionController, AdmissionShedError, AsyncTaskEngine, ResponseCache,
    TaskQueueFullError, canonical_params, task_class_of,
)
from ..serving.cache import CACHEABLE_ENDPOINTS, COALESCIBLE_ENDPOINTS
from ..utils.resilience import BreakerOpenError
from . import responses
from .endpoints import REVIEWABLE_ENDPOINTS, EndPoint, endpoint_for_path
from .parameters import ParameterParseError, parse_parameters
from .purgatory import Purgatory
from .security import (
    AuthenticationError, AuthorizationError, NoopSecurityProvider, Principal,
    SecurityProvider,
)
from .user_tasks import (
    USER_TASK_HEADER, TaskOwnershipError, TooManyUserTasksError,
    UserTaskManager,
)

LOG = logging.getLogger(__name__)

URL_PREFIX = "/kafkacruisecontrol"

# Endpoints answered inline; everything else runs as an async user task
# (handler/sync vs handler/async split in the reference).
_SYNC_ENDPOINTS = {
    EndPoint.STATE, EndPoint.KAFKA_CLUSTER_STATE, EndPoint.USER_TASKS,
    EndPoint.REVIEW_BOARD, EndPoint.PERMISSIONS, EndPoint.REVIEW,
    EndPoint.PAUSE_SAMPLING, EndPoint.RESUME_SAMPLING,
    EndPoint.STOP_PROPOSAL_EXECUTION, EndPoint.ADMIN, EndPoint.BOOTSTRAP,
    EndPoint.TRAIN, EndPoint.RIGHTSIZE, EndPoint.FLEET, EndPoint.HEALS,
    EndPoint.FORECAST, EndPoint.JOURNEYS, EndPoint.SLO, EndPoint.REDTEAM,
}

# Endpoints that consume solver time. In fleet mode these (a) are refused
# for paused clusters and (b) run through the FleetScheduler as ON_DEMAND
# jobs, so one cluster's requests share the device fairly with every
# other cluster's precompute and self-healing (fleet.scheduler).
# RIGHTSIZE is deliberately absent: it hands a recommendation to the
# provisioner without touching the solver (and is answered inline).
_SOLVER_ENDPOINTS = {
    EndPoint.PROPOSALS, EndPoint.REBALANCE, EndPoint.ADD_BROKER,
    EndPoint.REMOVE_BROKER, EndPoint.DEMOTE_BROKER,
    EndPoint.FIX_OFFLINE_REPLICAS, EndPoint.TOPIC_CONFIGURATION,
    EndPoint.REMOVE_DISKS, EndPoint.COMPARE_FUTURES,
}

# Async endpoints whose work is a cluster-model BUILD (device transfers +
# stats kernels, no solver search). In fleet mode these run through the
# FleetScheduler too (round 20, ROADMAP item 4 tail) so the handler layer
# never touches the device directly — but they stay outside
# _SOLVER_ENDPOINTS: reads keep working against a PAUSED cluster, and the
# breaker treats them as monitor traffic.
_MODEL_BUILD_ENDPOINTS = {EndPoint.LOAD, EndPoint.PARTITION_LOAD}


# Proposal-executing endpoints gated by request.reason.required (the
# parameter classes that consult REQUEST_REASON_REQUIRED_CONFIG:
# Rebalance/AddedOrRemovedBroker/DemoteBroker/FixOfflineReplicas/
# TopicConfiguration/RemoveDisks Parameters.java).
_REASON_REQUIRED_ENDPOINTS = {
    EndPoint.REBALANCE, EndPoint.ADD_BROKER, EndPoint.REMOVE_BROKER,
    EndPoint.DEMOTE_BROKER, EndPoint.FIX_OFFLINE_REPLICAS,
    EndPoint.TOPIC_CONFIGURATION, EndPoint.REMOVE_DISKS,
}

# The two-goal chain kafka_assigner mode swaps in
# (ParameterUtils.getGoals:755-771, RunnableUtils.KAFKA_ASSIGNER_GOALS).
_KAFKA_ASSIGNER_GOALS = ["KafkaAssignerEvenRackAwareGoal",
                         "KafkaAssignerDiskUsageDistributionGoal"]

# Endpoints whose EXPLICIT goal lists must contain the configured hard
# goals (GoalBasedOperationRunnable.init → sanityCheckGoals; PROPOSALS is
# dryrun-only and exempt, as in ProposalsParameters).
_HARD_GOAL_CHECKED_ENDPOINTS = {
    EndPoint.REBALANCE, EndPoint.ADD_BROKER, EndPoint.REMOVE_BROKER,
    EndPoint.FIX_OFFLINE_REPLICAS, EndPoint.TOPIC_CONFIGURATION,
}


def _resolve_goal_names(p: dict) -> list[str] | None:
    """Request goal list after mode switches (ParameterUtils.getGoals:755):
    kafka_assigner mode uses exactly the two assigner goals and conflicts
    with both explicit goals and rebalance-disk mode; rebalance-disk mode
    picks its intra-broker chain in the facade."""
    explicit = list(p["goals"]) if "goals" in p else None
    if p.get("kafka_assigner"):
        if p.get("rebalance_disk"):
            raise ParameterParseError(
                "Kafka assigner mode and rebalance disk mode cannot be set "
                "at the same time.")
        if explicit:
            raise ParameterParseError(
                "Kafka assigner mode does not support explicitly specifying "
                "goals in request.")
        if p.get("use_ready_default_goals"):
            raise ParameterParseError(
                "use_ready_default_goals is about the DEFAULT goal chain; "
                "it cannot be combined with kafka_assigner mode.")
        return list(_KAFKA_ASSIGNER_GOALS)
    if p.get("rebalance_disk") and explicit:
        raise ParameterParseError(
            "Rebalance disk mode does not support explicitly specifying "
            "goals in request.")
    if explicit and p.get("use_ready_default_goals"):
        raise ParameterParseError(
            "use_ready_default_goals cannot be combined with explicitly "
            "specified goals.")
    return explicit


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class CruiseControlApi:
    """Transport-independent request handling (so tests can drive it
    without sockets, like the reference's servlet unit tests)."""

    def __init__(self, cc: CruiseControl,
                 security_provider: SecurityProvider | None = None,
                 config: CruiseControlConfig | None = None,
                 fleet=None):
        self._cc = cc
        # Optional fleet.FleetRegistry: enables ?cluster= routing on every
        # endpoint plus the FLEET dashboard. The default (no ?cluster=)
        # path always serves ``cc`` — single-cluster deployments are
        # byte-for-byte unchanged.
        self._fleet = fleet
        cfg = config or cc.config
        self._config = cfg
        self._security = security_provider or (
            self._configured_security(cfg) if cfg.get_boolean("webserver.security.enable")
            else NoopSecurityProvider())
        self._two_step = cfg.get_boolean("two.step.verification.enabled")
        self._purgatory = Purgatory(
            retention_ms=cfg.get_long("two.step.purgatory.retention.time.ms"))
        from .user_tasks import CC_ADMIN, CC_MONITOR, KAFKA_ADMIN, KAFKA_MONITOR
        retention_overrides = {
            cls: cfg.get_long(key)
            for cls, key in (
                (KAFKA_MONITOR, "completed.kafka.monitor.user.task.retention.time.ms"),
                (KAFKA_ADMIN, "completed.kafka.admin.user.task.retention.time.ms"),
                (CC_MONITOR, "completed.cruise.control.monitor.user.task.retention.time.ms"),
                (CC_ADMIN, "completed.cruise.control.admin.user.task.retention.time.ms"))
            if cfg.get(key) is not None}
        # Serving front door (round 20): the unified async task engine
        # (bounded per-class queues), the model-generation response
        # cache, cross-user coalescing, and queue-depth admission.
        self._engine = AsyncTaskEngine(
            viewer_capacity=cfg.get_int("serving.task.queue.viewer.capacity"),
            solver_capacity=cfg.get_int("serving.task.queue.solver.capacity"),
            viewer_threads=cfg.get_int("serving.task.viewer.threads"),
            solver_threads=cfg.get_int("serving.task.solver.threads"))
        self._response_cache = ResponseCache(
            max_entries=cfg.get_int("serving.cache.max.entries"),
            enabled=cfg.get_boolean("serving.cache.enabled"),
            cache_state=cfg.get_boolean("serving.cache.state.enabled"))
        self._coalesce_enabled = cfg.get_boolean("serving.coalesce.enabled")
        self._admission = AdmissionController(
            viewer_max=cfg.get_int("serving.admission.queue.viewer.max"),
            solver_max=cfg.get_int("serving.admission.queue.solver.max"),
            enabled=cfg.get_boolean("serving.admission.enabled"))
        self._tasks = UserTaskManager(
            max_active_tasks=cfg.get_int("max.active.user.tasks"),
            completed_retention_ms=cfg.get_long(
                "completed.user.task.retention.time.ms"),
            max_cached_completed_monitor_tasks=cfg.get_int(
                "max.cached.completed.kafka.monitor.user.tasks"),
            max_cached_completed_admin_tasks=cfg.get_int(
                "max.cached.completed.kafka.admin.user.tasks"),
            max_cached_completed_tasks=cfg.get_int(
                "max.cached.completed.user.tasks"),
            max_cached_completed_cc_monitor_tasks=cfg.get_int(
                "max.cached.completed.cruise.control.monitor.user.tasks"),
            max_cached_completed_cc_admin_tasks=cfg.get_int(
                "max.cached.completed.cruise.control.admin.user.tasks"),
            retention_ms_by_class=retention_overrides,
            engine=self._engine)
        self._async_wait_s = cfg.get_long(
            "webserver.request.maxBlockTimeMs") / 1000.0
        self._reason_required = cfg.get_boolean("request.reason.required")

    @staticmethod
    def _configured_security(cfg: CruiseControlConfig) -> SecurityProvider:
        from .security import BasicSecurityProvider, SpnegoSecurityProvider
        cls_name = cfg.get("webserver.security.provider")
        if cls_name.endswith("BasicSecurityProvider"):
            return BasicSecurityProvider(
                credentials_file=cfg.get("webserver.auth.credentials.file") or "")
        if cls_name.endswith("SpnegoSecurityProvider"):
            return SpnegoSecurityProvider.from_config(cfg)
        if cls_name.endswith("JwtSecurityProvider"):
            from .security import JwtSecurityProvider
            return JwtSecurityProvider.from_config(cfg)
        import importlib
        module, _, name = cls_name.rpartition(".")
        return getattr(importlib.import_module(module), name)()

    def authenticate_readonly(self, headers: dict[str, str],
                              remote_addr: str = "") -> None:
        """Auth gate for the non-endpoint GET surfaces (/metrics, /openapi):
        any authenticated principal may read them; raises AuthenticationError
        when security is enabled and credentials are missing/invalid."""
        self._security.authenticate(headers, remote_addr)

    def metrics_text(self) -> str:
        """Prometheus exposition of the sensor registry + live state gauges
        (the JMX sensor surface of Sensors.md as a /metrics scrape)."""
        from ..utils.sensors import SENSORS
        extra: dict = {}
        try:
            # Live device-side telemetry (utils.xla_telemetry): memory
            # gauges refreshed at scrape time so the series track the
            # allocator, not the last model build.
            from ..utils import xla_telemetry
            xla_telemetry.refresh_device_gauges()
        except Exception:  # noqa: BLE001 — a scrape must not 500
            LOG.warning("device telemetry refresh failed", exc_info=True)
        try:
            st = self._cc.state()
            ms = st.get("MonitorState", {})
            extra["monitor_num_valid_windows"] = ms.get("numValidWindows", 0)
            extra["monitor_monitored_partitions_percentage"] = \
                ms.get("monitoringCoveragePct", 0.0)
            extra["monitor_total_num_partitions"] = \
                ms.get("totalNumPartitions", 0)
            extra["analyzer_balancedness_score"] = \
                st.get("AnalyzerState", {}).get("balancednessScore") or 0.0
            ex = st.get("ExecutorState", {})
            extra["executor_in_execution"] = \
                0.0 if ex.get("state") == "NO_TASK_IN_PROGRESS" else 1.0
            ad = st.get("AnomalyDetectorState", {})
            # selfHealing(Enabled|Disabled) are LISTS of type names
            # (AnomalyDetectorManager.state).
            for a_type in ad.get("selfHealingEnabled") or ():
                SENSORS.gauge("anomaly_detector_self_healing_enabled", 1.0,
                              labels={"anomaly_type": str(a_type)})
            for a_type in ad.get("selfHealingDisabled") or ():
                SENSORS.gauge("anomaly_detector_self_healing_enabled", 0.0,
                              labels={"anomaly_type": str(a_type)})
        except Exception:  # noqa: BLE001 — a scrape must not 500 on state
            LOG.warning("metrics state snapshot failed", exc_info=True)
        if self._fleet is not None:
            # Per-cluster fleet gauges (explicit labels; the ambient
            # cluster_label context covers per-cluster WORK, a scrape is
            # fleet-wide).
            for e in self._fleet.entries():
                labels = {"cluster": e.cluster_id}
                SENSORS.gauge("fleet_cluster_paused",
                              1.0 if e.paused else 0.0, labels=labels)
                if e.shape is not None:
                    SENSORS.gauge("fleet_cluster_brokers", e.shape[0],
                                  labels=labels)
                    SENSORS.gauge("fleet_cluster_partitions", e.shape[1],
                                  labels=labels)
        return SENSORS.render(extra)

    @property
    def purgatory(self) -> Purgatory:
        return self._purgatory

    @property
    def user_tasks(self) -> UserTaskManager:
        return self._tasks

    def shutdown(self) -> None:
        self._tasks.shutdown()
        self._engine.shutdown()

    @property
    def task_engine(self) -> AsyncTaskEngine:
        return self._engine

    @property
    def response_cache(self) -> ResponseCache:
        return self._response_cache

    @property
    def admission(self) -> AdmissionController:
        return self._admission

    def serving_stats(self) -> dict:
        """One snapshot of the serving front door's counters — what the
        load harness reads before/after a run (engine queues and service
        rates, cache hits/misses, coalesced joins, per-class sheds)."""
        return {"engine": self._engine.stats(),
                "cache": self._response_cache.stats(),
                "coalesced": self._tasks.coalesced,
                "admission": self._admission.stats()}

    # -- the dispatch pipeline ---------------------------------------------
    def handle(self, method: str, path: str, query_string: str = "",
               headers: dict[str, str] | None = None,
               remote_addr: str = "") -> tuple[int, dict, dict[str, str]]:
        """→ (http status, json body, extra response headers). Wraps the
        pipeline with the SLO registry's request classification
        (utils/slo.py): every front-door response — sheds and errors
        included — is one latency/error/shed event. Off means off: a
        disabled or absent registry costs one attribute read."""
        slo = getattr(self._cc, "slo", None)
        if slo is None or not slo.enabled:
            return self._handle_inner(method, path, query_string, headers,
                                      remote_addr)
        t0 = time.monotonic()
        status, body, out_headers = self._handle_inner(
            method, path, query_string, headers, remote_addr)
        slo.record_request(time.monotonic() - t0, status)
        return status, body, out_headers

    def _handle_inner(self, method: str, path: str, query_string: str = "",
                      headers: dict[str, str] | None = None,
                      remote_addr: str = "",
                      ) -> tuple[int, dict, dict[str, str]]:
        """→ (http status, json body, extra response headers)."""
        headers = headers or {}
        out_headers: dict[str, str] = {}
        try:
            endpoint = self._resolve(method, path)
            # The doas request param (ParameterUtils DO_AS_PARAM) is the
            # query-string form of trusted-proxy delegation: surface it to
            # the provider as the X-Do-As header when none is present.
            if "doas=" in query_string and "X-Do-As" not in headers:
                qs = urllib.parse.parse_qs(query_string)
                if qs.get("doas"):
                    headers = {**headers, "X-Do-As": qs["doas"][-1]}
            principal = self._security.authenticate(headers, remote_addr)
            self._security.authorize(principal, endpoint)
            query = urllib.parse.parse_qs(query_string, keep_blank_values=True)
            params = self._parse(endpoint, query)
            if self._reason_required and endpoint in _REASON_REQUIRED_ENDPOINTS \
                    and not params.get("reason"):
                raise ParameterParseError(
                    f"{endpoint.name} requires a reason parameter "
                    "(request.reason.required=true)")
            review_id = params.pop("review_id", None)
            if self._two_step and endpoint in REVIEWABLE_ENDPOINTS:
                if review_id is None:
                    info = self._purgatory.add(endpoint.name, query_string,
                                               principal.name)
                    return 200, responses.envelope(
                        {"reviewResult": info.to_dict(),
                         "message": "request parked for review"}), out_headers
                info = self._purgatory.submit(review_id, endpoint.name)
                # Execute EXACTLY what was reviewed: replay the parked query,
                # not whatever came with the resubmission (otherwise an
                # approved dry-run could smuggle in dryrun=false).
                query_string = info.query
                params = self._parse(endpoint, urllib.parse.parse_qs(
                    query_string, keep_blank_values=True))
                params.pop("review_id", None)
            # Fleet routing: ?cluster= selects the registered cluster's
            # facade (popped AFTER the purgatory replay so the reviewed
            # query's cluster wins over the resubmission's). A request
            # WITHOUT the parameter against a default facade that is
            # itself fleet-registered is that cluster's request too —
            # its solver work must share the device under the scheduler
            # and respect the pause state, not sneak around both.
            cluster_id = params.pop("cluster", None)
            if endpoint in (EndPoint.TRACE, EndPoint.SOLVER,
                            EndPoint.PROFILE):
                # Observability endpoints: cluster FILTERS recorded
                # traces/passes (it is a label on the record, not a
                # route) — valid without a fleet, and never subject to
                # the pause gate; PROFILE is process-wide by nature (one
                # device, one profiler gate). The request-class plugin
                # seam still applies (these bypass _dispatch, where other
                # endpoints' plugins are resolved).
                handler = self._request_plugin(endpoint)
                if handler is not None:
                    body = handler.handle(
                        self._cc, {**params, "cluster": cluster_id},
                        principal)
                elif endpoint is EndPoint.TRACE:
                    body = self._trace_handler(params, cluster_id)
                elif endpoint is EndPoint.SOLVER:
                    body = self._solver_handler(params, cluster_id)
                else:
                    body = self._profile_handler(params, out_headers)
            else:
                if cluster_id is None and self._fleet is not None:
                    cluster_id = self._fleet.cluster_id_of(self._cc)
                cc = self._route_cluster(endpoint, cluster_id)
                from ..utils.sensors import cluster_label
                with cluster_label(cluster_id):
                    body = self._dispatch(endpoint, params, principal,
                                          query_string, headers, out_headers,
                                          cc=cc, cluster_id=cluster_id)
            if params.get("get_response_schema"):
                body = {**body, "responseSchema": _schema_of(body)}
            if params.get("json") is False:
                # json=false plaintext rendering (ParameterUtils wantJSON;
                # the reference writes text tables).
                out_headers["Content-Type"] = "text/plain; charset=utf-8"
                body = {"__text__": _as_text(body)}
            return 200, body, out_headers
        except ParameterParseError as e:
            return 400, self._error(str(e)), out_headers
        except UnknownClusterError as e:
            return 404, self._error(
                f"unknown cluster {e.args[0]!r}"), out_headers
        except ClusterPausedError as e:
            return 409, self._error(str(e)), out_headers
        except AuthenticationError as e:
            out_headers["WWW-Authenticate"] = self._security.challenge()
            return 401, self._error(str(e)), out_headers
        except AuthorizationError as e:
            return 403, self._error(str(e)), out_headers
        except ApiError as e:
            return e.status, self._error(str(e)), out_headers
        except TooManyUserTasksError as e:
            return 429, self._error(str(e)), out_headers
        except (AdmissionShedError, TaskQueueFullError) as e:
            # Serving admission (round 20): overload sheds BEFORE a task
            # exists, with a Retry-After derived from the observed
            # per-class service rate.
            out_headers["Retry-After"] = str(max(1, int(e.retry_after_s + 0.5)))
            return 429, self._error(str(e)), out_headers
        except TaskOwnershipError as e:
            return 403, self._error(str(e)), out_headers
        except NotEnoughValidWindowsError as e:
            return 503, self._error(f"load model not ready: {e}"), out_headers
        except BreakerOpenError as e:
            # Resilience layer (round 9): an open circuit breaker fails
            # fast and tells the client exactly when to come back.
            out_headers["Retry-After"] = str(max(1, int(e.retry_after_s + 0.5)))
            return 503, self._error(str(e)), out_headers
        except (KeyError, ValueError) as e:
            return 400, self._error(str(e)), out_headers
        except Exception as e:
            LOG.exception("internal error handling %s %s", method, path)
            return 500, self._error(f"{type(e).__name__}: {e}"), out_headers

    def _trace_handler(self, p: dict, cluster_id: str | None) -> dict:
        """GET /trace: recent span trees (newest first) from the tracer's
        ring, as OTLP-shaped JSON. ``?cluster=`` / ``?operation=`` filter;
        ``?entries=`` bounds the response."""
        from ..utils.tracing import TRACER
        traces = TRACER.traces(cluster=cluster_id,
                               operation=p.get("operation"),
                               limit=p.get("entries", 50))
        return responses.envelope({
            "tracingEnabled": TRACER.enabled,
            "numTraces": len(traces),
            "spansClosed": TRACER.spans_closed,
            "traces": traces})

    def _solver_handler(self, p: dict, cluster_id: str | None) -> dict:
        """GET /solver: recent recorded optimization passes (newest first)
        from the flight recorder's ring — per-goal acceptance density,
        candidate-kill attribution, per-round violation trajectories,
        deficit-sizing decisions, and per-dispatch controller state.
        ``?cluster=`` / ``?goal=`` filter; ``?entries=`` bounds the
        response."""
        from ..utils.flight_recorder import FLIGHT
        passes = FLIGHT.passes(cluster=cluster_id, goal=p.get("goal"),
                               limit=p.get("entries", 20))
        return responses.envelope({
            "flightRecorderEnabled": FLIGHT.enabled,
            "ringRounds": FLIGHT.ring_rounds,
            "numPasses": len(passes),
            "passesClosed": FLIGHT.passes_closed,
            "dispatchesRecorded": FLIGHT.dispatches_recorded,
            "passes": passes})

    def _profile_handler(self, p: dict,
                         out_headers: dict[str, str]) -> dict:
        """GET /profile: on-demand device profiling (utils.profiling).
        ``?duration_s=`` captures a jax.profiler (Perfetto/TensorBoard)
        trace of whatever the live process executes during the window;
        ``?microbench=true`` runs the in-process op-class while_loop
        marginals instead. Both hold the single-flight profiler gate — a
        concurrent request gets 503 + Retry-After (the breaker response
        shape)."""
        from ..utils.profiling import PROFILER, ProfilerBusyError
        if not self._config.get_boolean("profiling.enabled"):
            raise ApiError(403, "profiling is disabled "
                                "(profiling.enabled=false)")
        try:
            if p.get("microbench"):
                result = PROFILER.microbench(
                    brokers=p.get("brokers", 1000),
                    partitions=p.get("partitions", 100_000),
                    iters=p.get("iters", 16))
                return responses.envelope(
                    {"profile": "microbench", **result})
            if "duration_s" not in p:
                raise ParameterParseError(
                    "PROFILE requires duration_s (seconds to capture) or "
                    "microbench=true")
            result = PROFILER.capture(
                p["duration_s"],
                trace_dir=self._config.get("profiling.trace.dir"),
                max_duration_s=self._config.get_double(
                    "profiling.max.duration.seconds"))
            return responses.envelope({"profile": "trace", **result})
        except ProfilerBusyError as e:
            out_headers["Retry-After"] = str(
                max(1, int(e.retry_after_s + 0.5)))
            raise ApiError(503, str(e)) from None

    def _route_cluster(self, endpoint: EndPoint,
                       cluster_id: str | None) -> CruiseControl:
        """?cluster= → the registered cluster's facade. No parameter =
        the default facade (single-cluster deployments unchanged); solver
        endpoints are refused for paused clusters."""
        if cluster_id is None:
            return self._cc
        if self._fleet is None:
            raise ParameterParseError(
                "cluster parameter given but this server is not running "
                "a fleet (no FleetRegistry configured)")
        return self._fleet.get(
            cluster_id, for_operation=endpoint in _SOLVER_ENDPOINTS)

    # Reference plugin-key spelling for each endpoint
    # (CruiseControlParametersConfig / CruiseControlRequestConfig).
    _PLUGIN_KEY = {EndPoint.STOP_PROPOSAL_EXECUTION: "stop.proposal"}

    def _plugin(self, endpoint: EndPoint, suffix: str):
        key = self._PLUGIN_KEY.get(endpoint,
                                   endpoint.name.lower().replace("_", "."))
        spec = self._config.get(f"{key}.{suffix}.class")
        if not spec:
            return None
        from ..config.abstract_config import resolve_class
        return resolve_class(spec) if isinstance(spec, str) else spec

    def _request_plugin(self, endpoint: EndPoint):
        """Resolved ``<endpoint>.request.class`` handler instance or None
        — the ONE plugin seam, shared by _dispatch and the TRACE branch
        (which bypasses _dispatch for its no-route cluster semantics)."""
        custom = self._plugin(endpoint, "request")
        if custom is None:
            return None
        return custom() if isinstance(custom, type) else custom

    def _parse(self, endpoint: EndPoint, query: dict) -> dict:
        """Config-swappable parameter parsing
        (CruiseControlParametersConfig reflection): a configured
        ``<endpoint>.parameters.class`` replaces the built-in schema."""
        custom = self._plugin(endpoint, "parameters")
        if custom is not None:
            return custom()(query) if isinstance(custom, type) else custom(query)
        return parse_parameters(endpoint, query)

    def _resolve(self, method: str, path: str) -> EndPoint:
        if not path.startswith(URL_PREFIX):
            raise ApiError(404, f"unknown path {path!r}; expected {URL_PREFIX}/*")
        endpoint = endpoint_for_path(path[len(URL_PREFIX):])
        if endpoint is None:
            raise ApiError(404, f"unknown endpoint {path!r}")
        if method != endpoint.method:
            raise ApiError(405, f"{endpoint.name} requires {endpoint.method}")
        return endpoint

    @staticmethod
    def _error(message: str) -> dict:
        return responses.envelope({"errorMessage": message})

    # -- handlers ----------------------------------------------------------
    def _dispatch(self, endpoint: EndPoint, params: dict, principal: Principal,
                  query_string: str, headers: dict[str, str],
                  out_headers: dict[str, str],
                  cc: CruiseControl | None = None,
                  cluster_id: str | None = None) -> dict:
        """Journey shell around the pipeline (serving/journey.py): open
        the ambient per-request record, run the real dispatch under its
        scope, close it with the outcome. Off means off: a disabled or
        absent journey log falls straight through to the inner
        pipeline."""
        journeys = getattr(cc or self._cc, "journeys", None)
        if journeys is None or not journeys.enabled:
            return self._dispatch_inner(endpoint, params, principal,
                                        query_string, headers, out_headers,
                                        cc=cc, cluster_id=cluster_id)
        from ..serving.journey import journey_scope
        jny = journeys.open(endpoint.name, cluster=cluster_id)
        with journey_scope(jny):
            try:
                body = self._dispatch_inner(endpoint, params, principal,
                                            query_string, headers,
                                            out_headers, cc=cc,
                                            cluster_id=cluster_id)
            except BaseException as e:
                jny.note(error=type(e).__name__)
                journeys.close(jny, status="error")
                raise
        journeys.close(jny, status=jny.attrs.get("outcome", "ok"))
        return body

    def _dispatch_inner(self, endpoint: EndPoint, params: dict,
                        principal: Principal, query_string: str,
                        headers: dict[str, str],
                        out_headers: dict[str, str],
                        cc: CruiseControl | None = None,
                        cluster_id: str | None = None) -> dict:
        cc = cc or self._cc
        p = params
        handler = self._request_plugin(endpoint)
        if handler is not None:
            # CruiseControlRequestConfig reflection: the configured request
            # class handles the endpoint end to end.
            return handler.handle(cc, p, principal)
        from ..serving.journey import current_journey
        jny = current_journey()
        if endpoint in _SYNC_ENDPOINTS:
            # One segment for inline endpoints: their wall IS response
            # assembly (STATE is the loadgen mix's heaviest read).
            with jny.seg("render"):
                return self._sync_handler(endpoint, p, principal, cc)
        # Async (model-building) endpoints run as user tasks. The
        # cluster label must be re-established INSIDE the work callable:
        # ContextVars do not cross into the user-task thread pool, so the
        # handle()-level context alone would label nothing async.
        # COMPARE_FUTURES validation runs ONCE here — a template typo
        # 400s before a user task is ever created — but the live-seed
        # MODEL BUILD is deferred into a lazy once-supplier shared by
        # the work closure AND the fleet-coalesced payload path:
        # _dispatch runs on the HTTP handler thread on EVERY request,
        # including each poll of an in-flight task, and must not pay a
        # cluster-model build the task dedup would discard.
        futures_req = futures_live = None
        if endpoint is EndPoint.COMPARE_FUTURES:
            futures_req = self._futures_request(cc, p)

            @lru_cache(maxsize=1)
            def futures_live():
                from ..futures.evaluator import live_seed_from
                return live_seed_from(cc)
        # Serving front door (round 20): on a NEW request (no User-Task-ID
        # presented), try the generation-keyed response cache, build the
        # coalescing key, and run admission — in that order, so a cache
        # hit or a coalesced join is never shed (neither consumes solver
        # capacity). Polls of existing tasks skip all three.
        resume_id = headers.get(USER_TASK_HEADER)
        store_key = coalesce_key = None
        if resume_id is None:
            with jny.seg("cache_lookup") as cache_seg:
                identity = self._response_identity(cc, cluster_id)
                if identity is not None:
                    generation, fingerprint = identity
                    pkey = canonical_params(endpoint.name, p,
                                            allowed=CACHEABLE_ENDPOINTS)
                    if pkey is not None:
                        store_key = (cluster_id, endpoint.name, pkey,
                                     generation, fingerprint)
                        cached = self._response_cache.get(store_key)
                        if cached is not None:
                            cache_seg.set(result="hit")
                            jny.note(outcome="cache_hit")
                            out_headers["X-Serving-Cache"] = "hit"
                            return cached
                    cache_seg.set(result="miss")
                    if self._coalesce_enabled:
                        ckey_params = canonical_params(
                            endpoint.name, p, allowed=COALESCIBLE_ENDPOINTS)
                        if ckey_params is not None:
                            coalesce_key = (cluster_id, endpoint.name,
                                            ckey_params, generation,
                                            fingerprint)
            if not self._tasks.has_inflight(coalesce_key):
                klass = task_class_of(endpoint.name)
                with jny.seg("admission", **{"class": klass.value}):
                    self._admission.admit(
                        klass, self._engine.queue_depth(klass),
                        self._engine.service_time_s(klass))
        work = self._async_work(endpoint, p, cc, futures_req=futures_req,
                                futures_live=futures_live)
        if cluster_id is not None:
            inner_work = work

            def work(inner=inner_work, cid=cluster_id):
                from ..utils.sensors import cluster_label
                with cluster_label(cid):
                    return inner()

        if jny.recording:
            # Same rewrap discipline as the cluster label just above:
            # ContextVars do not cross into the worker pools, so the
            # journey scope is re-established inside the work callable —
            # the model-build/solve stamps land on THIS request's record
            # whichever thread runs them.
            journey_inner = work

            def work(inner=journey_inner, j=jny):
                from ..serving.journey import journey_scope
                with journey_scope(j):
                    return inner()

        work = self._schedule_fleet_work(endpoint, cluster_id, work, cc, p,
                                         futures_req=futures_req,
                                         futures_live=futures_live)
        if store_key is not None:
            # Outermost wrapper (outside the fleet scheduling) so the
            # cached body is the FINAL envelope whichever path produced
            # it — solo work, scheduled job, or coalesced futures payload.
            caching_inner = work

            def work(inner=caching_inner, key=store_key, j=jny):
                body = inner()
                with j.seg("cache_store"):
                    self._response_cache.put(key, body)
                return body

        info = self._tasks.get_or_create_task(
            endpoint.name, query_string, work,
            task_id=resume_id, client=principal.name,
            coalesce_key=coalesce_key)
        out_headers[USER_TASK_HEADER] = info.task_id
        engine_task = getattr(info, "engine_task", None)
        # Follower ⟺ the user task rides another task's engine record
        # (user_tasks.get_or_create_task coalescing). A follower's wall
        # is spent WAITING on the leader's future — its own journey has
        # no work segments, so the wait itself is the named segment.
        follower = engine_task is not None \
            and engine_task.task_id != info.task_id
        if jny.recording and coalesce_key is not None \
                and engine_task is not None:
            jny.note(coalesce="follower" if follower else "leader")
        wait_t0 = jny.now() if follower else 0.0
        try:
            exc = info.future.exception(timeout=self._async_wait_s)
        except FuturesTimeoutError:
            if follower:
                jny.add("coalesce_wait", jny.now() - wait_t0)
            else:
                self._stamp_queue_wait(jny, engine_task)
            jny.note(outcome="in_progress")
            progress = info.progress.to_list() if info.progress else []
            return responses.envelope({
                "progress": [{"operation": endpoint.name, **p}
                             for p in progress],
                "message": f"operation still running; poll with "
                           f"{USER_TASK_HEADER} {info.task_id}"})
        if follower:
            jny.add("coalesce_wait", jny.now() - wait_t0)
        else:
            self._stamp_queue_wait(jny, engine_task)
        if exc is not None:
            if isinstance(exc, ApiError):
                raise exc
            if isinstance(exc, BreakerOpenError):
                raise exc  # handle() renders 503 + Retry-After
            if isinstance(exc, (ParameterParseError, ValueError, KeyError)):
                raise ApiError(400, str(exc))
            if isinstance(exc, NotEnoughValidWindowsError):
                raise ApiError(503, f"load model not ready: {exc}")
            raise ApiError(500, f"{type(exc).__name__}: {exc}")
        return info.future.result()

    @staticmethod
    def _stamp_queue_wait(jny, engine_task) -> None:
        """One ``queue_wait`` segment from the engine's lifecycle record
        (started − enqueued on the engine's monotonic seam) — stamped
        once the task left its class queue; a still-queued 202 has no
        wait to report yet (its poll will)."""
        if not jny.recording or engine_task is None \
                or engine_task.started_s <= 0.0:
            return
        jny.add("queue_wait",
                engine_task.started_s - engine_task.enqueued_s,
                **{"class": engine_task.klass.value})

    @staticmethod
    def _response_identity(cc: CruiseControl,
                           cluster_id: str | None) -> tuple | None:
        """(load-model generation, goal-chain fingerprint) — the serving
        cache/coalescing identity (round 20) — or None when the facade
        cannot provide one (a plugin facade without a monitor, say):
        without an identity nothing is cached or coalesced, never the
        other way around."""
        try:
            generation = int(cc.load_monitor.model_generation)
            from ..fleet.megabatch import solver_config_fingerprint
            fingerprint = solver_config_fingerprint(cc.config)
        except Exception:  # noqa: BLE001 — identity is best-effort
            return None
        return generation, fingerprint

    def _schedule_fleet_work(self, endpoint: EndPoint,
                             cluster_id: str | None, work,
                             cc: CruiseControl | None = None,
                             p: dict | None = None,
                             futures_req: dict | None = None,
                             futures_live=None):
        """Wrap a fleet-routed solver work callable so it runs as an
        ON_DEMAND FleetScheduler job: the user-task thread submits and
        blocks on the future (202-poll behavior unchanged), while the
        device itself is shared under the scheduler's priorities and
        starvation bound. Inline when no worker is draining (embedded or
        test schedulers) — blocking on a future nobody serves would hang
        the task forever. Model-build reads (_MODEL_BUILD_ENDPOINTS,
        round 20) schedule too — the handler layer no longer touches the
        device at all — but keep their monitor-class semantics (no pause
        gate, no breaker accounting as solver traffic)."""
        if cluster_id is None or self._fleet is None \
                or (endpoint not in _SOLVER_ENDPOINTS
                    and endpoint not in _MODEL_BUILD_ENDPOINTS):
            return work
        sched = self._fleet.scheduler
        if sched is None or not sched.running:
            return work
        if endpoint is EndPoint.PROPOSALS and cc is not None \
                and p is not None and not any(
                    p.get(k) for k in ("goals", "ignore_proposal_cache",
                                       "use_ready_default_goals",
                                       "fast_mode", "data_from")):
            # A default-chain PROPOSALS request with a fresh cache needs
            # NO solver time — answering inline keeps the pre-fleet
            # instant-cached-read behavior instead of parking a zero-work
            # request behind another cluster's multi-second solve.
            try:
                if cc._cached_proposals_fresh(
                        cc._load_monitor.model_generation):
                    return work
            except Exception:  # noqa: BLE001 — fall through to the queue
                pass
        from ..fleet.scheduler import JobKind

        batch_key = payload = None
        if endpoint is EndPoint.COMPARE_FUTURES and sched.coalescing \
                and p is not None:
            # Futures coalesce with precomputes (round 15): the request
            # submits under its cluster's precompute batch key plus a
            # runner payload, so a scheduler turn that picks either
            # drains both — the futures' decision solves and the paced
            # cache fills share one worker turn (and, when compatible,
            # one batched program). Solo fallback (``work``) covers
            # shutdown/inline execution unchanged.
            try:
                batch_key = \
                    self._precompute_key_for(cluster_id)
            except Exception:  # noqa: BLE001 — hint only; run solo
                batch_key = None
            if batch_key is not None and futures_req is not None:
                from ..futures.evaluator import FuturesPayload
                req = futures_req
                payload = FuturesPayload(
                    cluster_id, req["templates"], req["num_futures"],
                    req["seed"], req["ticks"],
                    include_present=req["include_present"],
                    wrap=responses.envelope,
                    # _dispatch's lazy once-supplier: the live seed
                    # builds at most ONE cluster model per request, on
                    # the worker thread, shared with the solo work path.
                    live_supplier=futures_live)
            if payload is None:
                # No payload to drain under the key: submit as a plain
                # solo job rather than a batch-keyed job with nothing
                # coalescible behind it.
                batch_key = None

        # Captured on the handler thread (the journey scope does not
        # cross into the engine worker that runs ``scheduled``): the
        # sched_wait segment is submit → the scheduler's device turn.
        from ..serving.journey import current_journey
        jny = current_journey()

        def scheduled():
            from concurrent.futures import CancelledError
            job = work
            if jny.recording:
                t0 = jny.now()

                def job(inner=work, j=jny, t0=t0):
                    j.add("sched_wait", j.now() - t0)
                    return inner()

            try:
                return sched.submit(cluster_id, JobKind.ON_DEMAND,
                                    job, batch_key=batch_key,
                                    payload=payload).result()
            except CancelledError:
                # Scheduler shut down before the job ran: a meaningful
                # 503 beats an opaque "CancelledError:" 500.
                raise ApiError(
                    503, "fleet scheduler shut down before the request "
                    "could run; retry once the fleet is back up")

        return scheduled

    def _precompute_key_for(self, cluster_id: str) -> tuple | None:
        """The cluster's precompute coalescing key (None when it has no
        recorded bucket yet)."""
        from ..fleet.megabatch import precompute_batch_key
        return precompute_batch_key(self._fleet.entry(cluster_id))

    def _futures_request(self, cc: CruiseControl, p: dict) -> dict:
        """Resolve + validate a COMPARE_FUTURES request against the
        cluster's config caps (shared by the direct work path and the
        fleet-coalesced payload path; template typos 400 up front)."""
        from ..futures.generator import FUTURE_TEMPLATES
        cfg = cc.config
        templates = [t for t in p.get("templates", ()) if t]
        live_templates = []
        for t in templates:
            if t not in FUTURE_TEMPLATES:
                raise ParameterParseError(
                    f"unknown futures template {t!r}; expected one of "
                    f"{', '.join(sorted(FUTURE_TEMPLATES))}")
            if FUTURE_TEMPLATES[t].requires_live:
                live_templates.append(t)
        if live_templates:
            # Validated ONCE outside the template loop. Only CHEAP
            # checks run here — this executes on the HTTP handler
            # thread for every request, including task polls; the
            # cluster-model build itself is deferred to _dispatch's
            # lazy once-supplier on the worker thread.
            t = live_templates[0]
            if not cfg.get_boolean("futures.live.seed.enabled"):
                raise ParameterParseError(
                    f"template {t!r} requires the live-cluster seam "
                    "(futures.live.seed.enabled=true)")
            if not cc.load_monitor.window_times():
                # Eager 400 with the REAL cause for the common case
                # (no stable windows yet — probe is a list read, no
                # model build); a build failure past this probe still
                # surfaces as the worker path's 400/503.
                raise ParameterParseError(
                    f"template {t!r} requires the live cluster model, "
                    "which is not ready yet (monitor still warming)")
        n = p.get("num_futures", cfg.get_int("futures.default.count"))
        n = max(1, min(int(n), cfg.get_int("futures.max.count")))
        ticks = p.get("ticks", cfg.get_int("futures.default.ticks"))
        ticks = max(1, min(int(ticks), cfg.get_int("futures.max.ticks")))
        return {"templates": templates or None, "num_futures": n,
                "seed": p.get("seed", 0), "ticks": ticks,
                "include_present": p.get("include_present", True)}

    def _sync_handler(self, endpoint: EndPoint, p: dict,
                      principal: Principal,
                      cc: CruiseControl | None = None) -> dict:
        cc = cc or self._cc
        if endpoint is EndPoint.FLEET:
            if self._fleet is None:
                return responses.envelope(
                    {"numClusters": 0, "clusters": {},
                     "message": "fleet mode not enabled"})
            return responses.envelope(self._fleet.state())
        if endpoint is EndPoint.HEALS:
            # GET /heals: correlated anomaly-lifecycle chains from the
            # routed facade's heal ledger (per-facade journals — a
            # fleet's ?cluster= routes, a twin's ledger stays its own).
            ledger = cc.heal_ledger
            chains = ledger.chains(anomaly_type=p.get("anomaly_type"),
                                   limit=p.get("entries", 20))
            return responses.envelope({
                "healLedgerEnabled": ledger.enabled,
                "numChains": len(chains),
                "chainsOpened": ledger.chains_opened,
                "chainsResolved": ledger.chains_resolved,
                "healsOpen": ledger.open_count(),
                "meanTimeToStartFixMs": ledger.mean_time_to_start_fix_ms(),
                "chains": chains})
        if endpoint is EndPoint.FORECAST:
            # GET /forecast: the routed facade's forecast engine —
            # per-broker current-vs-projected loads, horizon geometry,
            # and the predictive detector's hit-rate counters.
            refresh = bool(p.get("refresh", False))

            def _forecast_work():
                return responses.envelope(
                    cc.forecast_state(refresh=refresh))

            if refresh and self._fleet is not None:
                # refresh=true runs the jitted fit — device work, maybe
                # a first-shape compile. In fleet mode it shares the
                # device under the scheduler like every other
                # solver-time request instead of contending from the
                # HTTP handler thread mid-solve (the _SOLVER_ENDPOINTS
                # discipline; the cached read stays inline).
                sched = self._fleet.scheduler
                cid = self._fleet.cluster_id_of(cc)
                if sched is not None and sched.running \
                        and cid is not None:
                    from concurrent.futures import CancelledError

                    from ..fleet.scheduler import JobKind
                    try:
                        return sched.submit(
                            cid, JobKind.ON_DEMAND,
                            _forecast_work).result()
                    except CancelledError:
                        raise ApiError(
                            503, "fleet scheduler shut down before the "
                            "forecast refresh could run; retry once the "
                            "fleet is back up")
            return _forecast_work()
        if endpoint is EndPoint.JOURNEYS:
            # GET /journeys: the routed facade's completed-request ring
            # (serving/journey.py) — per-request latency attribution,
            # newest first. ``?endpoint=`` / ``?entries=`` filter.
            journeys = getattr(cc, "journeys", None)
            if journeys is None:
                return responses.envelope({
                    "journeysEnabled": False, "numJourneys": 0,
                    "journeys": []})
            entries = journeys.entries(endpoint=p.get("endpoint"),
                                       limit=p.get("entries", 50))
            return responses.envelope({
                **journeys.stats(),
                "numJourneys": len(entries),
                "journeys": entries})
        if endpoint is EndPoint.SLO:
            # GET /slo: the routed facade's objective registry
            # (utils/slo.py) — per-window burn rates, remaining budget,
            # burning verdicts — plus the burn detector's lifecycle.
            slo = getattr(cc, "slo", None)
            if slo is None:
                return responses.envelope(
                    {"sloEnabled": False, "objectives": {}})
            body = slo.state()
            objective = p.get("objective")
            if objective:
                body["objectives"] = {
                    name: entry
                    for name, entry in body["objectives"].items()
                    if name == objective}
            detector = getattr(cc, "slo_burn_detector", None)
            if detector is not None:
                body["burnDetector"] = detector.state()
            return responses.envelope(body)
        if endpoint is EndPoint.REDTEAM:
            # GET /redteam: the mined worst-case regression frontier
            # (redteam/, round 22) — per-entry SLO margins, verdict
            # strings, replay recipes, the forecaster blind-spot
            # report, and the canonical library's margin bar. Serves
            # the COMMITTED frontier file; mining never runs on the
            # request path.
            if not cc.config.get_boolean("redteam.enabled"):
                raise ParameterParseError(
                    "redteam.enabled=false: the mined frontier surface "
                    "is disabled on this cluster")
            from ..redteam.frontier import load_frontier
            path = cc.config.get_string("redteam.frontier.path")
            frontier = load_frontier(path)
            if frontier is None:
                return responses.envelope({
                    "redteamEnabled": True, "frontierPath": path,
                    "frontierFound": False, "numEntries": 0,
                    "frontier": [],
                    "hint": "no frontier file; run the miner — "
                            "python bench.py --redteam"})
            entries = list(frontier.get("frontier") or [])
            limit = p.get("entries")
            if limit is not None:
                entries = entries[:max(0, int(limit))]
            if not p.get("blind_spots", True):
                entries = [{k: v for k, v in e.items()
                            if k != "blindSpot"} for e in entries]
            return responses.envelope({
                "redteamEnabled": True, "frontierPath": path,
                "frontierFound": True,
                "sweepSeed": frontier.get("sweepSeed"),
                "generationsRun": frontier.get("generationsRun"),
                "evals": frontier.get("evals"),
                "replays": frontier.get("replays"),
                "partial": frontier.get("partial"),
                "partialReason": frontier.get("partialReason"),
                "library": frontier.get("library"),
                "foundBelowLibrary": frontier.get("foundBelowLibrary"),
                "blindSpotCount": frontier.get("blindSpotCount"),
                "numEntries": len(entries),
                "frontier": entries})
        if endpoint is EndPoint.STATE:
            key = None
            if self._response_cache.cache_state:
                # Opt-in only (serving.cache.state.enabled): /state is
                # NOT generation-pure — executor progress and anomaly
                # state move without a model-generation bump, so this
                # trades freshness for poll throughput, explicitly.
                cid = self._fleet.cluster_id_of(cc) \
                    if self._fleet is not None else None
                identity = self._response_identity(cc, cid)
                if identity is not None:
                    key = (cid, "STATE",
                           tuple(sorted((k, repr(v))
                                        for k, v in p.items())),
                           *identity)
                    cached = self._response_cache.get(key)
                    if cached is not None:
                        return cached
            body = responses.envelope(cc.state(
                p.get("substates", ()),
                super_verbose=p.get("super_verbose", False)))
            self._response_cache.put(key, body)
            return body
        if endpoint is EndPoint.KAFKA_CLUSTER_STATE:
            return responses.kafka_cluster_state(cc._admin, p.get("topic", ""))
        if endpoint is EndPoint.USER_TASKS:
            tasks = self._tasks.all_tasks()
            ids = set(p.get("user_task_ids", ()))
            if ids:
                tasks = [t for t in tasks if t.task_id in ids]
            eps = set(p.get("endpoints", ()))
            if eps:
                tasks = [t for t in tasks if t.endpoint in eps]
            clients = set(p.get("client_ids", ()))
            if clients:
                tasks = [t for t in tasks if t.client in clients]
            # types filter: task state names, e.g. Active / Completed /
            # CompletedWithError (UserTaskManager.TaskState).
            states = {s.lower() for s in p.get("types", ())}
            if states:
                tasks = [t for t in tasks
                         if t.to_dict()["Status"].lower() in states]
            tasks = tasks[: p.get("entries", len(tasks))]
            if p.get("fetch_completed_task"):
                # Return the stored final response of each completed task
                # instead of the summary row (FETCH_COMPLETED_TASK_PARAM).
                out = []
                for t in tasks:
                    body = None
                    if t.future is not None and t.future.done() \
                            and not t.future.exception():
                        body = t.future.result()
                    out.append({**t.to_dict(), "originalResponse": body})
                return responses.envelope({"userTasks": out})
            return responses.envelope(
                {"userTasks": [t.to_dict() for t in tasks]})
        if endpoint is EndPoint.REVIEW_BOARD:
            board = self._purgatory.review_board()
            ids = set(p.get("review_ids", ()))
            if ids:
                board = [r for r in board if r["Id"] in ids]
            return responses.envelope({"requestInfo": board})
        if endpoint is EndPoint.PERMISSIONS:
            return responses.envelope(
                {"user": principal.name, "role": principal.role.name})
        if endpoint is EndPoint.REVIEW:
            out = []
            for rid in p.get("approve", ()):
                out.append(self._purgatory.approve(rid, p.get("reason", "")).to_dict())
            for rid in p.get("discard", ()):
                out.append(self._purgatory.discard(rid, p.get("reason", "")).to_dict())
            return responses.envelope({"requestInfo": out})
        if endpoint is EndPoint.PAUSE_SAMPLING:
            cc.pause_metric_sampling(p.get("reason", ""))
            return responses.envelope({"message": "metric sampling paused"})
        if endpoint is EndPoint.RESUME_SAMPLING:
            cc.resume_metric_sampling(p.get("reason", ""))
            return responses.envelope({"message": "metric sampling resumed"})
        if endpoint is EndPoint.STOP_PROPOSAL_EXECUTION:
            cc.stop_proposal_execution(
                force_stop=p.get("force_stop", False),
                stop_external_agent=p.get("stop_external_agent", False))
            return responses.envelope({"message": "execution stop requested"})
        if endpoint is EndPoint.BOOTSTRAP:
            if not p.get("developer_mode", False):
                # BootstrapRequest.java:29: without developer_mode=true the
                # endpoint does nothing but say so.
                return responses.envelope({
                    "message": "This endpoint is used only for development "
                               "purposes in developer_mode=true."})
            start = p.get("start")
            if start is None:
                raise ParameterParseError("bootstrap requires start")
            cc.load_monitor.bootstrap(start, p.get("end", int(time.time() * 1000)),
                                      p.get("clearmetrics", True))
            return responses.envelope({"message": "bootstrap started"})
        if endpoint is EndPoint.TRAIN:
            start = p.get("start", 0)
            end = p.get("end", int(time.time() * 1000))
            return responses.envelope(
                {"message": "training pass completed",
                 **cc.load_monitor.train(start, end)})
        if endpoint is EndPoint.RIGHTSIZE:
            res = cc.rightsize(p.get("numbrokerstoadd", 0),
                               p.get("partition_count", 0), p.get("topic"))
            return responses.optimization_result(res)
        if endpoint is EndPoint.ADMIN:
            return self._admin_handler(p, cc)
        raise ApiError(500, f"no sync handler for {endpoint.name}")

    def _admin_handler(self, p: dict,
                       cc: CruiseControl | None = None) -> dict:
        from ..detector.anomaly import AnomalyType
        from ..executor.concurrency import ExecutionConcurrencyManager
        cc = cc or self._cc
        # Validate EVERY name-typed argument before applying ANY mutation:
        # a typo anywhere must 400 the whole request, not leave the earlier
        # toggles silently applied under an error response.
        healing_toggles = [(n, False) for n in
                           p.get("disable_self_healing_for", ())] + \
                          [(n, True) for n in
                           p.get("enable_self_healing_for", ())]
        for name, _e in healing_toggles:
            if name.upper() not in AnomalyType.__members__:
                raise ParameterParseError(
                    f"unknown anomaly type {name!r}; expected one of "
                    f"{', '.join(AnomalyType.__members__)}")
        adjuster_toggles = [(n, False) for n in
                            p.get("disable_concurrency_adjuster_for", ())] + \
                           [(n, True) for n in
                            p.get("enable_concurrency_adjuster_for", ())]
        for name, _e in adjuster_toggles:
            if name.upper() not in ExecutionConcurrencyManager.ADJUSTER_TYPES:
                raise ParameterParseError(
                    f"unknown concurrency type {name!r}; expected one of "
                    f"{', '.join(ExecutionConcurrencyManager.ADJUSTER_TYPES)}")
        changed: dict[str, Any] = {}
        for name, enabled in healing_toggles:
            old = cc.anomaly_detector.set_self_healing_for(
                AnomalyType[name.upper()], enabled)
            changed.setdefault("selfHealingEnabledBefore" if enabled
                               else "selfHealingDisabledBefore", {})[name] = old
        conc = {k: p[k] for k in
                ("concurrent_partition_movements_per_broker",
                 "concurrent_intra_broker_partition_movements",
                 "concurrent_leader_movements") if k in p}
        if conc:
            changed["concurrency"] = cc.set_concurrency(
                inter_broker_per_broker=conc.get(
                    "concurrent_partition_movements_per_broker"),
                intra_broker_per_broker=conc.get(
                    "concurrent_intra_broker_partition_movements"),
                leadership_cluster=conc.get("concurrent_leader_movements"))
        for name, enabled in adjuster_toggles:
            old = cc.executor.set_concurrency_adjuster_for(name, enabled)
            changed.setdefault("concurrencyAdjusterEnabledBefore", {})[name] = old
        if "min_isr_based_concurrency_adjustment" in p:
            changed["minIsrBasedAdjustmentBefore"] = \
                cc.executor.set_min_isr_based_adjustment(
                    p["min_isr_based_concurrency_adjustment"])
        dropped_removed = p.get("drop_recently_removed_brokers", ())
        if dropped_removed:
            cc.drop_recently_removed_brokers(dropped_removed)
            changed["droppedRecentlyRemoved"] = sorted(dropped_removed)
        dropped_demoted = p.get("drop_recently_demoted_brokers", ())
        if dropped_demoted:
            cc.drop_recently_demoted_brokers(dropped_demoted)
            changed["droppedRecentlyDemoted"] = sorted(dropped_demoted)
        return responses.envelope(changed or {"message": "no admin action given"})

    def _what_if_handler(self, cc: CruiseControl, p: dict) -> dict:
        """PROPOSALS ``?what_if=<scenario>``: replay a canonical scenario
        on the digital twin (testing/simulator.py) and return the scored
        trajectory — the time-dimension extension of the proposals dry
        run. ``what_if=random:<template>:<seed>`` replays a
        generator-sampled scenario (futures/generator.py) instead —
        every sampled row of a COMPARE_FUTURES answer is replayable this
        way — and ``what_if=mined:<frontier-id>`` replays a mined
        red-team frontier entry (redteam/, round 22) from its recorded
        recipe. The simulator wires its OWN backend/executor, so this
        cluster's executor state is never touched; tick counts are capped
        by ``scenario.what.if.max.ticks`` since a replay is real solver
        work."""
        from ..testing.simulator import CANONICAL_SCENARIOS, run_scenario
        name = p["what_if"]
        default_seed = 0
        if name.startswith("mined:"):
            # Mined frontier replay (redteam/, round 22): the entry's
            # recipe rebuilds the exact perturbed spec; the default sim
            # seed is the entry's recorded replaySeed so a bare
            # what_if=mined:<id> reproduces the mined score byte-for-
            # byte (what_if_seed still overrides for exploration).
            from ..redteam.frontier import entry_spec, load_frontier
            if not cc.config.get_boolean("redteam.enabled"):
                raise ParameterParseError(
                    "redteam.enabled=false: mined frontier replays are "
                    "disabled on this cluster")
            path = cc.config.get_string("redteam.frontier.path")
            frontier = load_frontier(path)
            entries = (frontier or {}).get("frontier") or []
            if not entries:
                raise ParameterParseError(
                    f"mined frontier is empty (no frontier file at "
                    f"{path!r}); run the miner — python bench.py "
                    "--redteam — to populate it")
            by_id = {e["id"]: e for e in entries}
            wanted = name[len("mined:"):]
            entry = by_id.get(wanted)
            if entry is None:
                raise ParameterParseError(
                    f"unknown mined frontier id {wanted!r}; known ids: "
                    f"{', '.join(sorted(by_id))}")
            spec = entry_spec(entry)
            default_seed = int(entry.get("replaySeed", 0))
        elif name.startswith("random:"):
            from ..futures.generator import FUTURE_TEMPLATES, sample_scenario
            parts = name.split(":")
            template = parts[1] if len(parts) >= 2 else ""
            if len(parts) not in (2, 3) or template not in FUTURE_TEMPLATES:
                raise ParameterParseError(
                    f"unknown futures template {template!r} in "
                    f"what_if={name!r}; expected "
                    "random:<template>[:<seed>] with a template from: "
                    f"{', '.join(sorted(FUTURE_TEMPLATES))}")
            if FUTURE_TEMPLATES[template].requires_live:
                # A requires_live template's standalone spec is a bare
                # renamed BASE_SPEC (its content lives in the
                # evaluator's live seam): replaying it here would serve
                # a meaningless synthetic trajectory under the
                # template's name. COMPARE_FUTURES is the surface that
                # answers it — same 400 discipline as there.
                raise ParameterParseError(
                    f"template {template!r} requires the live-cluster "
                    "seam and has no standalone replay; request it via "
                    "COMPARE_FUTURES (templates parameter) instead")
            try:
                gen_seed = int(parts[2]) if len(parts) == 3 else 0
            except ValueError:
                raise ParameterParseError(
                    f"bad generator seed in what_if={name!r}: "
                    f"{parts[2]!r} is not an integer")
            spec = sample_scenario(template, gen_seed)
        else:
            if name not in CANONICAL_SCENARIOS:
                raise ParameterParseError(
                    f"unknown what_if scenario {name!r}; expected one of "
                    f"{', '.join(sorted(CANONICAL_SCENARIOS))} or "
                    "random:<template>:<seed>")
            spec = CANONICAL_SCENARIOS[name]
        cap = cc.config.get_int("scenario.what.if.max.ticks")
        ticks = p.get("what_if_ticks")
        ticks = min(spec.ticks, cap) if ticks is None \
            else max(1, min(int(ticks), cap))
        seed = p.get("what_if_seed", default_seed)
        result = run_scenario(spec, seed=seed, ticks=ticks)
        return responses.envelope({
            "operation": "what_if", "dryrun": True, "executed": False,
            "scenario": spec.name, "seed": seed, "ticks": ticks,
            "score": result.score.as_dict(),
            "finalAssignmentDigest": result.assignment_digest,
            "events": result.events})

    def _sanity_check_hard_goals(self, endpoint: EndPoint, p: dict,
                                 cc: CruiseControl | None = None) -> None:
        """Explicitly requested goals must include every configured hard
        goal unless skip_hard_goal_check=true
        (KafkaCruiseControlUtils.sanityCheckGoals:426-437; a sole
        PreferredLeaderElectionGoal is exempt). Mode-derived chains
        (kafka_assigner, rebalance_disk) are not user goal lists and skip
        the check."""
        explicit = p.get("goals")
        if endpoint not in _HARD_GOAL_CHECKED_ENDPOINTS or not explicit \
                or p.get("skip_hard_goal_check", False):
            return
        short = [g.rsplit(".", 1)[-1] for g in explicit]
        if short == ["PreferredLeaderElectionGoal"]:
            return
        hard = {g.rsplit(".", 1)[-1]
                for g in (cc or self._cc)._config.get_list("hard.goals")}
        missing = sorted(hard - set(short))
        if missing:
            raise ParameterParseError(
                f"Missing hard goals {missing} in the provided goals: "
                f"{short}. Add skip_hard_goal_check=true parameter to "
                "ignore this sanity check.")

    def _async_work(self, endpoint: EndPoint, p: dict,
                    cc: CruiseControl | None = None,
                    futures_req: dict | None = None,
                    futures_live=None):
        cc = cc or self._cc
        dryrun = p.get("dryrun", True)
        goals = _resolve_goal_names(p)
        self._sanity_check_hard_goals(endpoint, p, cc)
        use_ready = p.get("use_ready_default_goals", False)
        fast_mode = p.get("fast_mode", False)
        reason = p.get("reason", "")
        verbose = p.get("verbose", False)

        def exec_scope():
            """Per-request execution overrides (ParameterUtils): scoped to
            the operation via the facade's context manager, so a dry run,
            an empty result, or an exception never leaks them into a later
            execution."""
            import contextlib
            if dryrun:
                return contextlib.nullcontext()
            conc = {}
            if "concurrent_partition_movements_per_broker" in p:
                conc["inter_broker_per_broker"] = \
                    p["concurrent_partition_movements_per_broker"]
            if "concurrent_intra_broker_partition_movements" in p:
                conc["intra_broker_per_broker"] = \
                    p["concurrent_intra_broker_partition_movements"]
            if "concurrent_leader_movements" in p:
                conc["leadership_cluster"] = p["concurrent_leader_movements"]
            if "max_partition_movements_in_cluster" in p:
                conc["cluster_inter_broker"] = \
                    p["max_partition_movements_in_cluster"]
            if "broker_concurrent_leader_movements" in p:
                conc["leadership_per_broker"] = \
                    p["broker_concurrent_leader_movements"]
            strategies = p.get("replica_movement_strategies", ())
            extras = {}
            if "execution_progress_check_interval_ms" in p:
                extras["progress_check_interval_s"] = \
                    p["execution_progress_check_interval_ms"] / 1000.0
            if "replication_throttle" in p:
                extras["replication_throttle"] = p["replication_throttle"]
            if p.get("stop_ongoing_execution"):
                extras["stop_ongoing_execution"] = True
            # throttle_added_broker / throttle_removed_broker = false:
            # leave the brokers being added/removed unthrottled
            # (AddedOrRemovedBrokerParameters.java).
            if (endpoint is EndPoint.ADD_BROKER
                    and not p.get("throttle_added_broker", True)) \
                    or (endpoint is EndPoint.REMOVE_BROKER
                        and not p.get("throttle_removed_broker", True)):
                extras["throttle_excluded_brokers"] = \
                    tuple(p.get("brokerid", ()))
            if conc or strategies or extras:
                return cc.execution_overrides(strategies, conc, extras)
            return contextlib.nullcontext()

        def load():
            if p.get("capacity_only"):
                # capacity_only=true answers from the capacity config alone
                # — no metric completeness needed (ParameterUtils
                # capacityOnly, excludes the time-range params).
                return responses.broker_capacities(
                    cc._admin, cc.load_monitor.capacity_resolver)
            state, meta = cc.load_monitor.cluster_model(
                allow_capacity_estimation=p.get("allow_capacity_estimation",
                                                True),
                start_ms=p.get("start", -1),
                end_ms=p.get("time", p.get("end", -1)))
            disk_info = None
            if p.get("populate_disk_info"):
                disk_info = (getattr(cc._admin, "describe_logdirs",
                                     lambda: {})(),
                             cc.load_monitor.capacity_resolver)
            return responses.broker_stats(state, meta, disk_info=disk_info)

        def partition_load():
            # max_load/avg_load pick the window reduction at model build
            # (Load.expectedUtilizationFor wantMaxLoad).
            reduction = "max" if p.get("max_load") \
                else ("avg" if p.get("avg_load") else "default")
            state, meta = cc.load_monitor.cluster_model(
                allow_capacity_estimation=p.get("allow_capacity_estimation",
                                                True),
                start_ms=p.get("start", -1), end_ms=p.get("end", -1),
                min_valid_partition_ratio=p.get("min_valid_partition_ratio"),
                reduction=reduction)
            return responses.partition_load(
                state, meta, p.get("resource", "DISK"), p.get("entries"),
                topic_rx=p.get("topic"), partition_range=p.get("partition"),
                brokerids=p.get("brokerid", ()))

        data_from = p.get("data_from")
        allow_cap = p.get("allow_capacity_estimation", True)

        # futures_req arrives pre-validated from _dispatch; the live
        # seed builds here on the WORKER thread via _dispatch's lazy
        # once-supplier (shared with the fleet payload path).

        def compare_futures():
            from ..futures.evaluator import compare_futures as _compare
            body = _compare(
                optimizer=cc.optimizer,
                width=cc.config.get_int("futures.batch.width"),
                live=futures_live() if futures_live is not None else None,
                **futures_req)
            return responses.envelope(body)

        def proposals():
            if p.get("what_if"):
                return self._what_if_handler(cc, p)
            return responses.optimization_result(cc.proposals(
                goals, p.get("ignore_proposal_cache", False),
                use_ready_default_goals=use_ready, fast_mode=fast_mode,
                data_from=data_from, allow_capacity_estimation=allow_cap),
                verbose)

        def rebalance():
            with exec_scope():
                if p.get("rebalance_disk"):
                    return responses.optimization_result(
                        cc.rebalance_disk(dryrun, reason=reason), verbose)
                return responses.optimization_result(cc.rebalance(
                    goals, dryrun,
                    excluded_topics=p.get("excluded_topics", ()),
                    destination_broker_ids=p.get("destination_broker_ids", ()),
                    exclude_recently_demoted_brokers=p.get(
                        "exclude_recently_demoted_brokers", False),
                    exclude_recently_removed_brokers=p.get(
                        "exclude_recently_removed_brokers", False),
                    use_ready_default_goals=use_ready, fast_mode=fast_mode,
                    data_from=data_from, allow_capacity_estimation=allow_cap,
                    reason=reason), verbose)

        def add_broker():
            with exec_scope():
                return responses.optimization_result(cc.add_brokers(
                    list(p.get("brokerid", ())), dryrun, goals,
                    use_ready_default_goals=use_ready, fast_mode=fast_mode,
                    data_from=data_from, allow_capacity_estimation=allow_cap,
                    reason=reason), verbose)

        def remove_broker():
            with exec_scope():
                return responses.optimization_result(cc.remove_brokers(
                    list(p.get("brokerid", ())), dryrun, goals,
                    use_ready_default_goals=use_ready, fast_mode=fast_mode,
                    data_from=data_from, allow_capacity_estimation=allow_cap,
                    reason=reason), verbose)

        def demote_broker():
            with exec_scope():
                return responses.optimization_result(cc.demote_brokers(
                    list(p.get("brokerid", ())), dryrun,
                    skip_urp_demotion=p.get("skip_urp_demotion", True),
                    exclude_follower_demotion=p.get(
                        "exclude_follower_demotion", False),
                    reason=reason), verbose)

        def fix_offline_replicas():
            with exec_scope():
                return responses.optimization_result(cc.fix_offline_replicas(
                    dryrun, goals, use_ready_default_goals=use_ready,
                    fast_mode=fast_mode, data_from=data_from,
                    allow_capacity_estimation=allow_cap,
                    reason=reason), verbose)

        def topic_configuration():
            topic = p.get("topic")
            rf = p.get("replication_factor")
            if not topic or rf is None:
                raise ParameterParseError(
                    "topic_configuration requires topic and replication_factor")
            with exec_scope():
                return responses.optimization_result(
                    cc.update_topic_replication_factor(
                        [topic], rf, dryrun, reason=reason,
                        skip_rack_awareness_check=p.get(
                            "skip_rack_awareness_check", False)), verbose)

        def remove_disks():
            mapping = p.get("brokerid_and_logdirs")
            if not mapping:
                raise ParameterParseError(
                    "remove_disks requires brokerid_and_logdirs")
            with exec_scope():
                return responses.optimization_result(
                    cc.remove_disks(mapping, dryrun, reason=reason), verbose)

        table = {EndPoint.LOAD: load, EndPoint.PARTITION_LOAD: partition_load,
                 EndPoint.PROPOSALS: proposals, EndPoint.REBALANCE: rebalance,
                 EndPoint.ADD_BROKER: add_broker,
                 EndPoint.REMOVE_BROKER: remove_broker,
                 EndPoint.DEMOTE_BROKER: demote_broker,
                 EndPoint.FIX_OFFLINE_REPLICAS: fix_offline_replicas,
                 EndPoint.TOPIC_CONFIGURATION: topic_configuration,
                 EndPoint.REMOVE_DISKS: remove_disks,
                 EndPoint.COMPARE_FUTURES: compare_futures}
        return table[endpoint]


def _schema_of(value: Any) -> Any:
    """Response-shape description for get_response_schema=true (the
    reference serves JSON schemas generated from its response classes)."""
    if isinstance(value, dict):
        return {k: _schema_of(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_schema_of(value[0])] if value else []
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, (int, float)):
        return "number"
    return "string"


def _as_text(value: Any, indent: int = 0) -> str:
    """Plaintext rendering for json=false (key: value lines, nested
    structures indented — the text-table role of the reference's
    plaintext writers)."""
    pad = " " * indent
    if isinstance(value, dict):
        lines = []
        for k, v in value.items():
            if isinstance(v, (dict, list)):
                lines.append(f"{pad}{k}:")
                lines.append(_as_text(v, indent + 2))
            else:
                lines.append(f"{pad}{k}: {v}")
        return "\n".join(lines)
    if isinstance(value, list):
        return "\n".join(_as_text(v, indent) if isinstance(v, (dict, list))
                         else f"{pad}- {v}" for v in value)
    return f"{pad}{value}"


class _Handler(BaseHTTPRequestHandler):
    api: CruiseControlApi  # set by make_server

    _UI_TYPES = {".html": "text/html; charset=utf-8",
                 ".js": "text/javascript", ".css": "text/css",
                 ".json": "application/json", ".svg": "image/svg+xml",
                 ".png": "image/png", ".ico": "image/x-icon",
                 ".woff2": "font/woff2", ".map": "application/json"}

    def _send(self, method: str, t0: float, status: int, data: bytes,
              content_type: str, extra: dict[str, str] | None = None) -> None:
        """The single response writer: every surface (API, scrapes, UI,
        errors) goes through here so HSTS, CORS, and the access log apply
        uniformly."""
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        cfg = self.api._config
        if cfg.get_boolean("webserver.ssl.enable") and \
                cfg.get_boolean("webserver.ssl.sts.enabled"):
            # webserver.ssl.sts.* (WebServerConfig HSTS surface).
            sts = f"max-age={cfg.get_long('webserver.ssl.sts.max.age')}"
            if cfg.get_boolean("webserver.ssl.sts.include.subdomains"):
                sts += "; includeSubDomains"
            self.send_header("Strict-Transport-Security", sts)
        if cfg.get_boolean("webserver.http.cors.enabled"):
            # webserver.http.cors.* (WebServerConfig CORS surface).
            self.send_header("Access-Control-Allow-Origin",
                             cfg.get("webserver.http.cors.origin"))
            self.send_header("Access-Control-Allow-Methods",
                             cfg.get("webserver.http.cors.allowmethods"))
            self.send_header("Access-Control-Expose-Headers",
                             cfg.get("webserver.http.cors.exposeheaders"))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)
        if cfg.get_boolean("webserver.accesslog.enabled"):
            LOG.info('access %s "%s %s" %d %dB %.1fms',
                     self.client_address[0], method, self.path, status,
                     len(data), 1000 * (time.time() - t0))

    def _ui_lookup(self, path: str) -> tuple[bytes, str] | None:
        """(content, content-type) for the static Web-UI surface
        (KafkaCruiseControlServletApp serves the webroot at
        webserver.ui.diskpath): the configured directory when set, else the
        bundled single-file dashboard. Assets only — all DATA flows through
        the API endpoints."""
        if path.startswith(URL_PREFIX):
            return None
        cfg = self.api._config
        base = cfg.get("webserver.ui.diskpath")
        bundled = not base
        if bundled:
            import cruise_control_tpu.webui as webui
            base = os.path.dirname(webui.__file__)
        rel = path.lstrip("/") or "index.html"
        full = os.path.realpath(os.path.join(base, rel))
        # Traversal guard: the resolved file must stay inside the UI dir.
        if not full.startswith(os.path.realpath(base) + os.sep):
            return None
        ext = os.path.splitext(full)[1].lower()
        if bundled and ext not in self._UI_TYPES:
            # The bundled dir is a Python package: only recognized asset
            # types are public (never __init__.py / __pycache__ bytecode).
            return None
        if not os.path.isfile(full):
            return None
        with open(full, "rb") as f:
            return f.read(), self._UI_TYPES.get(ext,
                                                "application/octet-stream")

    def _serve(self, method: str) -> None:
        t0 = time.time()
        cfg = self.api._config
        header_bytes = sum(len(k) + len(v) for k, v in self.headers.items())
        if header_bytes > cfg.get_int("webserver.http.header.size"):
            self._send(method, t0, 431, json.dumps(
                {"errorMessage": "request headers too large"}).encode(),
                "application/json")
            return
        parsed = urllib.parse.urlparse(self.path)
        scrape_paths = {"/metrics": "metrics", URL_PREFIX + "/metrics": "metrics",
                        "/openapi": "openapi", URL_PREFIX + "/openapi": "openapi"}
        kind = scrape_paths.get(parsed.path) if method == "GET" else None
        ui = None
        if method == "GET" and kind is None:
            ui = self._ui_lookup(parsed.path)
        if kind is not None or ui is not None:
            # These surfaces sit outside the endpoint enum but NOT outside
            # security: operational state — and operator-configured disk
            # content — must not leak unauthenticated.
            from .security import AuthenticationError
            try:
                self.api.authenticate_readonly(dict(self.headers),
                                               self.client_address[0])
            except AuthenticationError as e:
                self._send(method, t0, 401, json.dumps(
                    {"errorMessage": str(e)}).encode(), "application/json",
                    {"WWW-Authenticate": self.api._security.challenge()})
                return
            if ui is not None:
                self._send(method, t0, 200, ui[0], ui[1])
            elif kind == "metrics":
                self._send(method, t0, 200, self.api.metrics_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            else:
                from .openapi import openapi_yaml
                self._send(method, t0, 200, openapi_yaml().encode(),
                           "application/yaml")
            return
        status, body, extra = self.api.handle(
            method, parsed.path, parsed.query, dict(self.headers),
            self.client_address[0])
        if isinstance(body, dict) and "__text__" in body:
            data = (body["__text__"] + "\n").encode()
            content_type = extra.pop("Content-Type",
                                     "text/plain; charset=utf-8")
        else:
            data = json.dumps(body, indent=2).encode()
            content_type = extra.pop("Content-Type", "application/json")
        self._send(method, t0, status, data, content_type, extra)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        self._serve("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._serve("POST")

    def log_message(self, fmt: str, *args) -> None:
        LOG.debug("http: " + fmt, *args)


def make_server(cc: CruiseControl, host: str | None = None,
                port: int | None = None,
                security_provider: SecurityProvider | None = None,
                fleet=None) -> tuple[ThreadingHTTPServer, CruiseControlApi]:
    cfg = cc.config
    api = CruiseControlApi(cc, security_provider, fleet=fleet)
    handler = type("BoundHandler", (_Handler,), {"api": api})
    server = ThreadingHTTPServer(
        (host or cfg.get("webserver.http.address"),
         port if port is not None else cfg.get_int("webserver.http.port")),
        handler)
    if cfg.get_boolean("webserver.ssl.enable"):
        # webserver.ssl.* (WebServerConfig): PEM cert+key via stdlib ssl.
        import ssl
        pem = cfg.get("webserver.ssl.keystore.location")
        if not pem:
            raise ValueError("webserver.ssl.enable requires "
                             "webserver.ssl.keystore.location (PEM file)")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        password = cfg.get("webserver.ssl.keystore.password")
        ctx.load_cert_chain(pem, password=str(password) if password else None)
        include = cfg.get_list("webserver.ssl.include.ciphers")
        if include:
            ctx.set_ciphers(":".join(include))
        server.socket = ctx.wrap_socket(server.socket, server_side=True)
    return server, api


def serve_forever_in_thread(server: ThreadingHTTPServer) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="cruise-control-http")
    t.start()
    return t
