"""Async user-task tracking.

Reference parity: servlet/UserTaskManager.java:69-138,222 — maps a client's
``User-Task-ID`` header (or a generated UUID) to an OperationFuture so
long-running operations can be polled; bounded active set, completed-task
retention PER ENDPOINT CLASS (monitor-type vs admin-type task caches,
UserTaskManager.java:69-138), typed OperationProgress surfaced mid-flight.
"""

from __future__ import annotations

import threading
import time
import uuid as uuid_mod
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable

from ..utils.progress import OperationProgress, set_current
from ..utils.sensors import SENSORS

USER_TASK_HEADER = "User-Task-ID"

# Endpoint-class split (UserTaskManager.TaskState caches): the reference
# keeps FOUR completed-task caches — Kafka-facing vs Cruise-Control-facing,
# each split monitor (read-only) vs admin (state-changing).
KAFKA_MONITOR = "KAFKA_MONITOR"
KAFKA_ADMIN = "KAFKA_ADMIN"
CC_MONITOR = "CC_MONITOR"
CC_ADMIN = "CC_ADMIN"

_ENDPOINT_CLASS = {
    "LOAD": KAFKA_MONITOR, "PARTITION_LOAD": KAFKA_MONITOR,
    "PROPOSALS": KAFKA_MONITOR, "KAFKA_CLUSTER_STATE": KAFKA_MONITOR,
    # COMPARE_FUTURES is read-only analysis of the Kafka cluster's
    # candidate futures (dry-run only, never executes).
    "COMPARE_FUTURES": KAFKA_MONITOR,
    "STATE": CC_MONITOR, "USER_TASKS": CC_MONITOR,
    "REVIEW_BOARD": CC_MONITOR, "PERMISSIONS": CC_MONITOR,
    "ADMIN": CC_ADMIN, "REVIEW": CC_ADMIN, "PAUSE_SAMPLING": CC_ADMIN,
    "RESUME_SAMPLING": CC_ADMIN, "BOOTSTRAP": CC_ADMIN, "TRAIN": CC_ADMIN,
    # STOP_PROPOSAL_EXECUTION and RIGHTSIZE act on the KAFKA cluster, not
    # on Cruise Control itself (CruiseControlEndPoint.java assigns both to
    # KAFKA_ADMIN) — they fall through to the KAFKA_ADMIN default below.
}


def task_class(endpoint: str) -> str:
    """Cluster-changing endpoints (rebalance, add/remove/demote broker,
    fix-offline, RF change, remove-disks) default to KAFKA_ADMIN."""
    return _ENDPOINT_CLASS.get(endpoint, KAFKA_ADMIN)


class TooManyUserTasksError(RuntimeError):
    """Maps to HTTP 429 (the reference's ServletException on exceeding
    max.active.user.tasks)."""


class TaskOwnershipError(RuntimeError):
    """Maps to HTTP 403: a User-Task-ID presented by a client other than
    the one that created the task (UserTaskManager.java session binding —
    task ids are capability tokens scoped to their creator)."""


@dataclass
class UserTaskInfo:
    task_id: str
    endpoint: str
    query: str
    start_ms: int
    future: Future
    client: str = ""
    status_override: str | None = None
    progress: OperationProgress | None = None
    # Round-20 serving engine lifecycle record (serving.tasks.EngineTask);
    # a COALESCED task shares its leader's record, like its future.
    engine_task: Any = None

    @property
    def status(self) -> str:
        if self.status_override:
            return self.status_override
        if not self.future.done():
            return "Active"
        if self.future.cancelled():
            return "Cancelled"
        return "CompletedWithError" if self.future.exception() else "Completed"

    @property
    def task_class(self) -> str:
        return task_class(self.endpoint)

    def to_dict(self) -> dict:
        out = {"UserTaskId": self.task_id,
               "RequestURL": f"{self.endpoint}?{self.query}",
               "Status": self.status, "StartMs": self.start_ms,
               "ClientIdentity": self.client}
        if self.engine_task is not None:
            # queued|running|done|failed|evicted — the engine's finer
            # lifecycle alongside the reference-shaped Status.
            out["TaskLifecycle"] = self.engine_task.lifecycle
            out["TaskClass"] = self.engine_task.klass.value
        if self.progress is not None:
            out["Progress"] = self.progress.to_list()
        return out


class UserTaskManager:
    def __init__(self, max_active_tasks: int = 25,
                 completed_retention_ms: int = 86_400_000,
                 num_threads: int = 8,
                 max_cached_completed_monitor_tasks: int = 20,
                 max_cached_completed_admin_tasks: int = 30,
                 max_cached_completed_tasks: int = 100,
                 max_cached_completed_cc_monitor_tasks: int | None = None,
                 max_cached_completed_cc_admin_tasks: int | None = None,
                 retention_ms_by_class: dict | None = None,
                 engine=None):
        """The monitor/admin caps apply to the Kafka-facing classes; the
        Cruise-Control-facing classes default to the same caps unless given
        their own (max.cached.completed.cruise.control.*.user.tasks).
        ``retention_ms_by_class`` overrides the default retention per task
        class (completed.<class>.user.task.retention.time.ms).
        ``engine`` (serving.tasks.AsyncTaskEngine, round 20) replaces the
        undifferentiated thread pool with bounded per-class queues; the
        202/User-Task-ID protocol, session binding, and retention caches
        are unchanged. An RLock because the coalescing index is cleared by
        future done-callbacks that may fire inline under the lock."""
        self._lock = threading.RLock()
        self._engine = engine
        # Cross-user coalescing (round 20): identical concurrent in-flight
        # requests (same cluster, endpoint, canonical params, generation,
        # goal chain) share ONE solve — key -> leader task id.
        self._inflight: dict[tuple, str] = {}
        self.coalesced = 0
        self._tasks: dict[str, UserTaskInfo] = {}
        self._max_active = max_active_tasks
        self._retention_ms = completed_retention_ms
        self._max_completed = {
            KAFKA_MONITOR: max_cached_completed_monitor_tasks,
            KAFKA_ADMIN: max_cached_completed_admin_tasks,
            CC_MONITOR: (max_cached_completed_cc_monitor_tasks
                         if max_cached_completed_cc_monitor_tasks is not None
                         else max_cached_completed_monitor_tasks),
            CC_ADMIN: (max_cached_completed_cc_admin_tasks
                       if max_cached_completed_cc_admin_tasks is not None
                       else max_cached_completed_admin_tasks),
        }
        self._retention_by_class = dict(retention_ms_by_class or {})
        self._max_completed_total = max_cached_completed_tasks
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="user-task")

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _drop_locked(self, tid: str) -> None:
        del self._tasks[tid]
        if self._engine is not None:
            # The engine record outlives the dropped RESULT: a late poll
            # of the id sees lifecycle "evicted" on GET /user_tasks.
            self._engine.evict(tid)

    def _expire_locked(self) -> None:
        now = int(time.time() * 1000)
        for tid in [t for t, info in self._tasks.items()
                    if info.future.done()
                    and now - info.start_ms > self._retention_by_class.get(
                        info.task_class, self._retention_ms)]:
            self._drop_locked(tid)
        # Per-endpoint-class completed caches: keep the newest N completed
        # tasks of each of the four classes (UserTaskManager.java:69-138).
        for cls, cap in self._max_completed.items():
            done = sorted((t for t in self._tasks.values()
                           if t.future.done() and t.task_class == cls),
                          key=lambda t: -t.start_ms)
            for info in done[cap:]:
                self._drop_locked(info.task_id)
        # Overall completed bound on top of the per-class caches
        # (max.cached.completed.user.tasks).
        done = sorted((t for t in self._tasks.values() if t.future.done()),
                      key=lambda t: -t.start_ms)
        for info in done[self._max_completed_total:]:
            self._drop_locked(info.task_id)

    def has_inflight(self, coalesce_key: tuple | None) -> bool:
        """True when an ACTIVE task already serves this coalescing key —
        the admission layer never sheds a request that would only attach
        to an existing solve."""
        if coalesce_key is None:
            return False
        with self._lock:
            tid = self._inflight.get(coalesce_key)
            info = self._tasks.get(tid) if tid else None
            return info is not None and not info.future.done()

    def get_or_create_task(self, endpoint: str, query: str,
                           work: Callable[[], Any],
                           task_id: str | None = None,
                           client: str = "",
                           coalesce_key: tuple | None = None,
                           ) -> UserTaskInfo:
        """Resume the task for a presented User-Task-ID, else submit a new
        one (UserTaskManager.getOrCreateUserTask:222). With a
        ``coalesce_key`` (round 20), an identical concurrent in-flight
        request ATTACHES instead: the caller gets its OWN session-bound
        task id whose future (and progress) IS the leader's — one solve,
        N pollable tasks, capability-token semantics intact (a shared id
        would 403 every non-leader's poll)."""
        with self._lock:
            self._expire_locked()
            if task_id and task_id in self._tasks:
                info = self._tasks[task_id]
                # Session binding (UserTaskManager.java:222 matches the
                # task against the requesting session): a client may only
                # resume ITS OWN task — presenting a guessed/leaked UUID
                # from a different identity must not expose another
                # client's operation result.
                if info.client != client:
                    raise TaskOwnershipError(
                        f"user task {task_id} belongs to a different "
                        f"client")
                return info
            if task_id:
                # Unknown/expired id presented: 400, NOT a new task under
                # the client-chosen id — otherwise another client could
                # squat an evicted id and 403 the legitimate owner's next
                # poll (the reference 400s invalid User-Task-IDs too).
                raise ValueError(
                    f"unknown or expired {USER_TASK_HEADER} {task_id}")
            if coalesce_key is not None:
                leader_id = self._inflight.get(coalesce_key)
                leader = self._tasks.get(leader_id) if leader_id else None
                if leader is not None and not leader.future.done():
                    # Attach BEFORE the max-active check: a join consumes
                    # no worker, no queue slot, no solver time.
                    tid = str(uuid_mod.uuid4())
                    info = UserTaskInfo(
                        task_id=tid, endpoint=endpoint, query=query,
                        start_ms=int(time.time() * 1000),
                        future=leader.future, client=client,
                        progress=leader.progress,
                        engine_task=leader.engine_task)
                    self._tasks[tid] = info
                    self.coalesced += 1
                    SENSORS.count("serving_coalesced_requests",
                                  labels={"endpoint": endpoint})
                    return info
            active = sum(1 for t in self._tasks.values() if not t.future.done())
            if active >= self._max_active:
                raise TooManyUserTasksError(
                    f"exceeded max active user tasks ({self._max_active})")
            tid = str(uuid_mod.uuid4())
            progress = OperationProgress(endpoint)

            def tracked():
                token = set_current(progress)
                try:
                    return work()
                finally:
                    progress.done()
                    token.var.reset(token)

            engine_task = None
            if self._engine is not None:
                future, engine_task = self._engine.submit(
                    endpoint, tracked, task_id=tid)
            else:
                future = self._pool.submit(tracked)
            info = UserTaskInfo(task_id=tid, endpoint=endpoint, query=query,
                                start_ms=int(time.time() * 1000),
                                future=future, client=client,
                                progress=progress, engine_task=engine_task)
            self._tasks[tid] = info
            if coalesce_key is not None:
                self._inflight[coalesce_key] = tid

                def _clear(_f, key=coalesce_key, leader=tid):
                    # RLock: may fire inline on this thread if the work
                    # completed synchronously (engine shutdown path).
                    with self._lock:
                        if self._inflight.get(key) == leader:
                            del self._inflight[key]

                future.add_done_callback(_clear)
            return info

    def task(self, task_id: str) -> UserTaskInfo | None:
        with self._lock:
            return self._tasks.get(task_id)

    def all_tasks(self) -> list[UserTaskInfo]:
        with self._lock:
            self._expire_locked()
            return sorted(self._tasks.values(), key=lambda t: -t.start_ms)
