"""Async user-task tracking.

Reference parity: servlet/UserTaskManager.java:69-138,222 — maps a client's
``User-Task-ID`` header (or a generated UUID) to an OperationFuture so
long-running operations can be polled; bounded active set, completed-task
retention, per-endpoint history for the USER_TASKS endpoint.
"""

from __future__ import annotations

import threading
import time
import uuid as uuid_mod
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

USER_TASK_HEADER = "User-Task-ID"


@dataclass
class UserTaskInfo:
    task_id: str
    endpoint: str
    query: str
    start_ms: int
    future: Future
    client: str = ""
    status_override: str | None = None

    @property
    def status(self) -> str:
        if self.status_override:
            return self.status_override
        if not self.future.done():
            return "Active"
        if self.future.cancelled():
            return "Cancelled"
        return "CompletedWithError" if self.future.exception() else "Completed"

    def to_dict(self) -> dict:
        return {"UserTaskId": self.task_id, "RequestURL": f"{self.endpoint}?{self.query}",
                "Status": self.status, "StartMs": self.start_ms,
                "ClientIdentity": self.client}


class UserTaskManager:
    def __init__(self, max_active_tasks: int = 25,
                 completed_retention_ms: int = 86_400_000,
                 num_threads: int = 8):
        self._lock = threading.Lock()
        self._tasks: dict[str, UserTaskInfo] = {}
        self._max_active = max_active_tasks
        self._retention_ms = completed_retention_ms
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="user-task")

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _expire_locked(self) -> None:
        now = int(time.time() * 1000)
        for tid in [t for t, info in self._tasks.items()
                    if info.future.done()
                    and now - info.start_ms > self._retention_ms]:
            del self._tasks[tid]

    def get_or_create_task(self, endpoint: str, query: str,
                           work: Callable[[], Any],
                           task_id: str | None = None,
                           client: str = "") -> UserTaskInfo:
        """Resume the task for a presented User-Task-ID, else submit a new
        one (UserTaskManager.getOrCreateUserTask:222)."""
        with self._lock:
            self._expire_locked()
            if task_id and task_id in self._tasks:
                return self._tasks[task_id]
            active = sum(1 for t in self._tasks.values() if not t.future.done())
            if active >= self._max_active:
                raise RuntimeError(
                    f"exceeded max active user tasks ({self._max_active})")
            tid = task_id or str(uuid_mod.uuid4())
            info = UserTaskInfo(task_id=tid, endpoint=endpoint, query=query,
                                start_ms=int(time.time() * 1000),
                                future=self._pool.submit(work), client=client)
            self._tasks[tid] = info
            return info

    def task(self, task_id: str) -> UserTaskInfo | None:
        with self._lock:
            return self._tasks.get(task_id)

    def all_tasks(self) -> list[UserTaskInfo]:
        with self._lock:
            self._expire_locked()
            return sorted(self._tasks.values(), key=lambda t: -t.start_ms)
