"""REST API layer (reference: servlet/ + vertx/ — 23 endpoints, async user
tasks, two-step review purgatory, pluggable security)."""

from .endpoints import EndPoint, Role, endpoint_for_path
from .purgatory import Purgatory, RequestInfo, ReviewStatus
from .security import (
    AuthenticationError, AuthorizationError, BasicSecurityProvider,
    JwtSecurityProvider, NoopSecurityProvider, Principal,
    PrincipalValidatorSecurityProvider, SecurityProvider,
    TrustedProxySecurityProvider, decode_jwt, encode_jwt,
)
from .server import CruiseControlApi, make_server, serve_forever_in_thread
from .user_tasks import USER_TASK_HEADER, UserTaskInfo, UserTaskManager

__all__ = [
    "EndPoint", "Role", "endpoint_for_path", "Purgatory", "RequestInfo",
    "ReviewStatus", "AuthenticationError", "AuthorizationError",
    "BasicSecurityProvider", "JwtSecurityProvider", "NoopSecurityProvider",
    "Principal", "PrincipalValidatorSecurityProvider", "SecurityProvider",
    "TrustedProxySecurityProvider", "decode_jwt", "encode_jwt",
    "CruiseControlApi", "make_server", "serve_forever_in_thread",
    "USER_TASK_HEADER", "UserTaskInfo", "UserTaskManager",
]
